"""Event-driven fleet simulation core: a single-threaded virtual-time
event heap replacing thread-per-pod at fleet scale.

The scripted harness (sim/harness.py + sim/scenario.py) runs every pod
as a full ``ModelMeshInstance`` on real threads — perfect fidelity,
but 3–4 pods is the practical ceiling: every virtual step costs a wall
yield so product threads run, and every pod carries its full task
stack. Fleet-scale questions (does burn-rate autoscaling hold p99
through a 1000-pod diurnal day? does admission starve the wrong class
when routing feedback lags?) need orders of magnitude more pods and
requests than threads can simulate.

This module is the fast path: ``EventLoop`` owns a ``VirtualClock``
and a heap of (due_ms, seq) events; ``ModeledInstance`` is a
lightweight state machine standing in for a pod (copy states with
load/unload latencies, bytes accounting with LRU eviction, a host
snapshot tier, an mm-load-style load_ewma estimate, per-class burn
windows); ``ModeledFleet`` reproduces the control planes on top —
power-of-d routing with load feedback, demand loading, legacy
rate-task/janitor scaling or burn-rate authority with forecaster
pre-warming (the REAL ``autoscale.forecast.DemandForecaster``, not a
model of it), and modeled per-class admission throttles. Every
constant is calibrated against the real stack's defaults (see
``FleetConfig`` field comments; docs/testing.md documents the fidelity
contract and tests/test_sim_engine.py pins modeled-vs-full parity).

Two drive modes share the loop:

* pure modeled (``step_ms=0``): the clock jumps event-to-event —
  nothing else waits on it, so a virtual day is just the cost of its
  events (the macro bench's hot loop; see ``EventLoop.run``).
* bridged (``step_ms>0``): bounded advances with a wall yield per
  step, exactly the historical ``ScenarioRunner`` drive loop — full-
  fidelity ``ModelMeshInstance`` threads woken by the same
  ``VirtualClock`` run between steps. ScenarioRunner now schedules its
  scripted events on an ``EventLoop`` and drives it in this mode, so
  existing scenarios run unchanged while sharing one core.

Determinism: the heap orders by (due_ms, seq); seq is assigned in
schedule order, and all scheduling is single-threaded, so a run is a
pure function of (config, seed). No wall time, no unseeded draws —
the macro replay gate (tests/test_bench_macro.py) asserts bit-for-bit
digest equality across runs.
"""

from __future__ import annotations

import dataclasses
import heapq
import math
import time as _wall
import zlib
from collections import deque
from typing import Callable, Optional

from modelmesh_tpu.autoscale.forecast import DemandForecaster
from modelmesh_tpu.observability.slo import SloObjectives, parse_slo_spec
from modelmesh_tpu.utils import clock as _clock

__all__ = [
    "EventLoop",
    "FleetConfig",
    "ModeledInstance",
    "ModeledFleet",
    "RouteResult",
]


class _Ev:
    """One scheduled callback. ``args`` is a plain tuple (re-used, never
    copied) and cancellation is a flag flip, so the hot loop allocates
    nothing beyond the heap entry itself."""

    __slots__ = ("due_ms", "seq", "fn", "args", "cancelled")

    def __init__(self, due_ms: int, seq: int, fn: Callable, args: tuple):
        self.due_ms = due_ms
        self.seq = seq
        self.fn = fn
        self.args = args
        self.cancelled = False

    def __lt__(self, other: "_Ev") -> bool:
        return (self.due_ms, self.seq) < (other.due_ms, other.seq)


class EventLoop:
    """Virtual-time discrete-event loop over an injectable clock.

    Single-threaded: ``schedule_*`` and ``run`` must be called from the
    driving thread. Handlers read ``loop.now_ms`` (== clock.now_ms())
    instead of touching the clock — one seam, one reader.
    """

    def __init__(self, clock: Optional[_clock.VirtualClock] = None):
        self.clock = clock if clock is not None else _clock.VirtualClock()
        self._heap: list[_Ev] = []
        #: shared-ok: single-threaded EventLoop state — ticks, routing, and faults all run on the loop thread
        self._seq = 0
        #: shared-ok: single-threaded EventLoop state — ticks, routing, and faults all run on the loop thread
        self.now_ms: int = self.clock.now_ms()
        #: shared-ok: single-threaded EventLoop state — ticks, routing, and faults all run on the loop thread
        self.events_processed = 0

    # -- scheduling --------------------------------------------------------

    def schedule_at(self, due_ms: int, fn: Callable, *args) -> _Ev:
        ev = _Ev(int(due_ms), self._seq, fn, args)
        self._seq += 1
        heapq.heappush(self._heap, ev)
        return ev

    def schedule_in(self, delay_ms: float, fn: Callable, *args) -> _Ev:
        return self.schedule_at(self.now_ms + int(delay_ms), fn, *args)

    @staticmethod
    def cancel(ev: _Ev) -> None:
        ev.cancelled = True

    def pending(self) -> int:
        return sum(1 for e in self._heap if not e.cancelled)

    # -- driving -----------------------------------------------------------

    def run(
        self,
        until_ms: int,
        step_ms: int = 0,
        yield_s: float = 0.0,
    ) -> None:
        """Advance virtual time to ``until_ms``, firing every event due
        on the way (due <= until_ms fires; the clock lands exactly on
        ``until_ms``).

        ``step_ms=0``: pure modeled mode — the clock jumps straight to
        each event's due time and lands exactly on ``until_ms``.
        ``step_ms>0``: bridged mode — every advance is a FULL step
        followed by a real ``yield_s`` sleep so threads blocked on this
        VirtualClock (full-fidelity pods' timers, keepalives, watchers)
        get to run. Bridged semantics are the historical ScenarioRunner
        drive loop, bit-for-bit: events fire when a step lands at/past
        their due time (observed timestamps quantize onto the step
        grid) and the clock overshoots ``until_ms`` by up to one step.
        """
        heap = self._heap
        clock = self.clock
        until_ms = int(until_ms)
        while True:
            # Drop cancelled heads, then fire everything already due.
            # (Re-reading the clock per fire keeps now_ms honest when a
            # handler advances the clock itself — scenario clock_jump.)
            while heap and (heap[0].cancelled or heap[0].due_ms <= self.now_ms):
                ev = heapq.heappop(heap)
                if ev.cancelled:
                    continue
                if ev.due_ms > until_ms:
                    heapq.heappush(heap, ev)
                    break
                self.events_processed += 1
                ev.fn(*ev.args)
                self.now_ms = clock.now_ms()
            now = self.now_ms
            if now >= until_ms and not (
                heap and not heap[0].cancelled and heap[0].due_ms <= until_ms
            ):
                break
            if step_ms > 0:
                delta = step_ms
            else:
                next_due = until_ms
                if heap and not heap[0].cancelled and heap[0].due_ms < next_due:
                    next_due = heap[0].due_ms
                delta = max(next_due - now, 0)
            if delta > 0:
                clock.advance(delta)
                self.now_ms = clock.now_ms()
            if step_ms > 0 and yield_s > 0:
                _wall.sleep(yield_s)  #: wall-clock: yields the advancing thread so bridged full-fidelity threads run between virtual steps

    def drain(self) -> None:
        """Fire every remaining event immediately at the current virtual
        time (ScenarioRunner's 'leftover events past the horizon fire
        anyway' semantics)."""
        heap = self._heap
        while heap:
            ev = heapq.heappop(heap)
            if not ev.cancelled:
                self.events_processed += 1
                ev.fn(*ev.args)


# ---------------------------------------------------------------------------
# Modeled fleet
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Calibration constants for the modeled fleet. Every default is
    pinned to the real stack's default (source in the field comment);
    tests/test_sim_engine.py::test_parity_* gate drift."""

    # -- data plane (SimCluster congestion model, sim/harness.py) ----------
    service_base_ms: float = 2.0
    service_congestion_ms: float = 1.0
    service_congestion_cap: int = 64
    # -- copy lifecycle (SimLoader defaults + PR-6/PR-15 measurements) -----
    load_delay_ms: float = 50.0       # SimLoader load_delay_ms default
    unload_delay_ms: float = 5.0      # SimLoader unload_delay_ms default
    peer_stream_frac: float = 0.2     # peer weight stream ≈ 0.2x store load
    host_rewarm_frac: float = 0.11    # host-tier re-warm 9ms vs 82ms cold
    default_size_bytes: int = 1       # SimLoader crc32 sizing base
    capacity_bytes: int = 64          # modeled accelerator cache units
    host_budget_bytes: int = 128      # modeled host snapshot tier
    load_timeout_ms: int = 30_000     # cold wait bound before a request fails
    # -- routing (routing defaults: MM_ROUTE_D, mm-load feedback) ----------
    route_d: int = 2                  # power-of-d candidate set size
    # -- authority (serving/tasks.py + autoscale/controller.py defaults) ---
    authority: str = "legacy"         # "legacy" | "burn" | "off"
    scale_up_rpm: float = 2000.0      # DEFAULT_SCALE_UP_RPM per copy
    max_copies: int = 8               # DEFAULT_MAX_COPIES
    rate_interval_s: float = 10.0     # TaskConfig.rate_interval_s
    janitor_interval_s: float = 360.0  # TaskConfig.janitor_interval_s
    second_copy_max_age_s: float = 600.0  # janitor surplus-copy age cap
    autoscale_interval_s: float = 10.0    # AutoscaleConfig.interval_s
    burn_up: float = 0.5              # MM_AUTOSCALE_BURN_UP
    burn_flash: float = 2.0           # flash threshold: copy doubling
    burn_down: float = 0.25           # MM_AUTOSCALE_BURN_DOWN
    idle_ticks_down: int = 3          # calm ticks before scale-down
    min_burn_samples: int = 5         # window floor before burn is trusted
    max_models_per_tick: int = 4      # AutoscaleConfig.max_models_per_tick
    holddown_ms: int = 5_000          # MM_AUTOSCALE_HOLDDOWN_MS
    window_ms: int = 10_000           # SloTracker window
    prewarm: bool = True              # forecaster-driven host pre-warming
    # -- SLO / admission (observability/slo.py, routing/admission.py) ------
    slo_spec: str = "default:p99<250ms"
    admission: bool = False
    admission_floor: float = 0.01     # lowest admitted fraction per class


def model_size_bytes(model_id: str, default: int = 1) -> int:
    """SimLoader._size_for's sizing, bit-for-bit: crc32-hashed spread in
    [0.5x, 1.5x) of the default size."""
    h = zlib.crc32(model_id.encode()) % 1000
    return max(1, int(default * (0.5 + h / 1000.0)))


class _Copy:
    """One placement on one instance. States: 'loading' (bytes reserved,
    not servable), 'active' (servable), 'host' (host snapshot only —
    cheap to re-warm, not servable)."""

    __slots__ = ("phase", "ready_ms", "size", "last_used_ms", "source")

    def __init__(self, phase: str, ready_ms: int, size: int,
                 now_ms: int, source: str):
        self.phase = phase
        self.ready_ms = ready_ms
        self.size = size
        self.last_used_ms = now_ms
        self.source = source  # "store" | "peer" | "host"


class _BurnWindow:
    """Windowed per-class good/total aggregate — the SloTracker burn
    computation (burn = (1-good)/budget over the trailing window)
    applied to slot-level aggregates instead of per-request records."""

    __slots__ = ("buf", "bad", "total")

    def __init__(self):
        self.buf: deque = deque()  # (ts_ms, bad, total)
        self.bad = 0
        self.total = 0

    def observe(self, ts_ms: int, bad: int, total: int) -> None:
        self.buf.append((ts_ms, bad, total))
        self.bad += bad
        self.total += total

    def prune(self, cutoff_ms: int) -> None:
        buf = self.buf
        while buf and buf[0][0] < cutoff_ms:
            _, b, t = buf.popleft()
            self.bad -= b
            self.total -= t

    def burn(self, now_ms: int, window_ms: int, good_target: float,
             min_samples: int) -> Optional[float]:
        """SloTracker's burn: (1 - good_fraction) / error_budget.
        None when the window holds too few samples to judge (the
        controller's min_burn_samples gate)."""
        self.prune(now_ms - window_ms)
        if self.total < min_samples:
            return None
        good = 1.0 - (self.bad / self.total)
        budget = 1.0 - good_target
        if budget <= 0.0:
            return 0.0 if good >= 1.0 else math.inf
        return (1.0 - good) / budget


class ModeledInstance:
    """Lightweight pod stand-in: copy map + bytes accounting + load_ewma
    estimate + per-class burn windows. All mutation happens on the
    EventLoop thread — no locks."""

    __slots__ = (
        "iid", "capacity_bytes", "host_budget", "copies", "host_used",
        "used_bytes", "load_ewma", "slot_load", "served", "alive",
        "partitioned", "burn",
    )

    def __init__(self, iid: str, capacity_bytes: int, host_budget: int):
        self.iid = iid
        self.capacity_bytes = capacity_bytes
        self.host_budget = host_budget
        self.copies: dict[str, _Copy] = {}
        self.host_used = 0
        self.used_bytes = 0       # active + loading bytes
        self.load_ewma = 0.0       # mm-load analog: smoothed concurrency
        self.slot_load = 0.0      # concurrency accumulated this slot
        self.served = 0
        self.alive = True
        self.partitioned = False
        self.burn: dict[str, _BurnWindow] = {}

    @property
    def routable(self) -> bool:
        return self.alive and not self.partitioned

    def servable(self, mid: str) -> bool:
        c = self.copies.get(mid)
        return c is not None and c.phase == "active"

    def observe_class(self, cls: str, ts_ms: int, bad: int, total: int) -> None:
        w = self.burn.get(cls)
        if w is None:
            w = self.burn[cls] = _BurnWindow()
        w.observe(ts_ms, bad, total)

    def burn_rate(self, cls: str, now_ms: int, window_ms: int,
                  good_target: float, min_samples: int) -> Optional[float]:
        w = self.burn.get(cls)
        if w is None:
            return None
        return w.burn(now_ms, window_ms, good_target, min_samples)

    def lru_evictable(self, keep: str) -> list[str]:
        """Active copies other than ``keep``, LRU-first."""
        items = [
            (c.last_used_ms, mid) for mid, c in self.copies.items()
            if c.phase == "active" and mid != keep
        ]
        items.sort()
        return [mid for _, mid in items]


class _ModelState:
    __slots__ = (
        "mid", "cls", "size", "holders", "rpm", "last_used_ms",
        "holddown_until_ms", "registered_ms",
    )

    def __init__(self, mid: str, cls: str, size: int, now_ms: int):
        self.mid = mid
        self.cls = cls
        self.size = size
        self.holders: dict[str, int] = {}  # iid -> copy birth ts (insertion order)
        self.rpm = 0.0
        self.last_used_ms = now_ms
        self.holddown_until_ms = 0
        self.registered_ms = now_ms


class RouteResult:
    """Aggregate outcome of routing one (model, slot) flow: latency
    buckets as (latency_ms, count) pairs plus shed/failed counts."""

    __slots__ = ("served", "shed", "failed", "lat")

    def __init__(self):
        self.served = 0
        self.shed = 0
        self.failed = 0
        self.lat: list[tuple[float, int]] = []


class ModeledFleet:
    """The crowd: N ModeledInstances plus modeled routing, demand
    loading, autoscale authority, and admission — all calibrated against
    the real control planes (FleetConfig field comments name sources).

    The workload generator calls ``route_slot(mid, n, slot_ms)`` once
    per (model, slot) with an aggregate request count; everything else
    (control cadences, copy-ready flips, fault overlays) rides the
    EventLoop.
    """

    def __init__(self, loop: EventLoop, n: int,
                 config: Optional[FleetConfig] = None, seed: int = 0):
        self.loop = loop
        self.cfg = config or FleetConfig()
        self.seed = seed
        self.instances: list[ModeledInstance] = [
            ModeledInstance(
                f"pod-{i}", self.cfg.capacity_bytes, self.cfg.host_budget_bytes
            )
            for i in range(n)
        ]
        self.models: dict[str, _ModelState] = {}
        self.slo = parse_slo_spec(self.cfg.slo_spec)
        # Admission throttle per class: admitted fraction in (floor, 1].
        # Clause order in the spec is priority order; the first clause
        # is never shed (routing/admission.py semantics).
        self._slo_order = list(self.slo)
        #: shared-ok: single-threaded EventLoop state — ticks, routing, and faults all run on the loop thread
        self.throttle: dict[str, float] = {c: 1.0 for c in self.slo}
        self.forecaster = DemandForecaster() if self.cfg.prewarm else None
        #: shared-ok: single-threaded EventLoop state — ticks, routing, and faults all run on the loop thread
        self._calm_ticks: dict[str, int] = {}
        # Scale/churn observability for invariants and the bench tail.
        #: shared-ok: single-threaded EventLoop state — ticks, routing, and faults all run on the loop thread
        self.counters = {
            "scale_up": 0, "scale_down": 0, "loads_store": 0,
            "loads_peer": 0, "loads_host": 0, "evictions": 0,
            "sheds": 0, "cold_fails": 0, "prewarms": 0,
        }
        self._start_ticks()

    # -- setup -------------------------------------------------------------

    def _start_ticks(self) -> None:
        cfg = self.cfg
        if cfg.authority == "legacy":
            self.loop.schedule_in(
                cfg.rate_interval_s * 1000.0, self._rate_tick
            )
            self.loop.schedule_in(
                cfg.janitor_interval_s * 1000.0, self._janitor_tick
            )
        elif cfg.authority == "burn":
            self.loop.schedule_in(
                cfg.autoscale_interval_s * 1000.0, self._burn_tick
            )
        if cfg.admission:
            # Throttle refresh shares the autoscale cadence floor; the
            # real refresher runs at 250ms but the modeled slot grid is
            # coarser, so per-slot pressure updates happen in
            # _refresh_admission called from route feedback instead.
            self.loop.schedule_in(1_000.0, self._admission_tick)

    def class_of(self, mid: str) -> str:
        ms = self.models.get(mid)
        return ms.cls if ms is not None else "default"

    def objectives(self, cls: str) -> Optional[SloObjectives]:
        return self.slo.get(cls) or self.slo.get("default")

    def register(self, mid: str, cls: str = "default") -> None:
        if mid not in self.models:
            self.models[mid] = _ModelState(
                mid, cls, model_size_bytes(mid, self.cfg.default_size_bytes),
                self.loop.now_ms,
            )

    def unregister(self, mid: str) -> None:
        ms = self.models.pop(mid, None)
        if ms is None:
            return
        for iid in list(ms.holders):
            self._drop_copy(ms, iid, to_host=False)

    # -- copy lifecycle ----------------------------------------------------

    def _inst(self, iid: str) -> ModeledInstance:
        return self.instances[int(iid.rsplit("-", 1)[1])]

    def _load_latency_ms(self, inst: ModeledInstance, ms: _ModelState,
                         have_peer: bool) -> tuple[float, str]:
        cfg = self.cfg
        base = cfg.load_delay_ms * (ms.size / max(cfg.default_size_bytes, 1))
        c = inst.copies.get(ms.mid)
        if c is not None and c.phase == "host":
            return base * cfg.host_rewarm_frac, "host"
        if have_peer:
            return base * cfg.peer_stream_frac, "peer"
        return base, "store"

    def _evict_for(self, inst: ModeledInstance, need: int, keep: str) -> bool:
        """Free ``need`` bytes via LRU eviction (evicted actives demote
        to host snapshots when the host tier has room — the real cache's
        second-chance tier). False when impossible."""
        if inst.capacity_bytes - inst.used_bytes >= need:
            return True
        for mid in inst.lru_evictable(keep):
            self._drop_copy(self.models[mid], inst.iid, to_host=True)
            self.counters["evictions"] += 1
            if inst.capacity_bytes - inst.used_bytes >= need:
                return True
        return inst.capacity_bytes - inst.used_bytes >= need

    def _drop_copy(self, ms: _ModelState, iid: str, to_host: bool) -> None:
        inst = self._inst(iid)
        c = inst.copies.get(ms.mid)
        if c is None:
            return
        if c.phase in ("active", "loading"):
            inst.used_bytes -= c.size
            ms.holders.pop(iid, None)
        if c.phase == "host":
            inst.host_used -= c.size
            del inst.copies[ms.mid]
            return
        if to_host and inst.host_used + c.size <= inst.host_budget:
            c.phase = "host"
            inst.host_used += c.size
        else:
            del inst.copies[ms.mid]

    def add_copy(self, mid: str, iid: Optional[str] = None) -> bool:
        """Start loading one more copy (place on the least-loaded fitting
        routable instance when ``iid`` is None). Returns False when no
        instance can take it."""
        ms = self.models.get(mid)
        if ms is None:
            return False
        inst = self._inst(iid) if iid else self._pick_target(ms)
        if inst is None or not inst.routable or mid in ms.holders:
            return False
        if not self._evict_for(inst, ms.size, keep=mid):
            return False
        have_peer = any(
            self._inst(h).servable(mid) for h in ms.holders
        )
        lat, source = self._load_latency_ms(inst, ms, have_peer)
        now = self.loop.now_ms
        prior = inst.copies.get(mid)
        if prior is not None and prior.phase == "host":
            inst.host_used -= prior.size
        ready = now + int(lat)
        inst.copies[mid] = _Copy("loading", ready, ms.size, now, source)
        inst.used_bytes += ms.size
        ms.holders[inst.iid] = now
        self.counters["loads_" + source] += 1
        self.loop.schedule_at(ready, self._copy_ready, inst, mid)
        return True

    def _copy_ready(self, inst: ModeledInstance, mid: str) -> None:
        c = inst.copies.get(mid)
        if c is not None and c.phase == "loading" and inst.alive:
            c.phase = "active"

    def _pick_target(self, ms: _ModelState) -> Optional[ModeledInstance]:
        best, best_key = None, None
        for inst in self.instances:
            if not inst.routable or inst.iid in ms.holders:
                continue
            # Least-loaded by load_ewma, then most free bytes; index
            # breaks ties deterministically.
            key = (
                inst.load_ewma + inst.slot_load,
                inst.used_bytes / max(inst.capacity_bytes, 1),
            )
            if best_key is None or key < best_key:
                best, best_key = inst, key
        return best

    # -- data plane --------------------------------------------------------

    def route_slot(self, mid: str, n: int, slot_ms: int) -> RouteResult:
        """Route ``n`` requests arriving for ``mid`` uniformly over one
        slot. Returns aggregate latency buckets; feeds mm-load, burn
        windows (via the caller's observe step), rpm, and demand loads.
        """
        res = RouteResult()
        if n <= 0:
            return res
        ms = self.models.get(mid)
        now = self.loop.now_ms
        if ms is None:
            res.failed = n
            return res
        cfg = self.cfg
        ms.last_used_ms = now
        # EWMA demand rate (per-minute), tau ~= 3 slots.
        inst_rate = n * 60_000.0 / max(slot_ms, 1)
        alpha = 1.0 - math.exp(-1.0 / 3.0)
        ms.rpm += alpha * (inst_rate - ms.rpm)
        # Admission: classes under throttle shed a deterministic
        # fraction at the door (rounded half-up so tiny flows still
        # shed under full throttle).
        if cfg.admission:
            frac = self.throttle.get(ms.cls, 1.0)
            if frac < 1.0:
                shed = n - int(n * frac)
                if shed > 0:
                    # Sheds carry NO latency sample: rejected at the
                    # door, they never reach the runtime — they count
                    # against availability (slo_attained), not the
                    # served-latency distribution.
                    res.shed = shed
                    self.counters["sheds"] += shed
                    n -= shed
                if n <= 0:
                    return res
        holders = [
            self._inst(h) for h in ms.holders
            if self._inst(h).routable and self._inst(h).servable(mid)
        ]
        if not holders:
            return self._route_cold(ms, n, res)
        self._route_warm(ms, holders, n, slot_ms, res)
        return res

    def _route_cold(self, ms: _ModelState, n: int, res: RouteResult) -> RouteResult:
        """No active copy: requests wait on the (possibly just-started)
        load; beyond the timeout they fail — the real path's bounded
        cold-start wait."""
        cfg = self.cfg
        now = self.loop.now_ms
        loading = [
            self._inst(h) for h in ms.holders
            if self._inst(h).routable
            and self._inst(h).copies.get(ms.mid) is not None
            and self._inst(h).copies[ms.mid].phase == "loading"
        ]
        if not loading:
            if not self.add_copy(ms.mid):
                res.failed = n
                self.counters["cold_fails"] += n
                return res
            loading = [
                self._inst(h) for h in ms.holders
                if self._inst(h).copies.get(ms.mid) is not None
                and self._inst(h).copies[ms.mid].phase == "loading"
            ]
            if not loading:
                res.failed = n
                self.counters["cold_fails"] += n
                return res
        ready = min(i.copies[ms.mid].ready_ms for i in loading)
        wait = max(ready - now, 0)
        if wait > cfg.load_timeout_ms:
            res.failed = n
            self.counters["cold_fails"] += n
            return res
        lat = wait + cfg.service_base_ms
        res.served = n
        res.lat.append((lat, n))
        inst = loading[0]
        inst.served += n
        return res

    def _route_warm(self, ms: _ModelState, holders: list[ModeledInstance],
                    n: int, slot_ms: int, res: RouteResult) -> None:
        """Power-of-d over active holders, as a flow: the d-candidate
        least-loaded choice spreads the slot's n requests across holders
        in proportion to available headroom (water-filling on the
        load_ewma estimate), with a 1/(2d) uniform leak modeling the
        imperfection of sampling d candidates instead of all. d<=1 is
        the legacy single-winner greedy (herding preserved on purpose).
        """
        cfg = self.cfg
        now = self.loop.now_ms
        svc_frac = cfg.service_base_ms / max(slot_ms, 1)
        for h in holders:
            h.copies[ms.mid].last_used_ms = now
        if len(holders) == 1 or cfg.route_d <= 1:
            holders.sort(key=lambda h: (h.load_ewma + h.slot_load, h.iid))
            shares = [(holders[0], n)]
        else:
            leak = 1.0 / (2.0 * cfg.route_d)
            uniform = n * leak / len(holders)
            fill_n = n - uniform * len(holders)
            shares_f = self._water_fill(holders, fill_n, svc_frac)
            shares = []
            rem = n
            for h, f in shares_f[:-1]:
                k = max(0, min(int(round(f + uniform)), rem))
                shares.append((h, k))
                rem -= k
            shares.append((shares_f[-1][0], rem))
        for inst, k in shares:
            if k <= 0:
                continue
            # Concurrency an arriving request sees: the smoothed prior
            # load (mm-load feedback) plus everything already routed to
            # this instance THIS slot (other models share the pod), plus
            # this flow's own contribution. end_slot() folds slot_load
            # into the smoothed estimate.
            inst.slot_load += k * svc_frac
            conc = inst.load_ewma + inst.slot_load
            queued = max(conc - 1.0, 0.0)
            if cfg.service_congestion_cap > 0:
                queued = min(queued, float(cfg.service_congestion_cap))
            lat = cfg.service_base_ms + cfg.service_congestion_ms * queued
            res.lat.append((lat, k))
            res.served += k
            inst.served += k

    @staticmethod
    def _water_fill(holders: list[ModeledInstance], n: float,
                    svc_frac: float) -> list[tuple[ModeledInstance, float]]:
        """Distribute n requests so post-assignment load equalizes
        (perfect least-loaded flow assignment)."""
        hs = sorted(holders, key=lambda h: (h.load_ewma + h.slot_load, h.iid))
        w = max(svc_frac, 1e-9)
        total = n
        # Find the water level L: sum(max(0, L - s_i)) / w = n.
        levels = [h.load_ewma + h.slot_load for h in hs]
        assigned = [0.0] * len(hs)
        k = len(hs)
        # Raise level band by band.
        need = total * w
        for i in range(1, k + 1):
            band_top = levels[i] if i < k else math.inf
            band_cap = (band_top - levels[i - 1]) * i
            if band_cap >= need or i == k:
                level = levels[i - 1] + need / i
                for j in range(i):
                    assigned[j] = (level - levels[j]) / w
                break
            need -= band_cap
        return list(zip(hs, assigned))

    def end_slot(self) -> None:
        """Fold this slot's accumulated load into the smoothed load_ewma
        estimate (the mm-load feedback the NEXT slot's routing sees) and
        reset the accumulator. The workload generator calls this once
        per slot after routing every model's flow."""
        for inst in self.instances:
            inst.load_ewma += 0.5 * (inst.slot_load - inst.load_ewma)
            inst.slot_load = 0.0

    # -- burn observation (called by the workload per slot) ----------------

    def observe_slot(self, cls: str, ts_ms: int, bad: int, total: int) -> None:
        """Distribute a slot's per-class (bad, total) aggregate across
        entry instances — each alive instance sees ~1/n of the traffic,
        so the leader's window carries a leader-local sample exactly as
        in production (the PR-15 blind spot is reproduced, not papered
        over: its sample COUNT gates min_burn_samples realistically)."""
        live = [i for i in self.instances if i.alive]
        if not live:
            return
        n = len(live)
        b_share, b_extra = divmod(bad, n)
        t_share, t_extra = divmod(total, n)
        for idx, inst in enumerate(live):
            inst.observe_class(
                cls, ts_ms,
                b_share + (1 if idx < b_extra else 0),
                t_share + (1 if idx < t_extra else 0),
            )

    def _leader(self) -> Optional[ModeledInstance]:
        for inst in self.instances:
            if inst.alive:
                return inst
        return None

    # -- authority: legacy rate task + janitor -----------------------------

    def _rate_tick(self) -> None:
        cfg = self.cfg
        live = sum(1 for i in self.instances if i.alive)
        for mid in sorted(self.models):
            ms = self.models[mid]
            copies = len(ms.holders)
            if copies == 0:
                continue
            if ms.rpm > cfg.scale_up_rpm * copies and copies < min(
                cfg.max_copies, live
            ):
                if self.add_copy(mid):
                    self.counters["scale_up"] += 1
        self.loop.schedule_in(cfg.rate_interval_s * 1000.0, self._rate_tick)

    def _janitor_tick(self) -> None:
        """Cluster-full surplus shedding + aged second copies — the
        legacy janitor's scale-down half."""
        cfg = self.cfg
        now = self.loop.now_ms
        used = sum(i.used_bytes for i in self.instances if i.alive)
        cap = sum(i.capacity_bytes for i in self.instances if i.alive)
        full = cap > 0 and used / cap > 0.9
        for mid in sorted(self.models):
            ms = self.models[mid]
            if len(ms.holders) < 2:
                continue
            surplus_ok = ms.rpm < cfg.scale_up_rpm * (len(ms.holders) - 1)
            newest_iid = max(ms.holders, key=lambda h: (ms.holders[h], h))
            aged = now - ms.holders[newest_iid] > cfg.second_copy_max_age_s * 1000
            if (full and surplus_ok) or (aged and surplus_ok):
                self._drop_copy(ms, newest_iid, to_host=True)
                self.counters["scale_down"] += 1
        self.loop.schedule_in(
            cfg.janitor_interval_s * 1000.0, self._janitor_tick
        )

    # -- authority: burn-rate controller -----------------------------------

    def _burn_tick(self) -> None:
        cfg = self.cfg
        now = self.loop.now_ms
        leader = self._leader()
        if leader is None:
            self.loop.schedule_in(
                cfg.autoscale_interval_s * 1000.0, self._burn_tick
            )
            return
        live = sum(1 for i in self.instances if i.alive)
        if self.forecaster is not None:
            for mid in sorted(self.models):
                ms = self.models[mid]
                if ms.rpm > 0:
                    self.forecaster.observe(mid, ms.rpm, now)
        for cls in sorted(leader.burn):
            obj = self.objectives(cls)
            if obj is None:
                continue
            burn = leader.burn_rate(
                cls, now, cfg.window_ms, obj.good_target, cfg.min_burn_samples
            )
            if burn is None:
                continue
            if burn >= cfg.burn_up:
                self._calm_ticks[cls] = 0
                flash = burn >= cfg.burn_flash
                ceiling = min(cfg.max_copies, live)
                # Hottest models that can still GAIN a copy: once the
                # top of the class saturates, pressure walks down the
                # popularity list instead of stalling on maxed models.
                hot = sorted(
                    (m for m in self.models.values()
                     if m.cls == cls and m.holders
                     and len(m.holders) < ceiling),
                    key=lambda m: (-m.rpm, m.mid),
                )[: cfg.max_models_per_tick]
                for ms in hot:
                    if now < ms.holddown_until_ms:
                        continue
                    copies = len(ms.holders)
                    want = min(copies * 2 if flash else copies + 1,
                               cfg.max_copies, live)
                    added = False
                    for _ in range(want - copies):
                        if self.add_copy(ms.mid):
                            added = True
                            self.counters["scale_up"] += 1
                    if added:
                        ms.holddown_until_ms = now + cfg.holddown_ms
            elif burn <= cfg.burn_down:
                calm = self._calm_ticks.get(cls, 0) + 1
                self._calm_ticks[cls] = calm
                if calm >= cfg.idle_ticks_down:
                    self._scale_down_class(cls, now)
                    self._calm_ticks[cls] = 0
            else:
                self._calm_ticks[cls] = 0
        if self.forecaster is not None:
            self._prewarm(now)
        self.loop.schedule_in(
            cfg.autoscale_interval_s * 1000.0, self._burn_tick
        )

    def _scale_down_class(self, cls: str, now: int) -> None:
        cfg = self.cfg
        for mid in sorted(self.models):
            ms = self.models[mid]
            if ms.cls != cls or len(ms.holders) < 2:
                continue
            if now < ms.holddown_until_ms:
                continue
            newest = max(ms.holders, key=lambda h: (ms.holders[h], h))
            self._drop_copy(ms, newest, to_host=True)
            self.counters["scale_down"] += 1
            ms.holddown_until_ms = now + cfg.holddown_ms

    def _prewarm(self, now: int) -> None:
        """Stage host snapshots for trending models on instances that
        do not hold them — the PR-15 predictive pre-warm: when demand
        arrives, the load is a cheap host re-warm instead of a cold
        store pull."""
        assert self.forecaster is not None
        for mid in self.forecaster.trending(now_ms=now):
            ms = self.models.get(mid)
            if ms is None:
                continue
            staged = 0
            for inst in self.instances:
                if staged >= 1:
                    break
                if not inst.routable or ms.mid in inst.copies:
                    continue
                if inst.host_used + ms.size > inst.host_budget:
                    continue
                inst.copies[ms.mid] = _Copy(
                    "host", now, ms.size, now, "host"
                )
                inst.host_used += ms.size
                self.counters["prewarms"] += 1
                staged += 1

    # -- admission ---------------------------------------------------------

    def _admission_tick(self) -> None:
        """Per-class throttle refresh: when any class at-or-above a
        class's priority burns >= 1x on the leader's window, classes
        below halve their admitted fraction (multiplicative recovery
        when pressure lifts); the first clause is never shed —
        routing/admission.py's bucket semantics on the slot grid."""
        cfg = self.cfg
        now = self.loop.now_ms
        leader = self._leader()
        if leader is not None:
            burning_at: Optional[int] = None
            for pri, cls in enumerate(self._slo_order):
                obj = self.objectives(cls)
                if obj is None:
                    continue
                burn = leader.burn_rate(
                    cls, now, cfg.window_ms, obj.good_target,
                    cfg.min_burn_samples,
                )
                if burn is not None and burn >= 1.0:
                    burning_at = pri
                    break  # highest burning priority wins
            for pri, cls in enumerate(self._slo_order):
                if pri == 0:
                    self.throttle[cls] = 1.0  # first clause never shed
                    continue
                if burning_at is not None and pri >= burning_at:
                    self.throttle[cls] = max(
                        self.throttle[cls] * 0.5, cfg.admission_floor
                    )
                else:
                    self.throttle[cls] = min(self.throttle[cls] * 2.0, 1.0)
        self.loop.schedule_in(1_000.0, self._admission_tick)

    # -- fault overlays ----------------------------------------------------

    def kill(self, iid: str) -> None:
        inst = self._inst(iid)
        inst.alive = False
        inst.load_ewma = 0.0
        inst.slot_load = 0.0
        for mid in list(inst.copies):
            ms = self.models.get(mid)
            if ms is not None:
                ms.holders.pop(iid, None)
        inst.copies.clear()
        inst.used_bytes = 0
        inst.host_used = 0

    def partition(self, iid: str) -> None:
        self._inst(iid).partitioned = True

    def heal(self, iid: str) -> None:
        self._inst(iid).partitioned = False

    # -- invariant-facing --------------------------------------------------

    def total_copies(self) -> int:
        return sum(len(m.holders) for m in self.models.values())

    def bytes_conservation_violations(self) -> list[str]:
        """used_bytes must equal the sum of active+loading copy sizes
        and never exceed capacity — the modeled twin of the cache
        accounting invariant."""
        out = []
        for inst in self.instances:
            acc = sum(
                c.size for c in inst.copies.values()
                if c.phase in ("active", "loading")
            )
            if acc != inst.used_bytes:
                out.append(
                    f"{inst.iid}: used_bytes={inst.used_bytes} != sum={acc}"
                )
            if inst.used_bytes > inst.capacity_bytes:
                out.append(
                    f"{inst.iid}: over capacity "
                    f"{inst.used_bytes}>{inst.capacity_bytes}"
                )
            host = sum(
                c.size for c in inst.copies.values() if c.phase == "host"
            )
            if host != inst.host_used:
                out.append(
                    f"{inst.iid}: host_used={inst.host_used} != sum={host}"
                )
        return out
