"""In-process simulated cluster: N ModelMeshInstances, no sockets.

Unlike tests/cluster_util.py (real localhost gRPC — the wire-parity
tier), the sim cluster keeps everything in-process and on the virtual
clock: an in-process ``SimLoader`` replaces the gRPC sidecar runtime,
peer forwarding is a direct method call routed through the pod table,
and every instance talks to the shared KV through its own
fault-injectable facade (sim/kv.py). Background tasks run whatever
cadences the scenario's TaskConfig sets (production defaults unless the
scenario compresses them) — virtual time makes either cheap; hour-scale
boundary behavior (reaper grace, surplus-copy age caps) is pinned by the
direct-tick tests in tests/test_sim_cluster.py.
"""

from __future__ import annotations

import contextlib
import functools
import logging
import re
import threading
from typing import Optional

from modelmesh_tpu.observability.tracing import (
    SPAN_HEADER,
    TRACE_HEADER,
    Tracer,
    incoming_parent_span,
    incoming_trace_id,
)
from modelmesh_tpu.runtime.spi import (
    LoadedModel,
    LocalInstanceParams,
    ModelInfo,
    ModelLoader,
    ModelLoadException,
)
from modelmesh_tpu.serving.errors import (
    ModelNotHereError,
    ServiceUnavailableError,
)
from modelmesh_tpu.serving.instance import (
    InstanceConfig,
    InvokeResult,
    ModelMeshInstance,
    RoutingContext,
)
from modelmesh_tpu.serving.tasks import BackgroundTasks, TaskConfig
from modelmesh_tpu.sim.kv import SimKV, SimKVConfig
from modelmesh_tpu.sim.ringlog import RingLog
from modelmesh_tpu.utils import clock as _clock

log = logging.getLogger(__name__)

# Model-id prefixes triggering injected load faults (mirrors runtime/fake).
FAIL_LOAD_PREFIX = "fail-load-"
SLOW_LOAD_PREFIX = "slow-load-"

# "big<N>x-" ids are N x the loader's default size — the sharded-group
# scenarios' way to mint a model no single sim device can hold.
_BIG_PREFIX_RE = re.compile(r"^big(\d+)x-")


class SimLoader(ModelLoader):
    """In-process loader with virtual-time load delays and fault hooks.

    Weight-streaming capable: exports deterministic synthetic chunks
    (``transfer_chunks`` per model, small payloads — the ACCOUNTED size
    is the model's declared size, which is what the host tier and the
    invariants reason about) and re-materializes from a stream. Chunk
    delay defaults to ZERO virtual time: direct-tick tests drive the
    clock manually and a sleeping stream would deadlock them; the
    mid-stream fault hooks key on chunk COUNTS, not time, so scenario
    determinism doesn't need the delay. Scenarios that want transfers
    to consume virtual time opt in via ``transfer_chunk_delay_ms``."""

    def __init__(
        self,
        capacity_bytes: int = 64 << 20,
        default_size_bytes: int = 8 << 20,
        load_delay_ms: float = 0.0,
        load_concurrency: int = 8,
        transfer_chunks: int = 8,
        transfer_chunk_delay_ms: float = 0.0,
    ):
        self.capacity_bytes = capacity_bytes
        self.default_size_bytes = default_size_bytes
        self.load_delay_ms = load_delay_ms
        self.load_concurrency = load_concurrency
        self.transfer_chunks = max(int(transfer_chunks), 1)
        self.transfer_chunk_delay_ms = transfer_chunk_delay_ms
        self.loaded_models: dict[str, int] = {}  #: guarded-by: _lock
        # model_id -> (shard_index, shard_count) for copies materialized
        # through the sharded SPI (invariants cross-check these against
        # the registry's group claims).
        self.shard_coords: dict[str, tuple[int, int]] = {}  #: guarded-by: _lock
        self.load_count = 0  #: guarded-by: _lock
        self.stream_load_count = 0  #: guarded-by: _lock
        self.shard_load_count = 0  #: guarded-by: _lock
        self.unload_count = 0  #: guarded-by: _lock
        # model_id -> extra virtual load delay (the slow-loadModel fault).
        self.slow_models: dict[str, float] = {}  #: guarded-by: _lock
        # model_ids whose next load fails (one-shot unless re-armed).
        self.fail_models: set[str] = set()  #: guarded-by: _lock
        self._lock = threading.Lock()

    def startup(self) -> LocalInstanceParams:
        return LocalInstanceParams(
            capacity_bytes=self.capacity_bytes,
            load_concurrency=self.load_concurrency,
            load_timeout_ms=30_000,
        )

    def load(self, model_id: str, info: ModelInfo) -> LoadedModel:
        with self._lock:
            delay_ms = self.load_delay_ms + self.slow_models.get(model_id, 0)
            fail = model_id in self.fail_models or model_id.startswith(
                FAIL_LOAD_PREFIX
            )
        if model_id.startswith(SLOW_LOAD_PREFIX):
            delay_ms = max(delay_ms, 2_000.0)
        if delay_ms:
            _clock.sleep(delay_ms / 1000.0)
        if fail:
            with self._lock:
                self.fail_models.discard(model_id)
            raise ModelLoadException(f"injected load failure: {model_id}")
        size = self._size_for(model_id)
        with self._lock:
            self.loaded_models[model_id] = size
            self.load_count += 1
        return LoadedModel(handle=model_id, size_bytes=size)

    def predict_size(self, model_id: str, info: ModelInfo) -> int:
        return self._size_for(model_id)

    def model_size(self, model_id: str, handle) -> int:
        with self._lock:
            return self.loaded_models.get(model_id, 0)

    def unload(self, model_id: str) -> None:
        with self._lock:
            self.loaded_models.pop(model_id, None)
            self.shard_coords.pop(model_id, None)
            self.unload_count += 1

    def is_loaded(self, model_id: str) -> bool:
        with self._lock:
            return model_id in self.loaded_models

    def set_slow(self, model_id: str, delay_ms: float) -> None:
        with self._lock:
            self.slow_models[model_id] = delay_ms

    def set_fail_next(self, model_id: str) -> None:
        with self._lock:
            self.fail_models.add(model_id)

    def _size_for(self, model_id: str) -> int:
        m = _BIG_PREFIX_RE.match(model_id)
        if m:
            return self.default_size_bytes * int(m.group(1))
        # Deterministic per-id size (stable across runs — hash() is
        # salted per process, so use a real digest).
        import zlib

        h = zlib.crc32(model_id.encode()) % 1000
        return int(self.default_size_bytes * (0.5 + h / 1000.0))

    # -- weight streaming --------------------------------------------------

    @property
    def supports_weight_streaming(self) -> bool:
        return True

    def export_weights(self, model_id: str, handle):
        from modelmesh_tpu.runtime.spi import WeightChunk

        with self._lock:
            if model_id not in self.loaded_models:
                return None
        n = self.transfer_chunks

        def gen():
            for i in range(n):
                yield WeightChunk(
                    seq=i,
                    # Synthetic but deterministic payload; size accounting
                    # uses the declared model size, not these bytes.
                    payload=f"{model_id}:{i}".encode(),
                    layer=i,
                    last=i == n - 1,
                )

        return gen()

    def load_from_stream(
        self, model_id: str, info: ModelInfo, chunks, partial_ready=None,
    ) -> LoadedModel:
        size = self._size_for(model_id)
        seen = 0
        fired_partial = False
        for chunk in chunks:
            if self.transfer_chunk_delay_ms:
                _clock.sleep(self.transfer_chunk_delay_ms / 1000.0)
            seen += 1
            if (
                partial_ready is not None
                and not fired_partial
                and seen * 2 >= self.transfer_chunks
            ):
                # Half the layers landed: this synthetic runtime can
                # serve from here (the PARTIAL-phase test hook). Register
                # the copy before announcing — the runtime_call probe
                # checks is_loaded().
                fired_partial = True
                with self._lock:
                    self.loaded_models[model_id] = size
                partial_ready(LoadedModel(handle=model_id, size_bytes=size))
        if seen == 0:
            raise ModelLoadException(f"{model_id}: empty weight stream")
        with self._lock:
            self.loaded_models[model_id] = size
            self.stream_load_count += 1
        return LoadedModel(handle=model_id, size_bytes=size)

    # -- sharded execution -------------------------------------------------

    @property
    def supports_sharded_execution(self) -> bool:
        return True

    def _shard_share(self, model_id: str, shard_count: int) -> int:
        return -(-self._size_for(model_id) // max(shard_count, 1))

    def load_shard(
        self, model_id: str, info: ModelInfo, shard_index: int,
        shard_count: int,
    ) -> LoadedModel:
        with self._lock:
            delay_ms = self.load_delay_ms + self.slow_models.get(model_id, 0)
            fail = model_id in self.fail_models or model_id.startswith(
                FAIL_LOAD_PREFIX
            )
        if delay_ms:
            _clock.sleep(delay_ms / 1000.0)
        if fail:
            with self._lock:
                self.fail_models.discard(model_id)
            raise ModelLoadException(f"injected load failure: {model_id}")
        share = self._shard_share(model_id, shard_count)
        with self._lock:
            self.loaded_models[model_id] = share
            self.shard_coords[model_id] = (shard_index, shard_count)
            self.load_count += 1
            self.shard_load_count += 1
        return LoadedModel(handle=model_id, size_bytes=share)

    def export_shard_weights(self, model_id: str, handle):
        """Synthetic shard stream: ``transfer_chunks`` stands in for the
        model's leaf count, so this shard's slice of it (global layer
        indices, like the real loader) is what goes on the wire."""
        from modelmesh_tpu.runtime.spi import WeightChunk
        from modelmesh_tpu.transfer.protocol import shard_chunk_indices

        with self._lock:
            if model_id not in self.loaded_models:
                return None
            coords = self.shard_coords.get(model_id)
        if coords is None:
            return None
        layers = list(shard_chunk_indices(self.transfer_chunks, *coords))

        def gen():
            for pos, layer in enumerate(layers):
                yield WeightChunk(
                    seq=pos,
                    payload=f"{model_id}:{layer}".encode(),
                    layer=layer,
                    last=pos == len(layers) - 1,
                )

        return gen()

    def load_shard_from_stream(
        self, model_id: str, info: ModelInfo, shard_index: int,
        shard_count: int, chunks,
    ) -> LoadedModel:
        from modelmesh_tpu.transfer.protocol import shard_chunk_indices

        seen: set[int] = set()
        for chunk in chunks:
            if self.transfer_chunk_delay_ms:
                _clock.sleep(self.transfer_chunk_delay_ms / 1000.0)
            seen.add(chunk.layer)
        want = set(
            shard_chunk_indices(self.transfer_chunks, shard_index,
                                shard_count)
        )
        if seen != want:
            raise ModelLoadException(
                f"{model_id}: shard {shard_index}/{shard_count} stream "
                f"delivered layers {sorted(seen)}, expected {sorted(want)}"
            )
        share = self._shard_share(model_id, shard_count)
        with self._lock:
            self.loaded_models[model_id] = share
            self.shard_coords[model_id] = (shard_index, shard_count)
            self.stream_load_count += 1
            self.shard_load_count += 1
        return LoadedModel(handle=model_id, size_bytes=share)


class SimPod:
    def __init__(self, instance: ModelMeshInstance, tasks: BackgroundTasks,
                 loader: SimLoader):
        self.instance = instance
        self.tasks = tasks
        self.loader = loader
        self.alive = True

    @property
    def iid(self) -> str:
        return self.instance.instance_id


class SimCluster:
    """Build under an installed VirtualClock; drive via the scenario
    runner (sim/scenario.py) or directly in tests."""

    def __init__(
        self,
        n: int = 3,
        seed: int = 0,
        kv_config: Optional[SimKVConfig] = None,
        task_config: Optional[TaskConfig] = None,
        capacity_bytes: int = 64 << 20,
        start_tasks: bool = True,
        load_delay_ms: float = 50.0,
        instance_kwargs: Optional[dict] = None,
        service_base_ms: float = 0.0,
        service_congestion_ms: float = 0.0,
        service_scope: str = "fleet",
        service_congestion_cap: int = 0,
    ):
        self.seed = seed
        # Virtual-time service-cost model for runtime calls: each
        # dispatch costs base + congestion * (concurrent dispatches - 1)
        # virtual ms. ``service_scope`` picks what "concurrent" means:
        # "fleet" (default) counts dispatches FLEET-GLOBAL (one shared
        # accelerator domain — overload scenarios test admission
        # control, not placement spread); "instance" prices each pod's
        # dispatches independently, so COPY COUNT and placement spread
        # change latency — the model the autoscale scenarios need (more
        # copies = less per-pod concurrency = lower tail). Zero costs
        # keep the historical instantaneous runtime; without a
        # congestion term there is no tail for either controller to
        # protect.
        if service_scope not in ("fleet", "instance"):
            raise ValueError(f"unknown service_scope {service_scope!r}")
        self.service_base_ms = service_base_ms
        self.service_congestion_ms = service_congestion_ms
        self.service_scope = service_scope
        # Congestion ceiling (concurrent dispatches counted beyond the
        # first; 0 = uncapped). A real runtime bounds its admission
        # queue, so per-dispatch cost saturates instead of growing with
        # an unbounded backlog — without the cap, one deep pre-recovery
        # backlog prices NEW requests for as long as its slowest sleeper
        # lives, and no scaling action can ever look recovered.
        self.service_congestion_cap = int(service_congestion_cap)
        # scope key ("" fleet-global, else instance id) -> in-flight count
        self._service_inflight: dict[str, int] = {}  #: guarded-by: _service_lock
        self._service_lock = threading.Lock()
        self.kv = SimKV(seed=seed, config=kv_config)
        self.task_config = task_config or TaskConfig()
        #: shared-ok: scenario-driver state — the script step and upgrade coordinator run on one thread at a time
        self.pods: list[SimPod] = []
        # Instances this scenario demanded copies of (feeds the
        # availability invariant).
        self.demanded: set[str] = set()
        # Per-request outcome log:
        # (virtual_ms, model_id, ok, error, virtual_latency_ms).
        # The reconfiguration scenarios' no-failure-spike check and the
        # SLO invariant read this — "no demanded model unserved at any
        # virtual instant" and "p99 within objective at every
        # checkpoint" are asserted over the observed probe traffic, not
        # just quiescence. Bounded ring (MM_SIM_LOG_EVENTS): unbounded
        # per-probe accumulation is a memory blowup at macro scale.
        self.request_log = RingLog()
        # instance_id -> virtual ms it died (kill or post-drain); the
        # runner merges this into the dead-placement grace bookkeeping
        # for deaths IT didn't schedule (e.g. rolling-upgrade waves).
        #: shared-ok: scenario-driver state — the script step and upgrade coordinator run on one thread at a time
        self.deaths: dict[str, int] = {}
        # Drain reports by instance id (reconfig/drain.py), for scenario
        # checks (non-vacuity: the drained pod really migrated copies).
        #: shared-ok: scenario-driver state — the script step and upgrade coordinator run on one thread at a time
        self.drain_reports: dict = {}
        # reconfig/rolling.py UpgradeReport of the last rolling_upgrade.
        self.upgrade_report = None
        # Defaults reused when a scenario adds replacement instances
        # mid-run (rolling upgrade waves).
        self._default_instance = dict(
            capacity_bytes=capacity_bytes,
            start_tasks=start_tasks,
            load_delay_ms=load_delay_ms,
            **(instance_kwargs or {}),
        )
        # Transfer-progress fault hooks: fn(sender_iid, model_id,
        # chunk_index) called on EVERY peer chunk fetch before it is
        # served — scenarios arm mid-stream faults here (kill or
        # partition the sender after K chunks). List mutation is
        # GIL-atomic; hooks run on the fetching thread.
        self._transfer_hooks: list = []
        # (model_id, action, sender_iid) for every armed fault that
        # actually FIRED — scenario checks assert on this so a fault
        # that never triggered (stream never started) fails loudly
        # instead of passing vacuously.
        self.transfer_faults_fired: list[tuple[str, str, str]] = []
        # Batched data plane observability: one row per batched runtime
        # dispatch — (virtual_ms, instance_id, batch_size, distinct
        # models). Scenario checks assert the queue/flush state machine
        # coalesced concurrent requests under virtual time. Same bound
        # as request_log.
        self.batch_dispatches = RingLog()
        #: shared-ok: scenario-driver state — the script step and upgrade coordinator run on one thread at a time
        self._n = 0
        for _ in range(n):
            self.add_instance(
                capacity_bytes=capacity_bytes,
                start_tasks=start_tasks,
                load_delay_ms=load_delay_ms,
                **(instance_kwargs or {}),
            )
        # The fleet must see itself before a scenario starts killing it.
        for pod in self.pods:
            pod.instance.instances_view.wait_for(
                lambda v: len(v) >= n, timeout=10
            )

    # -- construction ------------------------------------------------------

    def add_instance(
        self,
        capacity_bytes: int = 64 << 20,
        start_tasks: bool = True,
        load_delay_ms: float = 50.0,
        **config_kwargs,
    ) -> SimPod:
        iid = f"sim-{self._n}"
        self._n += 1
        loader = SimLoader(
            capacity_bytes=capacity_bytes, load_delay_ms=load_delay_ms
        )
        # trace_sample=1 unless the scenario overrides: scenario trace
        # assertions must be deterministic, not a sampling coin flip.
        config_kwargs.setdefault("trace_sample", 1)
        inst = ModelMeshInstance(
            self.kv.for_instance(iid),
            loader,
            InstanceConfig(
                instance_id=iid,
                endpoint=iid,  # direct-call transport routes by id
                load_timeout_s=20,
                space_wait_s=5.0,
                min_churn_age_ms=0,
                **config_kwargs,
            ),
            peer_call=self._peer_call,
            peer_fetch=self._peer_fetch,
            runtime_call=self._runtime_call,
            # Deterministic batched twin: sim instances run the full
            # continuous-batching queue (serving/batching.py) under
            # virtual time, dispatching through the same per-pod checks
            # as the single-call path.
            runtime_call_batch=functools.partial(
                self._runtime_call_batch, iid
            ),
        )
        tasks = BackgroundTasks(inst, self.task_config)
        pod = SimPod(inst, tasks, loader)
        self.pods.append(pod)
        if start_tasks:
            tasks.start()
        return pod

    # -- in-process transport ----------------------------------------------

    def _find(self, endpoint: str) -> Optional[SimPod]:
        for pod in self.pods:
            if pod.iid == endpoint or pod.instance.config.endpoint == endpoint:
                return pod
        return None

    def _peer_call(
        self, endpoint: str, model_id: str, method, payload: bytes,
        headers, ctx: RoutingContext,
    ) -> InvokeResult:
        pod = self._find(endpoint)
        if pod is None or not pod.alive:
            raise ServiceUnavailableError(endpoint)
        # Emulate the wire's trace handoff (MeshInternalServicer.Forward):
        # the receiving pod re-opens the propagated trace in ITS tracer,
        # parented under the sender's forward span — even though the call
        # runs on the sender's thread here.
        headers = list(headers)
        tid = incoming_trace_id(headers)
        parent = incoming_parent_span(headers)
        if tid:
            # Like the wire servicer: the context is re-attached fresh on
            # any further outbound hop, never replayed from this list.
            headers = [
                (k, v) for k, v in headers
                if k not in (TRACE_HEADER, SPAN_HEADER)
            ]
        with pod.instance.tracer.trace(
            tid, model_id, method or "", parent_span=parent,
        ) if tid else contextlib.nullcontext():
            result = pod.instance.invoke_model(
                model_id, method, payload, headers, ctx, sync=True
            )
        # The wire piggybacks the responder's load on every Forward
        # response (mm-load trailer); the direct-call transport carries
        # the SAME feedback so scenarios exercise the real LoadView
        # decay/staleness machinery under virtual time.
        result.feedback = pod.instance.load_feedback()
        return result

    def _peer_fetch(self, endpoint: str, model_id: str, chunk_index: int,
                    fingerprint: str):
        """Direct-call FetchWeights transport with mid-stream fault
        injection: every chunk runs the armed transfer hooks first, then
        re-checks the sender — a hook that killed or partitioned the
        sender makes THIS chunk fail exactly like the wire would."""
        pod = self._find(endpoint)
        if pod is None or not pod.alive:
            raise ServiceUnavailableError(endpoint)
        for hook in list(self._transfer_hooks):
            hook(pod.iid, model_id, chunk_index)
        if not pod.alive or self.kv.is_partitioned(pod.iid):
            # A KV partition models a full network partition for the
            # instance: the transfer channel is unreachable too.
            raise ServiceUnavailableError(endpoint)
        # Trace handoff, as on the gRPC FetchWeights surface: the
        # fetching load's trace context (live on this thread) re-opens
        # in the SENDER pod's tracer so its chunk serving joins the
        # tree — once per transfer (chunk 0), like the wire servicer.
        tid = Tracer.current_trace_id() if chunk_index == 0 else ""
        if not tid:
            return pod.instance.handle_weight_fetch(
                model_id, chunk_index, fingerprint
            )
        with pod.instance.tracer.trace(
            tid, model_id, "FetchWeights",
            parent_span=Tracer.current_span_id(),
        ), pod.instance.tracer.span("serve-chunk", chunk=chunk_index):
            return pod.instance.handle_weight_fetch(
                model_id, chunk_index, fingerprint
            )

    def add_transfer_hook(self, hook) -> None:
        self._transfer_hooks.append(hook)

    def arm_transfer_fault(
        self, model_id: str, after_chunks: int, action: str,
    ) -> None:
        """One-shot mid-stream fault: once ``after_chunks`` chunks of
        ``model_id`` have been served, ``kill`` or ``partition`` the
        SENDER — the receiver's next chunk fetch fails and its store
        fallback must take over."""
        state = {"served": 0, "fired": False}

        def hook(sender_iid: str, mid: str, chunk_index: int) -> None:
            if mid != model_id or state["fired"]:
                return
            state["served"] += 1
            if state["served"] <= after_chunks:
                return
            state["fired"] = True
            self.transfer_faults_fired.append((model_id, action, sender_iid))
            log.info(
                "transfer fault: %s sender %s after %d chunks of %s",
                action, sender_iid, after_chunks, mid,
            )
            if action == "kill":
                self.kill(sender_iid)
            elif action == "partition":
                self.partition(sender_iid)
            else:
                raise ValueError(f"unknown transfer fault action {action}")

        self.add_transfer_hook(hook)

    def _service_delay(self, iid: str) -> None:
        """Charge one runtime dispatch its virtual service cost under
        the congestion model (no-op when unconfigured). The concurrency
        key is the serving pod under scope="instance", fleet-global
        otherwise."""
        if not self.service_base_ms and not self.service_congestion_ms:
            return
        key = iid if self.service_scope == "instance" else ""
        with self._service_lock:
            inflight = self._service_inflight.get(key, 0) + 1
            self._service_inflight[key] = inflight
        try:
            queued = inflight - 1
            if self.service_congestion_cap > 0:
                queued = min(queued, self.service_congestion_cap)
            delay_ms = self.service_base_ms + (
                self.service_congestion_ms * queued
            )
            if delay_ms > 0:
                _clock.sleep(delay_ms / 1000.0)
        finally:
            with self._service_lock:
                self._service_inflight[key] -= 1

    def _runtime_call(
        self, ce, method, payload: bytes, headers, cancel_event=None
    ) -> bytes:
        # ce.loaded.handle is the model id; the entry's OWNING loader is
        # found through the serving instance the entry lives in — but the
        # runtime_call closure is per-instance-agnostic here, so resolve
        # by membership (a model can be loaded on several pods).
        mid = ce.model_id
        for pod in self.pods:
            if pod.alive and pod.instance.cache.get_quietly(mid) is ce:
                if not pod.loader.is_loaded(mid):
                    raise ModelNotHereError(pod.iid, mid)
                self._service_delay(pod.iid)
                return f"{mid}:sim".encode()
        raise ModelNotHereError("?", mid)

    def _runtime_call_batch(
        self, iid: str, items, cancel_event=None
    ) -> list:
        """Batched twin of ``_runtime_call``: per-item results are
        byte-identical to N solo calls (the batched-vs-sequential
        identity), with per-item isolation — a model the pod's loader
        lost fails only its own slot. Each dispatch is recorded with
        its virtual timestamp for scenario assertions."""
        from modelmesh_tpu.cache.lru import now_ms
        from modelmesh_tpu.runtime.spi import ModelNotLoadedError

        pod = self._find(iid)
        self.batch_dispatches.append((
            now_ms(), iid, len(items),
            len({item.model_id for item in items}),
        ))
        # One batched dispatch = one service charge (that is the point
        # of batching); congestion still scales with concurrent
        # dispatches — fleet-global, like every service charge (see the
        # constructor comment).
        self._service_delay(iid)
        out: list = []
        for item in items:
            mid = item.model_id
            if pod is None or not pod.alive or not pod.loader.is_loaded(mid):
                out.append(ModelNotLoadedError(mid))
            else:
                out.append(f"{mid}:sim".encode())
        return out

    # -- faults ------------------------------------------------------------

    def pod(self, i: int) -> SimPod:
        return self.pods[i]

    def by_id(self, iid: str) -> SimPod:
        pod = self._find(iid)
        if pod is None:
            raise KeyError(iid)
        return pod

    def spawn(self, instance_version: str = "") -> SimPod:
        """Add a replacement instance with the cluster's construction
        defaults — the rolling-upgrade 'new pod at the new version'."""
        kwargs = dict(self._default_instance)
        if instance_version:
            kwargs["instance_version"] = instance_version
        return self.add_instance(**kwargs)

    def drain(self, iid: str):
        """Graceful drain + terminate (reconfig/drain.py semantics): the
        instance pre-copies its hot copies to survivors, deregisters,
        and only then dies. Returns the DrainReport."""
        from modelmesh_tpu.reconfig.drain import DrainController

        pod = self.by_id(iid)
        if not pod.alive:
            return None
        report = DrainController(pod.instance).drain()
        self.drain_reports[iid] = report
        self.kill(iid)
        return report

    def rolling_upgrade(
        self, target_version: str, max_unavailable: int = 1,
    ):
        """Drive the fleet to ``target_version`` in drain waves — the
        reconfig/rolling.py coordinator with its hooks mapped onto this
        cluster. Runs synchronously on the calling (worker) thread."""
        from modelmesh_tpu.reconfig.rolling import RollingUpgradeCoordinator

        def list_instances():
            return [
                (p.iid, p.instance._build_instance_record())
                for p in self.live_pods()
            ]

        def replace(_old_iid: str, version: str) -> str:
            return self.spawn(version).iid

        def wait_ready(expect_n: int) -> None:
            # Readiness = every live pod SEES every live pod (the
            # replacements included) — a raw count would be satisfied by
            # the killed pods' not-yet-deleted records and let the next
            # wave start while replacements are invisible to routing.
            live_ids = {p.iid for p in self.live_pods()}
            for pod in self.live_pods():
                pod.instance.instances_view.wait_for(
                    lambda v: live_ids <= {iid for iid, _ in v.items()},
                    timeout=10,
                )

        coordinator = RollingUpgradeCoordinator(
            target_version,
            list_instances=list_instances,
            drain_instance=self.drain,
            replace_instance=replace,
            wait_ready=wait_ready,
            max_unavailable=max_unavailable,
        )
        report = coordinator.run()
        self.upgrade_report = report
        return report

    def kill(self, iid: str) -> None:
        """Crash an instance: tasks stop, the serving surface vanishes,
        the session lease is revoked (peers see the ephemeral record
        disappear) — no graceful migration."""
        pod = self.by_id(iid)
        if not pod.alive:
            return
        self.deaths.setdefault(iid, _clock.get_clock().now_ms())
        pod.alive = False
        pod.tasks.stop()
        pod.instance.shutting_down = True
        pod.instance.loading_pool.shutdown()
        pod.instance._session.close()
        pod.instance._election.close()
        pod.instance.registry_view.close()
        pod.instance.instances_view.close()

    def partition(self, iid: str) -> None:
        self.kv.partition(iid)

    def heal(self, iid: str) -> None:
        self.kv.heal(iid)

    def expire_lease(self, iid: str) -> bool:
        pod = self.by_id(iid)
        return self.kv.expire_instance_session(pod.instance._session.key)

    def slow_load(self, iid: str, model_id: str, delay_ms: float) -> None:
        self.by_id(iid).loader.set_slow(model_id, delay_ms)

    def fail_next_load(self, iid: str, model_id: str) -> None:
        self.by_id(iid).loader.set_fail_next(model_id)

    # -- workload ----------------------------------------------------------

    def live_pods(self) -> list[SimPod]:
        return [p for p in self.pods if p.alive]

    def first_live(self) -> SimPod:
        pods = self.live_pods()
        if not pods:
            raise RuntimeError("no live instances")
        return pods[0]

    def register(self, model_id: str, model_type: str = "sim",
                 scheme: str = "mem") -> None:
        # ``scheme`` picks the model-path family: "mem" (default) is a
        # store-only spec, a layer-streamable family name (e.g. "mlp")
        # makes the model eligible for sharded placement groups.
        try:
            self.first_live().instance.register_model(
                model_id, ModelInfo(model_type, f"{scheme}://{model_id}")
            )
        except Exception as e:  # noqa: BLE001 — registration may race faults
            log.debug("sim register(%s) raced a fault: %s", model_id, e)

    def ensure(self, model_id: str, chain: int = 0) -> None:
        self.demanded.add(model_id)
        try:
            self.first_live().instance.ensure_loaded(
                model_id, sync=False, chain=chain
            )
        except Exception as e:  # noqa: BLE001 — demand may race faults
            log.debug("sim ensure(%s) raced a fault: %s", model_id, e)

    def invoke(self, model_id: str, via: Optional[str] = None) -> None:
        """One probe request, entered at ``via`` (default: first live
        pod), traced end-to-end (sim pods trace every root), and logged
        as (virtual_ms, model, ok, error, virtual_latency_ms) — the SLO
        invariant's observed-traffic witness."""
        self.demanded.add(model_id)
        clock = _clock.get_clock()
        now = clock.now_ms()
        try:
            pod = self.by_id(via) if via else self.first_live()
            with pod.instance.tracer.trace("", model_id, "/sim/Predict"):
                pod.instance.invoke_model(model_id, "/sim/Predict", b"x", [])
        except Exception as e:  # noqa: BLE001 — demand may race faults
            self.request_log.append(
                (now, model_id, False, f"{type(e).__name__}: {e}",
                 clock.now_ms() - now)
            )
            log.debug("sim invoke(%s) raced a fault: %s", model_id, e)
        else:
            self.request_log.append(
                (now, model_id, True, "", clock.now_ms() - now)
            )

    def unregister(self, model_id: str) -> None:
        try:
            self.first_live().instance.unregister_model(model_id)
            self.demanded.discard(model_id)
        except Exception as e:  # noqa: BLE001
            log.debug("sim unregister(%s) raced a fault: %s", model_id, e)

    # -- quiescence --------------------------------------------------------

    def pools_pending(self) -> int:
        """Queued/running async janitorial tasks (deregisters, unloads,
        deletion cleanups) across live pods. Non-zero at invariant time
        means a registry mutation is still in flight — the source of the
        registry_cache_convergence flake the quiesce drain closes."""
        total = 0
        for pod in self.live_pods():
            inst = pod.instance
            total += inst._unload_pool.pending + inst._cleanup_pool.pending
        return total

    def quiesce_async_work(
        self, clock, step_ms: int = 2_000, wall_timeout_s: float = 10.0,
    ) -> bool:
        """Pump virtual time until every pod's cleanup/unload pool is
        idle (a pending task may be sleeping on injected virtual
        latency). Wall-bounded: a task wedged on something external
        (e.g. an unreleased hold gate) times out rather than hanging the
        run — the caller's inline janitor pass then repairs whatever the
        stuck mutation would have."""
        import time as _wall

        deadline = _wall.monotonic() + wall_timeout_s  #: wall-clock: bounds REAL pool-thread progress (docstring above) — the clock is the thing being pumped here
        while self.pools_pending():
            if _wall.monotonic() >= deadline:  #: wall-clock: same wall bound as above
                return False
            clock.advance(step_ms)
            _wall.sleep(0.001)  #: wall-clock: yields to real pool threads between virtual pumps
        return True

    # -- teardown ----------------------------------------------------------

    def close(self) -> None:
        for pod in self.pods:
            if pod.alive:
                pod.tasks.stop()
                try:
                    pod.instance.shutdown()
                except Exception:  # noqa: BLE001 — faults may be armed
                    pass
                pod.alive = False
        self.kv.close()
