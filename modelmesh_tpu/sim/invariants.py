"""Machine-checked cluster invariants, run at scenario quiescence.

Each checker returns a list of violation strings (empty = PASS). They are
deliberately *quiescent-state* properties: mid-scenario the cluster is
allowed to be inconsistent (that's what convergence protocols are for);
after faults stop and enough virtual time passes for the janitor/reaper
cadences to run, these must hold.

Catalog (see docs/testing.md for the rationale of each):
- ``demanded_models_served``  — every model the scenario demanded has at
  least one ACTIVE copy on a live instance, or a recorded load failure,
  or was unregistered.
- ``no_dead_placements``      — no registry record points at an instance
  that has been dead longer than the reaper prune grace.
- ``registry_cache_convergence`` — live instances' ACTIVE cache entries
  and the registry's placement maps agree in both directions.
- ``vmodel_resolution_acyclic``  — vmodel target resolution terminates
  (no alias cycles, active targets exist in the registry).
- ``cache_weight_consistent`` — per instance: the cache's accounted
  weight equals the sum of entry weights, never exceeds capacity, and
  pending-unload units are non-negative; the host staging tier obeys the
  same conservation law in bytes against its own budget.
- ``host_claims_converged`` — registry host-tier claims
  (transfer/ demotions) on LIVE instances have an actual host-resident
  snapshot behind them.
- ``draining_deregistered`` — the DRAINING vocabulary (reconfig/): a
  live instance still advertising ``draining`` at quiescence has
  finished its drain, so no registry placement may point at it. Without
  this term the suite would misread a drained-but-alive pod's leftover
  placements as some other checker's problem (it is neither a dead
  placement — the pod is alive — nor a cache mismatch once the local
  copy is gone).
- ``copy_bounds`` — no record holds more placements than the fleet's
  per-model ceiling (``TaskConfig.max_copies``): the autoscale
  controller's hard cap, and the first place a runaway scale-up loop
  would show.
- ``group_complete_or_absent`` — sharded placement groups are
  all-or-nothing: every record either carries no group or a complete
  one whose live members' local entries agree with the claims.

``slo_attained(spec)`` is a FACTORY, not part of the standard suite:
scenarios attach it via ``extra_checks`` with their own objective spec.
Unlike the quiescent checkers it judges the OBSERVED probe traffic —
windowed p99/availability over the whole run's virtual timeline, a
violation string per failing virtual checkpoint — so a mid-run latency
spike fails the scenario even if the cluster later converges.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from modelmesh_tpu.serving.entry import EntryState

if TYPE_CHECKING:  # pragma: no cover
    from modelmesh_tpu.sim.harness import SimCluster


def demanded_models_served(cluster: "SimCluster") -> list[str]:
    out: list[str] = []
    inst = cluster.first_live().instance
    active: dict[str, set[str]] = {}
    for pod in cluster.live_pods():
        for mid in pod.instance.cache.keys():
            ce = pod.instance.cache.get_quietly(mid)
            if ce is not None and ce.state.is_servable:
                active.setdefault(mid, set()).add(pod.iid)
    for mid in sorted(cluster.demanded):
        mr = inst.registry.get(mid)
        if mr is None:
            continue  # unregistered (or lost demand) — nothing owed
        if active.get(mid):
            continue
        if mr.load_failures:
            continue  # failure record IS the answer (fail-fast, not silence)
        out.append(
            f"demanded model {mid} has no ACTIVE copy and no failure "
            f"record (placements={sorted(mr.all_placements)})"
        )
    return out


def no_dead_placements(
    cluster: "SimCluster", dead_since_ms: dict[str, int], now_ms: int,
    grace_ms: int,
) -> list[str]:
    """``dead_since_ms``: instance -> virtual time it died (scenario
    bookkeeping). ``grace_ms`` should be assume_gone_ms + one reaper
    interval — the window the protocol legitimately allows."""
    out: list[str] = []
    inst = cluster.first_live().instance
    for mid, mr in inst.registry.items():
        for iid in sorted(mr.all_placements):
            died = dead_since_ms.get(iid)
            if died is not None and now_ms - died > grace_ms:
                out.append(
                    f"record {mid} still points at {iid}, dead for "
                    f"{(now_ms - died) / 1000.0:.0f}s (> grace "
                    f"{grace_ms / 1000.0:.0f}s)"
                )
    return out


def registry_cache_convergence(cluster: "SimCluster") -> list[str]:
    out: list[str] = []
    inst = cluster.first_live().instance
    records = dict(inst.registry.items())
    for pod in cluster.live_pods():
        mmi = pod.instance
        for mid in mmi.cache.keys():
            ce = mmi.cache.get_quietly(mid)
            if ce is None or not ce.state.is_servable:
                continue
            mr = records.get(mid)
            if mr is None:
                out.append(
                    f"{pod.iid} serves {mid} but the registry has no record"
                )
            elif pod.iid not in mr.instance_ids:
                out.append(
                    f"{pod.iid} serves {mid} but the record does not list "
                    f"it (instance_ids={sorted(mr.instance_ids)})"
                )
    for mid, mr in records.items():
        for iid in sorted(mr.instance_ids):
            pod = next((p for p in cluster.live_pods() if p.iid == iid), None)
            if pod is None:
                continue  # dead holders are no_dead_placements' concern
            ce = pod.instance.cache.get_quietly(mid)
            # Servable covers ACTIVE plus the other promoted-to-registry
            # states (PARTIAL mid-stream, SHARDED group members).
            if ce is None or (
                not ce.state.is_servable
                and not ce.state.is_loading
            ):
                out.append(
                    f"record {mid} lists {iid} but that instance has no "
                    f"usable copy (entry={ce.state.value if ce else 'none'})"
                )
    return out


def vmodel_resolution_acyclic(cluster: "SimCluster") -> list[str]:
    """Vmodels resolve alias -> concrete model. A target naming another
    vmodel id (aliases-of-aliases) must terminate; active targets must
    exist in the registry."""
    out: list[str] = []
    inst = cluster.first_live().instance
    from modelmesh_tpu.kv.table import KVTable
    from modelmesh_tpu.records import VModelRecord

    table: KVTable[VModelRecord] = KVTable(
        inst.store, f"{inst.config.kv_prefix}/vmodels", VModelRecord
    )
    vmodels = dict(table.items())
    for vmid, vr in vmodels.items():
        seen = {vmid}
        cur = vr.active_model
        while cur in vmodels:
            if cur in seen:
                out.append(f"vmodel resolution cycle through {sorted(seen)}")
                break
            seen.add(cur)
            cur = vmodels[cur].active_model
        else:
            if cur and inst.registry.get(cur) is None:
                out.append(
                    f"vmodel {vmid} resolves to {cur}, which is not in "
                    "the registry"
                )
    return out


def cache_weight_consistent(cluster: "SimCluster") -> list[str]:
    out: list[str] = []
    for pod in cluster.live_pods():
        cache = pod.instance.cache
        with cache.eviction_lock:
            accounted = cache.weight
            actual = sum(e.weight for e in cache._entries.values())
            capacity = cache.capacity
        if accounted != actual:
            out.append(
                f"{pod.iid}: cache weight {accounted} != sum of entry "
                f"weights {actual} (double-counted or leaked units)"
            )
        if accounted > capacity:
            out.append(
                f"{pod.iid}: cache weight {accounted} exceeds capacity "
                f"{capacity}"
            )
        if pod.instance.unload_tracker.pending_units < 0:
            out.append(f"{pod.iid}: negative pending-unload units")
        # Host-tier byte accounting (transfer/): same conservation law
        # one tier down — accounted bytes equal the sum of resident
        # snapshot sizes and never exceed the host budget.
        tier = pod.instance.host_tier
        with tier._lock:
            host_used = tier.used_bytes
            host_actual = sum(e[1] for e in tier._copies.values())
            host_cap = tier.capacity_bytes
        if host_used != host_actual:
            out.append(
                f"{pod.iid}: host tier accounts {host_used}B but holds "
                f"{host_actual}B (leaked or double-counted snapshot)"
            )
        if host_used > host_cap:
            out.append(
                f"{pod.iid}: host tier {host_used}B exceeds budget "
                f"{host_cap}B"
            )
    return out


def host_claims_converged(cluster: "SimCluster") -> list[str]:
    """Registry host-tier claims and actual host-resident snapshots agree
    for LIVE instances: a claim with no snapshot behind it sends
    receivers to a source that will answer NOT_AVAILABLE forever (dead
    holders are the reaper's job, with the standard grace)."""
    out: list[str] = []
    inst = cluster.first_live().instance
    live = {p.iid: p for p in cluster.live_pods()}
    for mid, mr in inst.registry.items():
        for iid in sorted(getattr(mr, "host_instances", {})):
            pod = live.get(iid)
            if pod is None:
                continue
            if pod.instance.host_tier.peek(mid) is None:
                out.append(
                    f"record {mid} claims a host copy on {iid} but that "
                    "instance holds no snapshot"
                )
    return out


def draining_deregistered(cluster: "SimCluster") -> list[str]:
    """A LIVE instance still advertising DRAINING at quiescence has
    completed (or deadline-swept) its drain: every local copy was
    migrated/deregistered, so a registry placement or loading claim
    still pointing at it is a drain that lost state — not a dead
    placement (the pod is alive) and invisible to the cache-convergence
    check (the local cache is already empty)."""
    out: list[str] = []
    draining = {
        p.iid for p in cluster.live_pods() if p.instance.draining
    }
    if not draining:
        return out
    inst = cluster.first_live().instance
    for mid, mr in inst.registry.items():
        for iid in sorted(mr.all_placements):
            if iid in draining:
                out.append(
                    f"record {mid} still places on {iid}, which finished "
                    "draining (deregistration lost?)"
                )
    return out


def copy_bounds(cluster: "SimCluster") -> list[str]:
    """No registry record may hold more placements than the fleet's
    configured per-model ceiling (``TaskConfig.max_copies``) — the
    autoscale controller's hard cap, and a sanity bound every scenario
    should respect (a runaway scale-up loop shows here before it shows
    anywhere else)."""
    out: list[str] = []
    cap = cluster.task_config.max_copies
    inst = cluster.first_live().instance
    for mid, mr in inst.registry.items():
        if mr.copy_count > cap:
            out.append(
                f"record {mid} holds {mr.copy_count} copies "
                f"(> max_copies {cap}): {sorted(mr.all_placements)}"
            )
    return out


def group_complete_or_absent(cluster: "SimCluster") -> list[str]:
    """Sharded placement groups are all-or-nothing at quiescence: a
    record either carries NO group (``shard_count`` 0 and no shard
    claims) or a COMPLETE one — every shard index 0..K-1 held by a live,
    promoted member whose LOCAL cache entry agrees on its coordinates.
    A lingering partial group means the atomic plan/evict rules lost a
    member without tearing the group down (exactly the state routing
    must never see)."""
    out: list[str] = []
    inst = cluster.first_live().instance
    live = {p.iid: p for p in cluster.live_pods()}
    for mid, mr in inst.registry.items():
        count = getattr(mr, "shard_count", 0)
        shards = dict(getattr(mr, "shard_instances", {}) or {})
        if not count:
            if shards:
                out.append(
                    f"record {mid} carries shard claims "
                    f"{sorted(shards.items())} with shard_count=0"
                )
            continue
        held = {
            idx for iid, idx in shards.items()
            if iid in mr.instance_ids and iid in live
        }
        missing = sorted(set(range(count)) - held)
        if missing:
            out.append(
                f"record {mid} {count}-way group incomplete: no live "
                f"holder for indices {missing} "
                f"(claims={sorted(shards.items())})"
            )
        for iid, idx in sorted(shards.items()):
            pod = live.get(iid)
            if pod is None or iid not in mr.instance_ids:
                continue  # loading claim / dead holder: judged above
            ce = pod.instance.cache.get_quietly(mid)
            if (
                ce is None or not ce.is_shard
                or ce.shard_index != idx or ce.shard_count != count
            ):
                got = (
                    f"shard {ce.shard_index}/{ce.shard_count}"
                    if ce is not None and ce.is_shard
                    else (ce.state.value if ce is not None else "none")
                )
                out.append(
                    f"record {mid} claims shard {idx}/{count} on {iid} "
                    f"but the local entry is {got}"
                )
    return out


def slo_attained(spec: str, window_ms: int = 10_000, min_requests: int = 1,
                 model_filter=None, slo_class: str = "",
                 judge_after_ms: int = 0):
    """Machine-checked SLO attainment over the scenario's observed probe
    traffic (``SimCluster.request_log``: virtual ts, model, ok, error,
    virtual latency). The run's virtual timeline is cut into
    ``window_ms`` checkpoints; every checkpoint with at least
    ``min_requests`` completions must meet the spec's objectives
    (observability/slo.py grammar). Returns the standard checker shape:
    one violation string per failing checkpoint; a run with NO evaluated
    checkpoint fails as vacuous.

    ``model_filter`` restricts the judged traffic (e.g. one model-class
    prefix — how the overload scenario asserts per-class divergence);
    ``slo_class`` names both the spec clause to judge by and the class
    tag in violation strings (default: the spec's 'default' clause
    judging everything the filter admits).

    ``judge_after_ms`` drops samples earlier than that many virtual ms
    after the FIRST filtered sample — the detection-ramp allowance for
    REACTIVE controllers (PR-14 house style): an autoscaler or admission
    throttle cannot promise no-breach while its burn window is still
    accumulating evidence, so the judged property is "the SLO holds once
    the controller has had its detection window", with the ramp's
    duration pinned explicitly in the scenario. The vacuity guard still
    applies to what remains."""
    from modelmesh_tpu.observability.slo import (
        _percentile,
        parse_slo_spec,
    )

    objectives = parse_slo_spec(spec)
    obj = (
        objectives.get(slo_class)
        or objectives.get("default")
        or next(iter(objectives.values()))
    )
    tag = f"[{slo_class}] " if slo_class else ""

    def check(cluster: "SimCluster") -> list[str]:
        log_ = list(cluster.request_log)
        if model_filter is not None:
            log_ = [row for row in log_ if model_filter(row[1])]
        if not log_:
            return [f"{tag}no probe requests observed (vacuous SLO run)"]
        out: list[str] = []
        if judge_after_ms:
            ramp_end = min(t for t, *_ in log_) + judge_after_ms
            log_ = [row for row in log_ if row[0] >= ramp_end]
            if not log_:
                return [
                    f"{tag}no probe requests after the {judge_after_ms}ms "
                    "detection ramp (vacuous SLO run)"
                ]
        base = min(t for t, *_ in log_)
        windows: dict[int, list[tuple[float, bool]]] = {}
        for t, _mid, ok, _err, latency_ms in log_:
            windows.setdefault((t - base) // window_ms, []).append(
                (latency_ms, ok)
            )
        evaluated = 0
        for idx in sorted(windows):
            samples = windows[idx]
            if len(samples) < min_requests:
                continue
            evaluated += 1
            at = f"{tag}checkpoint @{base + idx * window_ms}ms"
            lat = sorted(v for v, _ in samples)
            n = len(samples)
            avail = sum(1 for _, ok in samples if ok) / n
            for name, q, want in (
                ("p50", 0.50, obj.p50_ms), ("p95", 0.95, obj.p95_ms),
                ("p99", 0.99, obj.p99_ms),
            ):
                if want is None:
                    continue
                got = _percentile(lat, q)
                if got > want:
                    out.append(
                        f"{at}: {name}={got:.0f}ms > {want:g}ms "
                        f"(n={n}, spec {spec!r})"
                    )
            if obj.availability is not None and avail < obj.availability:
                out.append(
                    f"{at}: availability={avail:.4f} < "
                    f"{obj.availability:g} (n={n})"
                )
        if not evaluated:
            out.append(
                f"{tag}no checkpoint reached {min_requests} requests "
                "(vacuous SLO run)"
            )
        return out

    return check


def check_all(
    cluster: "SimCluster",
    dead_since_ms: dict[str, int],
    now_ms: int,
    grace_ms: int,
) -> dict[str, list[str]]:
    """name -> violations (empty list = PASS); stable key order."""
    return {
        "demanded_models_served": demanded_models_served(cluster),
        "no_dead_placements": no_dead_placements(
            cluster, dead_since_ms, now_ms, grace_ms
        ),
        "registry_cache_convergence": registry_cache_convergence(cluster),
        "vmodel_resolution_acyclic": vmodel_resolution_acyclic(cluster),
        "cache_weight_consistent": cache_weight_consistent(cluster),
        "host_claims_converged": host_claims_converged(cluster),
        "draining_deregistered": draining_deregistered(cluster),
        "copy_bounds": copy_bounds(cluster),
        "group_complete_or_absent": group_complete_or_absent(cluster),
    }
