"""Closed-loop macro workload: millions of synthetic users against a
``ModeledFleet`` on one ``EventLoop``.

The generator is slot-batched — the macro perf strategy. Instead of an
event per request (billions per simulated day), each virtual slot
(default 10 s) draws one Poisson arrival count from the closed-loop
rate, splits it across models with one seeded multinomial over the
Zipf popularity vector, and routes each model's count as a flow
(``ModeledFleet.route_slot``). Latencies come back as (latency, count)
aggregate pairs and land in per-window per-class histograms quantized
to 0.5 ms buckets — memory is O(windows x classes x distinct buckets),
not O(requests), and weighted nearest-rank percentiles over the merged
histogram match per-request percentiles to bucket width.

Closed loop: the offered rate is ``users x slot / (think + latency)``
— latency feedback throttles arrivals exactly like real users waiting
on responses, so overload self-limits the way production traffic does
(and sheds return fast, so admission INCREASES offered rate — the
retry-pressure effect the admission matrix cells exercise).

Traffic shapes compose declaratively on ``WorkloadSpec``:

* diurnal: a 24-bucket hourly profile (linear interpolation between
  buckets) — the PR-15 forecaster's native resolution, exercised over
  a full virtual day by the macro headline.
* flash crowds: a seeded band of mid-popularity models gets its weight
  multiplied for a window — the scale-up burst that separates burn
  doubling from legacy +1 stepping.
* mass churn: a seeded fraction of models is unregistered and replaced
  by fresh ids that INHERIT the old popularity — the "new model
  version goes instantly hot" cold-load storm.
* fault overlays: kill / partition / heal a seeded fraction of the
  fleet at a virtual time.

Determinism: every draw comes from one ``numpy.random.default_rng``
seeded at construction; time comes only from the EventLoop. The same
(spec, seed) replays bit-for-bit — ``MacroStats.digest()`` is the
witness (pinned in tier-1 by tests/test_bench_macro.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from typing import Optional

import numpy as np

from modelmesh_tpu.sim.engine import EventLoop, FleetConfig, ModeledFleet

__all__ = [
    "FlashCrowd",
    "MassChurn",
    "FaultOverlay",
    "WorkloadSpec",
    "MacroStats",
    "WorkloadGenerator",
    "run_macro",
    "DEFAULT_DIURNAL",
]

# Hourly demand multipliers (fraction of peak), one per hour-of-day:
# overnight trough, morning ramp, lunch plateau, evening peak — the
# usual consumer-traffic shape, normalized to max 1.0.
DEFAULT_DIURNAL = (
    0.30, 0.25, 0.22, 0.20, 0.20, 0.24,
    0.32, 0.45, 0.60, 0.72, 0.80, 0.85,
    0.88, 0.86, 0.82, 0.80, 0.82, 0.88,
    0.95, 1.00, 0.98, 0.85, 0.60, 0.42,
)


@dataclasses.dataclass(frozen=True)
class FlashCrowd:
    at_ms: int
    duration_ms: int
    boost: float = 30.0   # weight multiplier on the target band
    n_models: int = 4     # seeded picks from the mid-popularity band


@dataclasses.dataclass(frozen=True)
class MassChurn:
    at_ms: int
    frac: float = 0.2     # fraction of models replaced by fresh ids


@dataclasses.dataclass(frozen=True)
class FaultOverlay:
    at_ms: int
    kind: str             # "kill" | "partition" | "heal_all"
    frac: float = 0.1     # fleet fraction targeted (kill/partition)


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    users: int = 100_000
    models: int = 1024
    zipf_s: float = 1.1
    think_ms: float = 20_000.0
    day_s: int = 86_400
    slot_ms: int = 10_000
    window_ms: int = 60_000
    diurnal: tuple = DEFAULT_DIURNAL
    # (class_name, fraction of models) in SLO-priority order; fractions
    # are cumulative-assigned over the seeded model permutation.
    classes: tuple = (("default", 1.0),)
    flash: tuple = ()
    churn: tuple = ()
    faults: tuple = ()
    # Judge slo_attained only after this ramp (cold start + first
    # control cadences are not steady state).
    judge_after_ms: int = 300_000


class MacroStats:
    """Slot-aggregated outcome accounting: per-(window, class) latency
    histograms plus conservation counters. All integers — no request
    identity survives, only distributions (the macro memory contract).
    """

    BUCKET_PER_MS = 2  # 0.5 ms quantization

    def __init__(self, window_ms: int):
        self.window_ms = window_ms
        # (window_idx, cls) -> {"lat": {bucket: count}, "shed": n,
        #                       "failed": n, "served": n}
        self.windows: dict = {}
        self.offered = 0
        self.served = 0
        self.shed = 0
        self.failed = 0

    def observe(self, rel_ms: int, cls: str, res) -> None:
        """Fold one RouteResult into the window grid."""
        n = res.served + res.shed + res.failed
        self.offered += n
        self.served += res.served
        self.shed += res.shed
        self.failed += res.failed
        key = (rel_ms // self.window_ms, cls)
        w = self.windows.get(key)
        if w is None:
            w = self.windows[key] = {
                "lat": {}, "shed": 0, "failed": 0, "served": 0,
            }
        w["shed"] += res.shed
        w["failed"] += res.failed
        w["served"] += res.served
        lat = w["lat"]
        q = self.BUCKET_PER_MS
        for latency_ms, count in res.lat:
            b = int(latency_ms * q)
            lat[b] = lat.get(b, 0) + count

    # -- reductions --------------------------------------------------------

    def percentile(self, p: float, cls: Optional[str] = None) -> float:
        """Weighted nearest-rank percentile (ms) over served requests,
        merged across windows (optionally one class)."""
        merged: dict[int, int] = {}
        for (_, c), w in self.windows.items():
            if cls is not None and c != cls:
                continue
            for b, n in w["lat"].items():
                merged[b] = merged.get(b, 0) + n
        total = sum(merged.values())
        if total == 0:
            return 0.0
        rank = max(int(math.ceil(p / 100.0 * total)), 1)
        acc = 0
        for b in sorted(merged):
            acc += merged[b]
            if acc >= rank:
                return b / self.BUCKET_PER_MS
        return max(merged) / self.BUCKET_PER_MS

    def slo_attained(self, cls: str, bound_ms: Optional[float],
                     good_target: float, judge_after_ms: int) -> float:
        """Fraction of post-ramp windows whose good-event fraction
        (served under the latency bound, over ALL offered including
        sheds and failures) meets the class's implied target — the
        windowed twin of invariants.slo_attained."""
        judged = attained = 0
        first_win = judge_after_ms // self.window_ms
        q = self.BUCKET_PER_MS
        for (win, c), w in sorted(self.windows.items()):
            if c != cls or win < first_win:
                continue
            total = w["served"] + w["shed"] + w["failed"]
            if total == 0:
                continue
            if bound_ms is None:
                good = w["served"]
            else:
                cut = int(bound_ms * q)
                good = sum(n for b, n in w["lat"].items() if b <= cut)
            judged += 1
            if good / total >= good_target:
                attained += 1
        return attained / judged if judged else 0.0

    def digest(self) -> str:
        """Canonical sha256 over every window histogram + totals: the
        bit-for-bit replay witness."""
        canon = {
            "offered": self.offered, "served": self.served,
            "shed": self.shed, "failed": self.failed,
            "windows": [
                [win, c, sorted(w["lat"].items()),
                 w["shed"], w["failed"], w["served"]]
                for (win, c), w in sorted(self.windows.items())
            ],
        }
        return hashlib.sha256(
            json.dumps(canon, separators=(",", ":")).encode()
        ).hexdigest()


class WorkloadGenerator:
    """Drives one ``ModeledFleet`` through one ``WorkloadSpec`` on the
    fleet's EventLoop. Construct, ``start()``, then run the loop to
    ``t0 + day_s*1000``."""

    def __init__(self, loop: EventLoop, fleet: ModeledFleet,
                 spec: WorkloadSpec, seed: int = 0):
        self.loop = loop
        self.fleet = fleet
        self.spec = spec
        self.rng = np.random.default_rng(seed)
        self.t0 = loop.now_ms
        self.stats = MacroStats(spec.window_ms)
        self.requests_simulated = 0
        # -- popularity: Zipf over a seeded permutation ---------------------
        m = spec.models
        ranks = np.arange(1, m + 1, dtype=np.float64)
        w = 1.0 / ranks ** spec.zipf_s
        self.base_weights = w / w.sum()
        # model index -> current id (churn swaps ids in place, so the
        # replacement inherits the slot's popularity).
        self.ids = [f"mm-{seed}-{i:05d}" for i in range(m)]
        # class per index: spec fractions over a seeded permutation, so
        # class membership is popularity-independent.
        perm = self.rng.permutation(m)
        self.cls = [""] * m
        start = 0
        for cname, frac in spec.classes:
            end = m if (cname == spec.classes[-1][0]) else min(
                m, start + int(round(frac * m))
            )
            for j in perm[start:end]:
                self.cls[int(j)] = cname
            start = end
        for j in perm[start:]:
            self.cls[int(j)] = spec.classes[-1][0]
        for i, mid in enumerate(self.ids):
            fleet.register(mid, self.cls[i])
        # flash targets: seeded picks from the mid-popularity band
        # (ranks m//8 .. m//2): popular enough to matter, cold enough
        # that the burst forces real scale-up.
        self._flash_targets: list[np.ndarray] = [
            self.rng.choice(
                np.arange(m // 8, max(m // 2, m // 8 + 1)),
                size=min(f.n_models, m), replace=False,
            )
            for f in spec.flash
        ]
        self._lat_ewma = fleet.cfg.service_base_ms
        #: shared-ok: single-threaded EventLoop state — slot callbacks run on the loop thread
        self._slot_ev = None
        for f in spec.faults:
            loop.schedule_at(self.t0 + f.at_ms, self._fault, f)
        for c in spec.churn:
            loop.schedule_at(self.t0 + c.at_ms, self._churn, c)

    def warm_start(self) -> None:
        """Pre-place one copy per model, most popular first, until the
        fleet is ~60% full — the steady-state cache a real fleet would
        have at the start of a day."""
        cap = sum(
            i.capacity_bytes for i in self.fleet.instances if i.alive
        )
        order = np.argsort(-self.base_weights, kind="stable")
        for j in order:
            mid = self.ids[int(j)]
            used = sum(
                i.used_bytes for i in self.fleet.instances if i.alive
            )
            if cap and used / cap > 0.6:
                break
            self.fleet.add_copy(mid)

    def start(self) -> None:
        self._slot_ev = self.loop.schedule_at(
            self.t0 + self.spec.slot_ms, self._slot
        )

    # -- per-slot hot path -------------------------------------------------

    def _diurnal_factor(self, rel_ms: int) -> float:
        prof = self.spec.diurnal
        h = (rel_ms / 3_600_000.0) % 24.0
        i = int(h) % 24
        frac = h - int(h)
        return prof[i] * (1.0 - frac) + prof[(i + 1) % 24] * frac

    def _weights(self, rel_ms: int) -> np.ndarray:
        w = self.base_weights
        boosted = None
        for f, targets in zip(self.spec.flash, self._flash_targets):
            if f.at_ms <= rel_ms < f.at_ms + f.duration_ms:
                if boosted is None:
                    boosted = w.copy()
                boosted[targets] *= f.boost
        if boosted is None:
            return w
        return boosted / boosted.sum()

    def _slot(self) -> None:
        spec = self.spec
        now = self.loop.now_ms
        rel = now - self.t0
        # Closed loop: each user cycles think -> request -> response.
        rate_per_user = spec.slot_ms / (spec.think_ms + self._lat_ewma)
        mean = spec.users * rate_per_user * self._diurnal_factor(rel)
        arrivals = int(self.rng.poisson(mean)) if mean > 0 else 0
        if arrivals > 0:
            counts = self.rng.multinomial(arrivals, self._weights(rel))
            fleet = self.fleet
            stats = self.stats
            observe = stats.observe
            class_bad: dict[str, list] = {}
            lat_sum = 0.0
            lat_n = 0
            nz = np.nonzero(counts)[0]
            for j in nz:
                k = int(counts[j])
                res = fleet.route_slot(self.ids[j], k, spec.slot_ms)
                cls = self.cls[j]
                observe(rel, cls, res)
                bound = self._bound(cls)
                # Burn-window feed EXCLUDES sheds: admission rejects at
                # the door, before the real SloTracker ever records the
                # request — counting sheds as burn would make shedding
                # self-sustaining (shed -> burn >= 1 -> shed forever).
                # slo_attained still counts them (user-visible misses).
                bad = res.failed
                for latency_ms, c in res.lat:
                    lat_sum += latency_ms * c
                    lat_n += c
                    if bound is not None and latency_ms > bound:
                        bad += c
                agg = class_bad.get(cls)
                if agg is None:
                    class_bad[cls] = [bad, res.served + res.failed]
                else:
                    agg[0] += bad
                    agg[1] += res.served + res.failed
            self.requests_simulated += arrivals
            fleet.end_slot()
            for cls in sorted(class_bad):
                bad, total = class_bad[cls]
                fleet.observe_slot(cls, now, bad, total)
            if lat_n:
                # EWMA latency feedback, tau ~= 3 slots.
                alpha = 1.0 - math.exp(-1.0 / 3.0)
                self._lat_ewma += alpha * (lat_sum / lat_n - self._lat_ewma)
        if rel + spec.slot_ms <= spec.day_s * 1000:
            self._slot_ev = self.loop.schedule_at(
                now + spec.slot_ms, self._slot
            )

    def _bound(self, cls: str) -> Optional[float]:
        obj = self.fleet.objectives(cls)
        return obj.latency_bound_ms if obj is not None else None

    # -- overlays ----------------------------------------------------------

    def _fault(self, f: FaultOverlay) -> None:
        insts = self.fleet.instances
        if f.kind == "heal_all":
            for inst in insts:
                self.fleet.heal(inst.iid)
            return
        n = max(1, int(round(f.frac * len(insts))))
        # Never target pod-0: the modeled leader must survive (the
        # leader-loss case is a scripted full-fidelity scenario).
        pool = np.arange(1, len(insts))
        targets = self.rng.choice(pool, size=min(n, len(pool)), replace=False)
        for t in sorted(int(x) for x in targets):
            if f.kind == "kill":
                self.fleet.kill(insts[t].iid)
            elif f.kind == "partition":
                self.fleet.partition(insts[t].iid)
            else:
                raise ValueError(f"unknown fault overlay kind {f.kind!r}")

    def _churn(self, c: MassChurn) -> None:
        m = self.spec.models
        n = max(1, int(round(c.frac * m)))
        picks = self.rng.choice(np.arange(m), size=n, replace=False)
        for j in sorted(int(x) for x in picks):
            old = self.ids[j]
            self.fleet.unregister(old)
            new = old + "+"  # version bump; popularity slot unchanged
            self.ids[j] = new
            self.fleet.register(new, self.cls[j])

    # -- result ------------------------------------------------------------

    def summary(self) -> dict:
        spec = self.spec
        stats = self.stats
        out = {
            "users": spec.users,
            "models": spec.models,
            "virtual_day_s": spec.day_s,
            "offered": stats.offered,
            "served": stats.served,
            "shed": stats.shed,
            "failed": stats.failed,
            "p50_ms": stats.percentile(50.0),
            "p99_ms": stats.percentile(99.0),
            "digest": stats.digest(),
            "classes": {},
            "fleet": dict(self.fleet.counters),
        }
        for cname, _ in spec.classes:
            obj = self.fleet.objectives(cname)
            out["classes"][cname] = {
                "p99_ms": stats.percentile(99.0, cname),
                "slo_attained": stats.slo_attained(
                    cname,
                    obj.latency_bound_ms if obj else None,
                    obj.good_target if obj else 1.0,
                    spec.judge_after_ms,
                ),
            }
        return out


def run_macro(
    spec: WorkloadSpec,
    n_pods: int,
    fleet_config: Optional[FleetConfig] = None,
    seed: int = 0,
) -> dict:
    """One macro run, self-contained: build loop + fleet + generator,
    warm-start, run the virtual day, return the summary dict (with the
    engine's event count — callers add wall-clock around this)."""
    loop = EventLoop()
    fleet = ModeledFleet(loop, n_pods, fleet_config, seed=seed)
    gen = WorkloadGenerator(loop, fleet, spec, seed=seed)
    gen.warm_start()
    gen.start()
    loop.run(gen.t0 + spec.day_s * 1000)
    out = gen.summary()
    out["pods"] = n_pods
    out["engine_events"] = loop.events_processed
    out["requests_simulated"] = gen.requests_simulated
    out["conservation_violations"] = (
        fleet.bytes_conservation_violations()
    )
    offered = out["offered"]
    if offered != out["served"] + out["shed"] + out["failed"]:
        out["conservation_violations"].append(
            f"request conservation: offered={offered} != "
            f"served+shed+failed"
        )
    return out
