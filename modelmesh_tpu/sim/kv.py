"""Fault-injectable KV layer for the cluster simulator.

``SimKV`` wraps a (clock-threaded) ``InMemoryKV`` and hands each simulated
instance a per-instance facade (``for_instance``). Faults are injected at
the facade boundary — exactly where a real deployment's network sits:

- **partitions**: a blacked-out instance's ops raise ``ConnectionError``;
  its watch deliveries queue behind a paused per-facade worker and drain
  IN ORDER on heal (watch disconnect + catch-up semantics);
- **per-op latency**: virtual-time sleeps drawn from a seeded hash of
  (instance, op, key, per-key sequence);
- **CAS-conflict amplification**: guarded txns spuriously fail with
  probability ``cas_conflict_p`` (callers must re-read and retry — the
  contract every CAS loop in the codebase claims to honor);
- **watch delay / bounded reorder**: deliveries are held for a virtual
  delay; adjacent deliveries may swap ONLY when they share no key, so
  per-key order — the invariant real watch streams guarantee, and the
  one ``TableView``'s unconditional DELETE apply relies on — is never
  violated;
- **session expiry**: ``expire_instance_session`` revokes the lease under
  an instance's ephemeral advertisement out from under its SessionNode.

Determinism: the scenario TRACE (schedule + verdicts) is bit-for-bit
replayable from the seed. Fault draws are keyed on (seed, instance, op,
key, that key's op sequence) — independent of cross-key thread
interleavings, so a replay perturbs only draws whose own key saw a
genuinely racy op order; they are NOT hashed from a shared counter whose
value depends on unrelated threads' scheduling.
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import Iterable, Optional, Sequence

from modelmesh_tpu.kv.memory import InMemoryKV
from modelmesh_tpu.kv.store import (
    Compare,
    KeyValue,
    KVStore,
    Op,
    WatchCallback,
    WatchHandle,
)
from modelmesh_tpu.utils import clock as _clock


class SimKVConfig:
    def __init__(
        self,
        latency_ms: float = 0.0,
        latency_jitter_ms: float = 0.0,
        cas_conflict_p: float = 0.0,
        watch_delay_ms: float = 0.0,
        watch_reorder_p: float = 0.0,
    ):
        self.latency_ms = latency_ms
        self.latency_jitter_ms = latency_jitter_ms
        self.cas_conflict_p = cas_conflict_p
        self.watch_delay_ms = watch_delay_ms
        self.watch_reorder_p = watch_reorder_p


def _unit_hash(*parts) -> float:
    """Deterministic [0,1) draw from the identity of an operation."""
    h = hashlib.blake2b(
        "|".join(str(p) for p in parts).encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big") / 2**64


class _SimWatchHandle(WatchHandle):
    def __init__(self, inner: WatchHandle):
        self._inner = inner

    def cancel(self) -> None:
        self._inner.cancel()


class _InstanceKV(KVStore):
    """Per-instance view of the shared SimKV: the injection boundary."""

    def __init__(self, sim: "SimKV", owner: str):
        self.sim = sim
        self.owner = owner
        # op-identity sequence numbers feeding the fault draws.
        self._op_counts: dict[tuple, int] = {}  #: guarded-by: _lock
        self._lock = threading.Lock()
        # Delayed/held watch deliveries: ONE FIFO per facade, drained by
        # one worker — global per-facade order is preserved (so per-key
        # order is too); the reorder fault swaps only key-disjoint
        # neighbors at enqueue time.
        #: guarded-by: _delivery_cv
        self._queue: collections.deque = collections.deque()
        self._worker: Optional[threading.Thread] = None  #: guarded-by: _delivery_cv
        self._dispatching = False  #: guarded-by: _delivery_cv
        self._closed = False  #: guarded-by: _delivery_cv
        self._delivery_cv = threading.Condition()

    # -- fault plumbing ----------------------------------------------------

    def _draw(self, op: str, key: str) -> float:
        with self._lock:
            n = self._op_counts.get((op, key), 0) + 1
            self._op_counts[(op, key)] = n
        return _unit_hash(self.sim.seed, self.owner, op, key, n)

    def _before_op(self, op: str, key: str = "") -> None:
        self.sim.check_partition(self.owner)
        self.sim.check_hold(self.owner, op, key)
        cfg = self.sim.config
        if cfg.latency_ms or cfg.latency_jitter_ms:
            extra = cfg.latency_jitter_ms * self._draw("lat:" + op, key)
            _clock.sleep((cfg.latency_ms + extra) / 1000.0)

    def _amplify_cas(self, compares: Sequence[Compare]) -> bool:
        cfg = self.sim.config
        if not compares or cfg.cas_conflict_p <= 0:
            return False
        return self._draw("cas", compares[0].key) < cfg.cas_conflict_p

    # -- reads -------------------------------------------------------------

    def get(self, key: str) -> Optional[KeyValue]:
        self._before_op("get", key)
        return self.sim.inner.get(key)

    def range(self, prefix: str) -> list[KeyValue]:
        self._before_op("range", prefix)
        return self.sim.inner.range(prefix)

    def range_from(self, prefix: str, start_key: str, limit: int):
        self._before_op("range_from", prefix)
        return self.sim.inner.range_from(prefix, start_key, limit)

    # -- writes ------------------------------------------------------------

    def put(self, key: str, value: bytes, lease: int = 0) -> KeyValue:
        self._before_op("put", key)
        return self.sim.inner.put(key, value, lease)

    def delete(self, key: str) -> bool:
        self._before_op("delete", key)
        return self.sim.inner.delete(key)

    def txn(
        self,
        compares: Iterable[Compare],
        on_success: Iterable[Op],
        on_failure: Iterable[Op] = (),
    ) -> tuple[bool, list[KeyValue]]:
        compares = list(compares)
        self._before_op("txn")
        # Hold gates match on the guarded key (the latency draw above
        # keeps its keyless identity so armed holds don't perturb the
        # seeded fault schedule of unrelated ops).
        if compares:
            self.sim.check_hold(self.owner, "txn", compares[0].key)
        if self._amplify_cas(compares):
            # Spurious conflict: by the CAS contract the caller re-reads
            # and retries; a correct caller converges, a broken one is
            # exactly what this fault exists to expose.
            return False, []
        return self.sim.inner.txn(compares, on_success, on_failure)

    # -- watch -------------------------------------------------------------

    def watch(
        self,
        prefix: str,
        callback: WatchCallback,
        start_rev: Optional[int] = None,
    ) -> WatchHandle:
        inner_handle = self.sim.inner.watch(
            prefix, lambda events: self._deliver(callback, events), start_rev
        )
        return _SimWatchHandle(inner_handle)

    def _deliver(self, callback: WatchCallback, events) -> None:
        """Runs on the inner store's (single) dispatch thread, so enqueue
        order is the store's event order. Fast path: nothing armed and no
        backlog — dispatch inline, exact real-store behavior. Otherwise
        queue behind the facade worker (partitioned deliveries just wait
        there until heal)."""
        cfg = self.sim.config
        partitioned = self.sim.is_partitioned(self.owner)
        delay = cfg.watch_delay_ms
        with self._delivery_cv:
            if (
                not partitioned
                and delay <= 0
                and not self._queue
                and not self._dispatching
            ):
                inline = True
            else:
                inline = False
                fire_at = _clock.get_clock().now_ms() + max(0.0, delay)
                entry = (fire_at, callback, list(events))
                if cfg.watch_reorder_p > 0 and self._queue:
                    keys_new = {ev.kv.key for ev in events}
                    tail = self._queue[-1]
                    keys_tail = {ev.kv.key for ev in tail[2]}
                    # Bounded reorder: swap with the neighbor ONLY when
                    # no key is shared — per-key order is sacrosanct.
                    if not (keys_new & keys_tail) and self._draw(
                        "reorder", min(keys_new, default="")
                    ) < cfg.watch_reorder_p:
                        self._queue.pop()
                        self._queue.append(entry)
                        entry = tail
                self._queue.append(entry)
                if self._worker is None:
                    self._worker = threading.Thread(
                        target=self._drain,
                        name=f"watch-queue-{self.owner}",
                        daemon=True,
                    )
                    self._worker.start()
                self._delivery_cv.notify_all()
        if inline:
            self._safe_dispatch(callback, events)

    def _drain(self) -> None:
        """Facade delivery worker: strictly FIFO, paused while the owner
        is partitioned, virtual-delay aware."""
        clock = _clock.get_clock()
        while True:
            with self._delivery_cv:
                entry = None
                while entry is None:
                    if self._closed:
                        return
                    if self._queue and not self.sim.is_partitioned(
                        self.owner
                    ):
                        fire_at, cb, evs = self._queue[0]
                        now = clock.now_ms()
                        if now >= fire_at:
                            self._queue.popleft()
                            self._dispatching = True
                            entry = (cb, evs)
                            continue
                        clock.cond_wait(
                            self._delivery_cv, (fire_at - now) / 1000.0
                        )
                    else:
                        # Empty, or partitioned: wait for an enqueue /
                        # heal kick / clock movement.
                        clock.cond_wait(self._delivery_cv, 60.0)
            try:
                self._safe_dispatch(*entry)
            finally:
                with self._delivery_cv:
                    self._dispatching = False
                    self._delivery_cv.notify_all()

    @staticmethod
    def _safe_dispatch(callback, events) -> None:
        try:
            callback(events)
        except Exception:  # noqa: BLE001 — watcher bugs must not kill sim
            import traceback

            traceback.print_exc()

    def kick(self) -> None:
        """Wake the delivery worker (heal, teardown)."""
        with self._delivery_cv:
            self._delivery_cv.notify_all()

    # -- leases ------------------------------------------------------------

    def lease_grant(self, ttl_s: float) -> int:
        self._before_op("lease_grant")
        return self.sim.inner.lease_grant(ttl_s)

    def lease_keepalive(self, lease_id: int) -> bool:
        self._before_op("lease_keepalive")
        return self.sim.inner.lease_keepalive(lease_id)

    def lease_revoke(self, lease_id: int) -> None:
        self._before_op("lease_revoke")
        self.sim.inner.lease_revoke(lease_id)

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        with self._delivery_cv:
            self._closed = True
            self._delivery_cv.notify_all()

    def wait_idle(self, timeout: float = 5.0) -> None:
        self.sim.inner.wait_idle(timeout)


class SimKV:
    """Shared fault-injection state over one InMemoryKV."""

    def __init__(
        self,
        seed: int = 0,
        config: Optional[SimKVConfig] = None,
        inner: Optional[InMemoryKV] = None,
    ):
        self.seed = seed
        self.config = config or SimKVConfig()
        self.inner = inner or InMemoryKV(sweep_interval_s=0.5)
        #: guarded-by: _lock
        self._partitioned: set[str] = set()
        #: guarded-by: _lock
        self._facades: dict[str, _InstanceKV] = {}
        # Write-hold gates: (owner, key-substring, release event). A
        # matching write BLOCKS (wall, not virtual) until released —
        # the deterministic way to model "this async mutation lands
        # arbitrarily late" (e.g. an eviction's deregister CAS racing
        # the quiesce). Released wholesale on close().
        #: guarded-by: _lock
        self._holds: list[tuple[str, str, threading.Event]] = []
        self._lock = threading.Lock()

    def for_instance(self, instance_id: str) -> KVStore:
        with self._lock:
            facade = self._facades.get(instance_id)
            if facade is None:
                facade = self._facades[instance_id] = _InstanceKV(
                    self, instance_id
                )
            return facade

    # -- partitions --------------------------------------------------------

    def partition(self, instance_id: str) -> None:
        with self._lock:
            self._partitioned.add(instance_id)

    def heal(self, instance_id: str) -> None:
        with self._lock:
            self._partitioned.discard(instance_id)
            facade = self._facades.get(instance_id)
        if facade is not None:
            facade.kick()  # the paused worker drains its backlog in order

    def is_partitioned(self, instance_id: str) -> bool:
        with self._lock:
            return instance_id in self._partitioned

    def check_partition(self, instance_id: str) -> None:
        if self.is_partitioned(instance_id):
            raise ConnectionError(
                f"simulated partition: {instance_id} cannot reach the KV"
            )

    # -- write-hold gates --------------------------------------------------

    def hold_writes(self, instance_id: str, key_substr: str) -> threading.Event:
        """Arm a gate: ``instance_id``'s writes touching a key containing
        ``key_substr`` block until the returned event is set."""
        ev = threading.Event()
        with self._lock:
            self._holds.append((instance_id, key_substr, ev))
        return ev

    def release_holds(self) -> None:
        with self._lock:
            holds, self._holds = self._holds, []
        for _, _, ev in holds:
            ev.set()

    def check_hold(self, instance_id: str, op: str, key: str) -> None:
        if not self._holds or not key:
            return
        if op not in ("put", "delete", "txn"):
            return
        with self._lock:
            holds = list(self._holds)
        for owner, sub, ev in holds:
            if owner == instance_id and sub in key:
                ev.wait()

    # -- session faults ----------------------------------------------------

    def expire_instance_session(self, session_key: str) -> bool:
        """Revoke the lease holding ``session_key`` (an instance's
        ephemeral advertisement) — simulated session expiry: the owner's
        next keepalive finds the lease gone and re-establishes."""
        kv = self.inner.get(session_key)
        if kv is None or not kv.lease:
            return False
        self.inner.lease_revoke(kv.lease)
        return True

    def close(self) -> None:
        self.release_holds()
        with self._lock:
            facades = list(self._facades.values())
        for facade in facades:
            facade.close()
        self.inner.close()
