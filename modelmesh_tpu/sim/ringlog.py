"""Bounded append-only ring for sim observation logs.

``SimCluster.request_log`` and ``batch_dispatches`` were plain lists:
one row per probe request / runtime dispatch, kept for the whole run.
Fine at scripted-scenario scale (hundreds of probes); a memory blowup
at macro scale, where a closed-loop day of a million synthetic users
offers hundreds of millions of requests (sim/workload.py aggregates
those — but the full-fidelity pods bridged into the same loop still
log per-request here, and a long exploration sweep accumulates too).

``RingLog`` is the same convention as ``observability/flightrec.py``
scaled down to one stripe: a bounded ring with a monotonically
increasing total-order sequence. Consumers (sim/invariants.py,
scenario ``extra_checks``, tests) only iterate / ``len()`` / truth-test
the log, so the ring is a drop-in replacement for the list; ``total``
and ``dropped`` expose whether the window is complete — the SLO
invariant's "observed-traffic witness" is explicit about truncation
instead of silently unbounded.

Capacity comes from ``MM_SIM_LOG_EVENTS`` (0 = unbounded, the
pre-ring behavior, for tests that assert over a whole run's traffic).
A single lock suffices: appenders are scenario worker threads at
human-scale rates, not the macro hot loop (which never touches this).
"""

from __future__ import annotations

import collections
import threading
from typing import Iterator, Optional


class RingLog:
    """Bounded, thread-safe, append-only event ring.

    Iteration yields the retained tail in append (= total) order as a
    point-in-time snapshot; ``seq`` of the i-th yielded item is
    ``total - len(self) + i``.
    """

    __slots__ = ("_lock", "_buf", "_total", "capacity")

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            from modelmesh_tpu.utils import envs

            capacity = envs.get_int("MM_SIM_LOG_EVENTS")
        self.capacity = max(int(capacity), 0)
        self._lock = threading.Lock()
        self._buf: collections.deque = collections.deque(
            maxlen=self.capacity or None
        )  #: guarded-by: _lock
        self._total = 0  #: guarded-by: _lock

    def append(self, item) -> int:
        """Record one event; returns its total-order sequence number."""
        with self._lock:
            seq = self._total
            self._total += 1
            self._buf.append(item)
            return seq

    @property
    def total(self) -> int:
        """Events ever appended (retained + dropped)."""
        with self._lock:
            return self._total

    @property
    def dropped(self) -> int:
        """Events the bound evicted (0 means the window is complete)."""
        with self._lock:
            return self._total - len(self._buf)

    def snapshot(self) -> list:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def __iter__(self) -> Iterator:
        return iter(self.snapshot())

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def __bool__(self) -> bool:
        return len(self) > 0
