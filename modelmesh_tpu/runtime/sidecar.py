"""Sidecar model-runtime client: ModelLoader over the gRPC runtime SPI.

The coupling layer to an external model-server container, capability-parity
with the reference's SidecarModelMesh external-loader path
(SidecarModelMesh.java): startup status polling until READY
(waitForModelServerStart :597), load/unload via the SPI with ref-counted
pairing so out-of-order load/unload cancel out (:838-868), a background
unload retry queue so failed unloads don't silently leak serving memory
(:129, :876-944), and inference passthrough to the serving channel with the
model id in metadata.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Optional

import grpc

from modelmesh_tpu.observability.tracing import outgoing_headers
from modelmesh_tpu.utils.grpcopts import message_size_options
from modelmesh_tpu.proto import mesh_runtime_pb2 as rpb
from modelmesh_tpu.runtime import grpc_defs
from modelmesh_tpu.utils.clock import get_clock
from modelmesh_tpu.runtime.spi import (
    LoadedModel,
    LocalInstanceParams,
    ModelInfo,
    ModelLoader,
    ModelLoadException,
)

log = logging.getLogger(__name__)

UNLOAD_MAX_RETRIES = 90           # reference: ~15 min at 10 s intervals
UNLOAD_RETRY_INTERVAL_S = 10.0


class SidecarRuntime(ModelLoader[str]):
    """gRPC-backed loader. The runtime handle is the model id itself; actual
    inference goes through ``call_model`` on the serving channel."""

    def __init__(
        self,
        target: str = "localhost:8085",
        startup_timeout_s: float = 120.0,
        poll_interval_s: float = 1.0,
        channel: Optional[grpc.Channel] = None,
        tls=None,
    ):
        """``tls`` (serving.tls.TlsConfig) secures the runtime link — needed
        whenever the model server isn't a loopback/UDS sidecar."""
        if channel is None:
            if tls is not None:
                from modelmesh_tpu.serving.tls import secure_channel

                channel = secure_channel(target, tls)
            else:
                channel = grpc.insecure_channel(
                    target, options=message_size_options()
                )
        self._channel = channel
        self._stub = grpc_defs.make_stub(
            self._channel, grpc_defs.RUNTIME_SERVICE, grpc_defs.RUNTIME_METHODS
        )
        self._startup_timeout_s = startup_timeout_s
        self._poll_interval_s = poll_interval_s
        # Ref-counted load state: +1 per load, -1 per unload; a model is
        # unloaded from the runtime only when the count returns to 0, so
        # out-of-order load/unload pairs cancel (reference :838-868).
        self._load_counts: dict[str, int] = {}
        self._counts_lock = threading.Lock()
        self._unload_queue: "queue.Queue[tuple[str, int]]" = queue.Queue()
        self._closed = threading.Event()
        self._unload_thread = threading.Thread(
            target=self._unload_retry_loop, name="unload-retry", daemon=True
        )
        self._unload_thread.start()
        self._params: Optional[LocalInstanceParams] = None

    # -- SPI ------------------------------------------------------------------

    def startup(self) -> LocalInstanceParams:
        clock = get_clock()
        deadline = clock.monotonic() + self._startup_timeout_s
        last_err: Optional[str] = None
        while clock.monotonic() < deadline:
            try:
                st = self._stub.RuntimeStatus(rpb.RuntimeStatusRequest())
                if st.status == rpb.RuntimeStatusResponse.READY:
                    self._params = LocalInstanceParams(
                        capacity_bytes=st.capacity_bytes,
                        load_concurrency=st.load_concurrency or 8,
                        load_timeout_ms=st.load_timeout_ms or 240_000,
                        default_model_size_bytes=st.default_model_size_bytes
                        or (1 << 20),
                        limit_model_concurrency=st.limit_model_concurrency,
                    )
                    return self._params
                last_err = rpb.RuntimeStatusResponse.Status.Name(st.status)
            except grpc.RpcError as e:
                last_err = f"{e.code()}: {e.details()}"
            clock.sleep(self._poll_interval_s)
        raise ModelLoadException(
            f"model runtime not ready within {self._startup_timeout_s}s "
            f"(last: {last_err})",
            timeout=True,
        )

    def load(self, model_id: str, info: ModelInfo) -> LoadedModel[str]:
        with self._counts_lock:
            self._load_counts[model_id] = self._load_counts.get(model_id, 0) + 1
            count = self._load_counts[model_id]
        if count > 1:
            # Already loaded in the runtime (re-load paired with a pending
            # unload); just bump the refcount.
            return LoadedModel(handle=model_id)
        try:
            resp = self._stub.LoadModel(
                rpb.LoadModelRequest(
                    model_id=model_id,
                    info=rpb.ModelInfo(
                        model_type=info.model_type,
                        model_path=info.model_path,
                        model_key=info.model_key,
                    ),
                )
            )
        except grpc.RpcError as e:
            with self._counts_lock:
                self._load_counts[model_id] -= 1
                if self._load_counts[model_id] <= 0:
                    del self._load_counts[model_id]
            raise ModelLoadException(
                f"loadModel({model_id}) failed: {e.code()}: {e.details()}",
                timeout=e.code() == grpc.StatusCode.DEADLINE_EXCEEDED,
            ) from e
        return LoadedModel(
            handle=model_id,
            size_bytes=resp.size_bytes,
            max_concurrency=resp.max_concurrency,
        )

    def predict_size(self, model_id: str, info: ModelInfo) -> int:
        try:
            resp = self._stub.PredictModelSize(
                rpb.PredictModelSizeRequest(
                    model_id=model_id,
                    info=rpb.ModelInfo(
                        model_type=info.model_type,
                        model_path=info.model_path,
                        model_key=info.model_key,
                    ),
                )
            )
            return resp.size_bytes
        except grpc.RpcError:
            return 0

    def model_size(self, model_id: str, handle: str) -> int:
        try:
            return self._stub.ModelSize(
                rpb.ModelSizeRequest(model_id=model_id)
            ).size_bytes
        except grpc.RpcError:
            return 0

    def unload(self, model_id: str) -> None:
        with self._counts_lock:
            count = self._load_counts.get(model_id, 0) - 1
            if count > 0:
                self._load_counts[model_id] = count
                return  # paired with an outstanding load; runtime keeps it
            self._load_counts.pop(model_id, None)
        self._try_unload(model_id, attempt=0)

    def _try_unload(self, model_id: str, attempt: int) -> None:
        try:
            # Deadline-bounded: a hung runtime must not wedge the caller —
            # unloads run on the instance's small shared pool, where one
            # unbounded RPC would block every queued unload's capacity
            # accounting. DEADLINE_EXCEEDED lands in the retry queue below.
            self._stub.UnloadModel(
                rpb.UnloadModelRequest(model_id=model_id), timeout=30.0
            )
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return  # already gone
            if attempt + 1 >= UNLOAD_MAX_RETRIES:
                # Capacity is considered lost (reference gives up after ~15
                # min and logs loudly, SidecarModelMesh.java:876-944).
                log.error(
                    "unload of %s failed %d times; capacity presumed lost",
                    model_id, attempt + 1,
                )
                return
            self._unload_queue.put((model_id, attempt + 1))

    def _unload_retry_loop(self) -> None:
        while not self._closed.is_set():
            try:
                model_id, attempt = self._unload_queue.get(timeout=0.5)
            except queue.Empty:
                continue
            if self._closed.wait(UNLOAD_RETRY_INTERVAL_S):
                return
            with self._counts_lock:
                if self._load_counts.get(model_id, 0) > 0:
                    continue  # got re-loaded meanwhile; retry is moot
            self._try_unload(model_id, attempt)

    # -- inference --------------------------------------------------------------

    def call_model(
        self,
        model_id: str,
        full_method: str,
        payload: bytes,
        headers: Optional[list[tuple[str, str]]] = None,
        timeout_s: Optional[float] = None,
        cancel_event=None,
    ) -> bytes:
        """Invoke an arbitrary method on the runtime with the model id header
        (reference ExternalModel.callModel, SidecarModelMesh.java:337-510).
        The trace context rides this hop too (outgoing_headers attaches
        the live trace id + span once), so runtime-side tooling can join
        mesh traces — previously the runtime-SPI hop silently dropped it."""
        md = outgoing_headers(
            [(grpc_defs.MODEL_ID_HEADER, model_id)] + (headers or [])
        )
        call = grpc_defs.raw_method(self._channel, full_method)
        if cancel_event is None:
            return call(payload, metadata=md, timeout=timeout_s)
        return grpc_defs.call_cancellable(
            call, payload, timeout=timeout_s, metadata=md,
            cancel_event=cancel_event,
        )

    def close(self) -> None:
        self._closed.set()
        self._channel.close()

    @property
    def requires_unload(self) -> bool:
        return True
