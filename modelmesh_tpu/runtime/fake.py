"""Fake model runtime: the test backbone (ExampleModelRuntime equivalent).

A real gRPC server implementing the runtime SPI with simulated load times
and sizes, plus an arbitrary-method inference endpoint that echoes a
deterministic "prediction" for whichever model id arrives in metadata.
Fault injection mirrors what the reference's example runtime supports
(example/ExampleModelRuntime.java, SURVEY.md section 4): per-model load
failure, load delay, NOT_FOUND-on-serve (the Triton refresh quirk), and a
fast mode for cheap tests.

Runnable in-process (tests) or as a subprocess:
    python -m modelmesh_tpu.runtime.fake --port 8085
"""

from __future__ import annotations

import argparse
import logging
import threading
from concurrent import futures
from typing import Optional

import grpc

from modelmesh_tpu.utils.clock import get_clock
from modelmesh_tpu.utils.grpcopts import message_size_options
from modelmesh_tpu.proto import mesh_runtime_pb2 as rpb
from modelmesh_tpu.runtime import grpc_defs

log = logging.getLogger(__name__)

PREDICT_METHOD = "/mmtpu.example.Predictor/Predict"

# Model-id prefixes triggering injected faults (tests construct ids).
FAIL_LOAD_PREFIX = "fail-load-"
SLOW_LOAD_PREFIX = "slow-load-"
NOT_FOUND_SERVE_PREFIX = "vanish-"
# max_concurrency=1 models (latency-mode / cancellation tests).
GATED_PREFIX = "gated-"
# predict sleeps (anywhere in the id, composable with gated-).
SLOW_PREDICT_MARK = "slow-predict"


class FakeRuntimeServicer:
    """Implements mmtpu.runtime.ModelRuntime."""

    def __init__(
        self,
        capacity_bytes: int = 512 << 20,
        default_size_bytes: int = 8 << 20,
        load_delay_s: float = 0.0,
        ready_delay_s: float = 0.0,
        load_concurrency: int = 8,
    ):
        self.capacity_bytes = capacity_bytes
        self.default_size_bytes = default_size_bytes
        self.load_delay_s = load_delay_s
        self._clock = get_clock()
        self._ready_at = self._clock.monotonic() + ready_delay_s
        self.load_concurrency = load_concurrency
        self.loaded: dict[str, int] = {}  # model_id -> size
        self.load_count = 0      # successful loads
        self.load_attempts = 0   # LoadModel RPCs incl. injected failures
        self.unload_count = 0
        # Batched-dispatch accounting (predict_batch): batch count and
        # per-batch sizes, so tests can assert the serving layer's
        # micro-batch queue really coalesced concurrent requests.
        self.batch_calls = 0      #: guarded-by: _lock
        self.batch_sizes: list[int] = []  #: guarded-by: _lock
        self._lock = threading.Lock()

    # -- SPI methods ----------------------------------------------------------

    def RuntimeStatus(self, request, context):
        status = (
            rpb.RuntimeStatusResponse.READY
            if self._clock.monotonic() >= self._ready_at
            else rpb.RuntimeStatusResponse.STARTING
        )
        return rpb.RuntimeStatusResponse(
            status=status,
            capacity_bytes=self.capacity_bytes,
            load_concurrency=self.load_concurrency,
            load_timeout_ms=30_000,
            default_model_size_bytes=self.default_size_bytes,
            runtime_version="fake-0.1",
        )

    def LoadModel(self, request, context):
        mid = request.model_id
        with self._lock:
            self.load_attempts += 1
        if mid.startswith(FAIL_LOAD_PREFIX):
            context.abort(grpc.StatusCode.INTERNAL, f"injected load failure: {mid}")
        delay = self.load_delay_s
        if mid.startswith(SLOW_LOAD_PREFIX):
            delay = max(delay, 2.0)
        if delay:
            self._clock.sleep(delay)
        size = self._size_for(mid)
        with self._lock:
            self.loaded[mid] = size
            self.load_count += 1
        return rpb.LoadModelResponse(
            size_bytes=size,
            max_concurrency=1 if mid.startswith(GATED_PREFIX) else 0,
        )

    def UnloadModel(self, request, context):
        with self._lock:
            self.loaded.pop(request.model_id, None)
            self.unload_count += 1
        return rpb.UnloadModelResponse()

    def PredictModelSize(self, request, context):
        return rpb.ModelSizeResponse(size_bytes=self._size_for(request.model_id))

    def ModelSize(self, request, context):
        size = self.loaded.get(request.model_id, 0)
        return rpb.ModelSizeResponse(size_bytes=size)

    def _size_for(self, model_id: str) -> int:
        # Deterministic per-id size: default +/- up to 50%. A real
        # digest, not builtin hash() — that one is salted per process,
        # so "deterministic" sizes would diverge across test processes
        # (same fix as SimLoader._size_for).
        import zlib

        h = zlib.crc32(model_id.encode()) % 1000
        return int(self.default_size_bytes * (0.5 + h / 1000.0))

    # -- inference ----------------------------------------------------------

    def predict(self, method: str, request: bytes, context) -> bytes:
        md = dict(context.invocation_metadata())
        self.last_predict_metadata = md  #: shared-ok: test-introspection hook; last-writer-wins by design
        mid = md.get(grpc_defs.MODEL_ID_HEADER, "")
        if not mid:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, "missing mm-model-id header"
            )
        with self._lock:
            present = mid in self.loaded
        if not present or mid.startswith(NOT_FOUND_SERVE_PREFIX):
            # The Triton/MLServer quirk: runtime lost the model
            # (reference handling at SidecarModelMesh.java:304-322, 961-988).
            context.abort(grpc.StatusCode.NOT_FOUND, f"model {mid} not loaded")
        if SLOW_PREDICT_MARK in mid:
            self._clock.sleep(3.0)
        if method.endswith("/Echo"):
            # Large-payload data-plane probe: response mirrors the request,
            # exercising the send path at the same size as the receive path.
            return request
        # Deterministic "prediction": classify payload by hash.
        label = (len(request) + sum(request[:16])) % 10
        return f"{mid}:category_{label}".encode()

    def predict_batch(self, items) -> list:
        """Deterministic batched twin of ``predict`` (direct-call, no
        gRPC context): per-item results are byte-identical to N solo
        calls — the batched-vs-sequential identity the serving layer's
        parity tests pin — with per-item fault isolation (a missing or
        vanish- model fails only its own slot) and batch accounting for
        queue assertions. One slow-predict member costs the batch ONE
        virtual sleep (a fused dispatch is one kernel), not N.

        ``items`` are ``runtime.spi.BatchItem``-shaped (model_id,
        payload attrs).
        """
        from modelmesh_tpu.runtime.spi import ModelNotLoadedError

        with self._lock:
            self.batch_calls += 1
            self.batch_sizes.append(len(items))
        if any(SLOW_PREDICT_MARK in item.model_id for item in items):
            self._clock.sleep(3.0)
        out: list = []
        for item in items:
            mid = item.model_id
            with self._lock:
                present = mid in self.loaded
            if not present or mid.startswith(NOT_FOUND_SERVE_PREFIX):
                out.append(ModelNotLoadedError(mid))
                continue
            request = item.payload
            if (getattr(item, "method", "") or "").endswith("/Echo"):
                # Mirror the solo path's large-payload Echo probe.
                out.append(request)
                continue
            label = (len(request) + sum(request[:16])) % 10
            out.append(f"{mid}:category_{label}".encode())
        return out


def start_fake_runtime(
    port: int = 0,
    servicer: Optional[FakeRuntimeServicer] = None,
    max_workers: int = 16,
    uds_path: Optional[str] = None,
) -> tuple[grpc.Server, int, FakeRuntimeServicer]:
    """Start on localhost (or a unix socket); returns (server, bound_port,
    servicer). With ``uds_path`` the returned port is 0 and clients dial
    ``unix://<path>`` — the sidecar-pod transport (SidecarModelMesh.java:991
    buildLocalChannel)."""
    servicer = servicer or FakeRuntimeServicer()
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=message_size_options(),
    )
    grpc_defs.add_servicer(
        server, servicer, grpc_defs.RUNTIME_SERVICE, grpc_defs.RUNTIME_METHODS
    )
    server.add_generic_rpc_handlers(
        (grpc_defs.RawFallbackHandler(servicer.predict),)
    )
    bound = grpc_defs.bind_server(server, port, uds_path=uds_path or "")
    server.start()
    return server, bound, servicer


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=8085)
    parser.add_argument("--capacity-mb", type=int, default=512)
    parser.add_argument("--load-delay-s", type=float, default=0.0)
    parser.add_argument("--ready-delay-s", type=float, default=0.0)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    server, port, _ = start_fake_runtime(
        args.port,
        FakeRuntimeServicer(
            capacity_bytes=args.capacity_mb << 20,
            load_delay_s=args.load_delay_s,
            ready_delay_s=args.ready_delay_s,
        ),
    )
    log.info("fake runtime on :%d", port)
    server.wait_for_termination()


if __name__ == "__main__":
    main()
