"""gRPC service definitions built from method maps (no codegen plugin).

Provides stub/servicer factories for the three services (runtime SPI,
external management API, internal forwarding) plus the raw-bytes
identity marshallers used for arbitrary-method inference passthrough —
the equivalent of the reference's zero-copy ByteBuf method descriptors
(GrpcSupport.java:425-463, ModelMeshApi.java:649-819).
"""

from __future__ import annotations

from typing import Callable, Mapping, Type

import grpc

from modelmesh_tpu.proto import (
    mesh_api_pb2,
    mesh_internal_pb2,
    mesh_runtime_pb2,
    mesh_transfer_pb2,
)

# Metadata keys carrying the model/vmodel id on inference calls
# (reference: GrpcSupport.java:110-126).
MODEL_ID_HEADER = "mm-model-id"
VMODEL_ID_HEADER = "mm-vmodel-id"

_MethodMap = Mapping[str, tuple[Type, Type]]

RUNTIME_SERVICE = "mmtpu.runtime.ModelRuntime"
RUNTIME_METHODS: _MethodMap = {
    "LoadModel": (
        mesh_runtime_pb2.LoadModelRequest, mesh_runtime_pb2.LoadModelResponse),
    "UnloadModel": (
        mesh_runtime_pb2.UnloadModelRequest, mesh_runtime_pb2.UnloadModelResponse),
    "PredictModelSize": (
        mesh_runtime_pb2.PredictModelSizeRequest, mesh_runtime_pb2.ModelSizeResponse),
    "ModelSize": (
        mesh_runtime_pb2.ModelSizeRequest, mesh_runtime_pb2.ModelSizeResponse),
    "RuntimeStatus": (
        mesh_runtime_pb2.RuntimeStatusRequest, mesh_runtime_pb2.RuntimeStatusResponse),
}

API_SERVICE = "mmtpu.api.ModelMesh"
API_METHODS: _MethodMap = {
    "RegisterModel": (
        mesh_api_pb2.RegisterModelRequest, mesh_api_pb2.ModelStatusInfo),
    "UnregisterModel": (
        mesh_api_pb2.UnregisterModelRequest, mesh_api_pb2.UnregisterModelResponse),
    "GetModelStatus": (
        mesh_api_pb2.GetModelStatusRequest, mesh_api_pb2.ModelStatusInfo),
    "EnsureLoaded": (
        mesh_api_pb2.EnsureLoadedRequest, mesh_api_pb2.ModelStatusInfo),
    "SetVModel": (
        mesh_api_pb2.SetVModelRequest, mesh_api_pb2.VModelStatusInfo),
    "DeleteVModel": (
        mesh_api_pb2.DeleteVModelRequest, mesh_api_pb2.DeleteVModelResponse),
    "GetVModelStatus": (
        mesh_api_pb2.GetVModelStatusRequest, mesh_api_pb2.VModelStatusInfo),
}

INTERNAL_SERVICE = "mmtpu.internal.MeshInternal"
INTERNAL_METHODS: _MethodMap = {
    "Forward": (
        mesh_internal_pb2.ForwardRequest, mesh_internal_pb2.ForwardResponse),
    # Weight-transfer fetch (live scale-up): chunk-indexed peer pull of a
    # model's weight snapshot, served beside Forward on the internal port.
    "FetchWeights": (
        mesh_transfer_pb2.FetchWeightsRequest,
        mesh_transfer_pb2.FetchWeightsResponse),
}


def make_stub(channel: grpc.Channel, service: str, methods: _MethodMap):
    """Build a stub object with one unary-unary callable per method."""

    class _Stub:
        pass

    stub = _Stub()
    for name, (req_cls, resp_cls) in methods.items():
        setattr(
            stub,
            name,
            channel.unary_unary(
                f"/{service}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString,
            ),
        )
    return stub


def add_servicer(
    server: grpc.Server, servicer: object, service: str, methods: _MethodMap
) -> None:
    """Register ``servicer`` (which has a method per RPC name) on a server."""
    handlers = {}
    for name, (req_cls, resp_cls) in methods.items():
        fn = getattr(servicer, name)
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service, handlers),)
    )


# -- raw-bytes passthrough ----------------------------------------------------

def _identity(b: bytes) -> bytes:
    return b


def raw_method(channel: grpc.Channel, full_method: str):
    """Client callable for an arbitrary method with opaque byte payloads."""
    return channel.unary_unary(
        full_method, request_serializer=_identity, response_deserializer=_identity
    )


class RawFallbackHandler(grpc.GenericRpcHandler):
    """Server-side catch-all: any unregistered unary method is delivered to
    ``handler(method_name, request_bytes, context) -> response_bytes``.

    This is how arbitrary inference RPCs enter the mesh without registering
    per-method descriptors (reference fallback Registry,
    ModelMeshApi.java:1099-1160).
    """

    def __init__(self, handler: Callable[[str, bytes, grpc.ServicerContext], bytes]):
        self._handler = handler

    def service(self, handler_call_details):
        method = handler_call_details.method

        def unary(request: bytes, context: grpc.ServicerContext) -> bytes:
            return self._handler(method, request, context)

        return grpc.unary_unary_rpc_method_handler(
            unary, request_deserializer=_identity, response_serializer=_identity
        )


def call_cancellable(callable_, request, timeout=None, metadata=None,
                     cancel_event=None, with_trailers=False):
    """Invoke a unary-unary multicallable, aborting early when
    ``cancel_event`` fires (client disconnect): the in-flight RPC is
    cancelled so the remote side's context deactivates too, and the local
    concurrency slot frees immediately instead of riding out the call.

    ``with_trailers=True`` returns ``(response, trailing_metadata)`` so
    callers can read piggybacked response trailers (the Forward path's
    mm-load feedback) without a second RPC surface."""
    if cancel_event is None:
        if with_trailers:
            resp, call = callable_.with_call(
                request, timeout=timeout, metadata=metadata
            )
            return resp, call.trailing_metadata() or ()
        return callable_(request, timeout=timeout, metadata=metadata)
    import threading

    from modelmesh_tpu.serving.errors import RequestCancelledError

    fut = callable_.future(request, timeout=timeout, metadata=metadata)
    done = threading.Event()
    fut.add_done_callback(lambda _f: done.set())
    while not done.wait(0.05):  #: wall-clock: polls a REAL in-flight gRPC future at cancel-check cadence
        if cancel_event.is_set():
            fut.cancel()
            raise RequestCancelledError("client disconnected")
    result = fut.result()
    if with_trailers:
        # The rendezvous future is also the Call: trailers are available
        # once the result is.
        return result, fut.trailing_metadata() or ()
    return result


def bind_server(server, port: int = 0, bind_host: str = "127.0.0.1",
                uds_path: str = "") -> int:
    """Bind a grpc.Server to TCP or a unix socket; returns the bound TCP
    port (0 for UDS). A failed unix bind raises instead of the silent
    0-return grpc gives."""
    if uds_path:
        if server.add_insecure_port(f"unix://{uds_path}") == 0:
            raise RuntimeError(f"failed to bind unix socket {uds_path}")
        return 0
    return server.add_insecure_port(f"{bind_host}:{port}")
