"""Model-loading SPI: what the serving core calls to manage model copies.

Parity with the reference's per-type loading interface
(MM/ModelLoader.java:36-98: predictSize/modelSize/loadRuntime/unloadModel)
and the startup parameter block (MM/LocalInstanceParameters.java:26-124).
Sizes here are plain bytes; the cache's accounting unit (CACHE_UNIT_BYTES)
is applied by the serving layer.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Callable, Generic, Iterator, Optional, TypeVar

# Cache accounting unit (reference: 8 KiB, ModelLoader.java:37).
CACHE_UNIT_BYTES = 8 * 1024

T = TypeVar("T")  # runtime handle type for a loaded model


@dataclasses.dataclass(frozen=True)
class ModelInfo:
    model_type: str
    model_path: str = ""
    model_key: str = ""


@dataclasses.dataclass(frozen=True)
class LocalInstanceParams:
    """Instance runtime parameters, produced by loader startup.

    Defaults match the reference envelope (BASELINE.md): 8 loading threads,
    240 s load timeout.
    """

    capacity_bytes: int
    load_concurrency: int = 8
    load_timeout_ms: int = 240_000
    default_model_size_bytes: int = 1 << 20
    limit_model_concurrency: bool = False

    @property
    def capacity_units(self) -> int:
        return max(self.capacity_bytes // CACHE_UNIT_BYTES, 1)


class ModelLoadException(Exception):
    def __init__(self, message: str, timeout: bool = False):
        super().__init__(message)
        self.timeout = timeout


class ModelNotLoadedError(Exception):
    """Runtime no longer has the model (the NOT_FOUND-on-serve case);
    the serving layer purges its entry and retries elsewhere."""


@dataclasses.dataclass(frozen=True)
class BatchItem:
    """One request inside a batched runtime dispatch
    (``ModelLoader.call_model_batch``). ``headers`` is the per-request
    metadata list exactly as ``call_model`` receives it."""

    model_id: str
    method: str = ""
    payload: bytes = b""
    headers: Optional[list] = None


@dataclasses.dataclass(frozen=True)
class WeightChunk:
    """One unit of a streamed weight transfer (peer fetch / host-tier
    re-warm). ``layer`` tags the model layer this chunk completes for
    layer-streamable families (-1 = not layer-aligned); ``last`` marks
    the end of the stream so a receiver can distinguish a complete
    transfer from a truncated one."""

    seq: int
    payload: bytes
    layer: int = -1
    last: bool = False


class ModelLoader(abc.ABC, Generic[T]):
    """Per-instance loading SPI. All methods may block; the serving core
    runs them on its loading pool with timeouts."""

    @abc.abstractmethod
    def startup(self) -> LocalInstanceParams:
        """Block until the runtime is ready; return instance parameters
        (reference: SidecarModelMesh.startup() polling runtimeStatus,
        SidecarModelMesh.java:157-232)."""

    @abc.abstractmethod
    def load(self, model_id: str, info: ModelInfo) -> "LoadedModel[T]":
        """Load; raise ModelLoadException on failure."""

    def predict_size(self, model_id: str, info: ModelInfo) -> int:
        """Estimated bytes before loading. 0 = unknown."""
        return 0

    def model_size(self, model_id: str, handle: T) -> int:
        """Measured bytes of a loaded model. 0 = unknown."""
        return 0

    def unload(self, model_id: str) -> None:
        """Release a loaded model. Must be idempotent."""

    @property
    def requires_unload(self) -> bool:
        """True if capacity isn't freed until unload completes (drives the
        unload-buffer accounting, ModelCacheUnloadBufManager)."""
        return True

    # -- batched dispatch (optional capability; serving/batching.py) -------

    @property
    def supports_batched_dispatch(self) -> bool:
        """True when ``call_model_batch`` executes a whole micro-batch as
        one (or few) real runtime dispatches, so the serving layer's
        continuous-batching queue is worth putting in front of this
        loader. The default loop-over-singles implementation keeps
        ``call_model_batch`` callable everywhere, but a loader that
        merely loops gains nothing from queueing — the serving layer
        only engages the batch queue when this flag is True (or an
        explicit batched runtime call is injected)."""
        return False

    def call_model_batch(self, items: list[BatchItem], cancel_event=None):
        """Execute a micro-batch of inference requests.

        Returns a list aligned with ``items``; each entry is either the
        response ``bytes`` or an ``Exception`` instance failing THAT
        item (per-item isolation — one malformed payload must not fail
        its batch-mates). A raised exception fails the whole batch.

        Default: loop over ``call_model`` singles with per-item error
        isolation, so sidecar/fake/bench loaders keep working unchanged.
        """
        call_model = getattr(self, "call_model", None)
        if call_model is None:
            raise NotImplementedError(
                f"{type(self).__name__} has no call_model"
            )
        out: list = []
        for item in items:
            try:
                out.append(call_model(
                    item.model_id, item.method, item.payload,
                    item.headers, cancel_event=cancel_event,
                ))
            except Exception as e:  # noqa: BLE001 — per-item isolation
                out.append(e)
        return out

    def batch_group_key(self, model_id: str) -> str:
        """Micro-batch grouping key: requests whose models share a key
        may ride one dispatch. Default = the model id (per-model
        batching only); a fused-dispatch-capable loader returns a shared
        architecture key for co-located same-family models so
        cross-model requests fuse into one kernel."""
        return model_id

    # -- weight streaming (optional capability; transfer/ subsystem) -------

    @property
    def supports_weight_streaming(self) -> bool:
        """True when this loader implements the ``export_weights`` /
        ``load_from_stream`` pair. The serving layer gates every transfer
        decision (peer fetch, host-tier demotion, serve-before-loaded) on
        this flag — a plain store-only loader is never asked to stream."""
        return False

    def export_weights(
        self, model_id: str, handle: T
    ) -> Optional[Iterator[WeightChunk]]:
        """Serialize a LOADED model's weights as an ordered chunk stream
        (the peer-fetch / host-demotion source). None = unsupported or the
        runtime can't export this model right now. Chunks must be
        reproducible for the same loaded copy; the final chunk must carry
        ``last=True``."""
        return None

    def load_from_stream(
        self,
        model_id: str,
        info: ModelInfo,
        chunks: Iterator[WeightChunk],
        partial_ready: Optional[Callable[["LoadedModel[T]"], None]] = None,
    ) -> "LoadedModel[T]":
        """Materialize a model from a chunk stream instead of the model
        store (peer fetch or host-tier re-warm).

        Contract: loader-side failures raise ``ModelLoadException``;
        exceptions raised BY the chunk iterator (peer death, stream error
        mid-transfer) must propagate unwrapped so the serving layer can
        fall back to a store load. ``partial_ready(loaded)`` may be called
        at most once, as soon as enough layers have landed to serve
        requests (layer-streamable families only) — the handle passed must
        already be usable for inference at that point.
        """
        raise NotImplementedError(
            f"{type(self).__name__} does not support weight streaming"
        )

    # -- sharded execution (optional capability; placement groups) ---------

    @property
    def supports_sharded_execution(self) -> bool:
        """True when this loader can materialize and serve ONE SHARD of a
        model (``load_shard`` / ``load_shard_from_stream``) — the runtime
        half of the sharded-execution subsystem. The serving layer only
        plans multi-instance placement groups for models whose loader
        declares this; everyone else keeps the single-copy contract (an
        oversized model simply fails to place, as before)."""
        return False

    def load_shard(
        self, model_id: str, info: ModelInfo, shard_index: int,
        shard_count: int,
    ) -> "LoadedModel[T]":
        """Materialize shard ``shard_index`` of ``shard_count`` from the
        model store. The returned size must be the SHARD's resident
        bytes (≈ total/shard_count) — that is what the cache accounts.
        Raise ModelLoadException on failure."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support sharded execution"
        )

    def load_shard_from_stream(
        self,
        model_id: str,
        info: ModelInfo,
        shard_index: int,
        shard_count: int,
        chunks: Iterator[WeightChunk],
    ) -> "LoadedModel[T]":
        """Materialize one shard from a transfer stream carrying ONLY
        that shard's chunks (a peer holding the same shard, or the
        shard-sliced subset of a full snapshot). Same error contract as
        ``load_from_stream``: loader failures raise ModelLoadException,
        iterator failures propagate unwrapped so the transfer manager
        can fall back to ``load_shard`` from the store. No
        ``partial_ready``: a shard is already the minimal servable
        granule — serve-before-loaded composes at the GROUP level (the
        group serves when every shard has landed), not within a shard."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support sharded execution"
        )


@dataclasses.dataclass
class LoadedModel(Generic[T]):
    handle: T
    size_bytes: int = 0            # 0 = needs post-load sizing
    max_concurrency: int = 0       # 0 = unlimited
