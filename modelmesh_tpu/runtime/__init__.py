"""Model-runtime SPI: loader interface, gRPC sidecar client, fake runtime."""

from modelmesh_tpu.runtime.spi import (
    CACHE_UNIT_BYTES,
    LoadedModel,
    LocalInstanceParams,
    ModelInfo,
    ModelLoader,
    ModelLoadException,
)

__all__ = [
    "CACHE_UNIT_BYTES",
    "LoadedModel",
    "LocalInstanceParams",
    "ModelInfo",
    "ModelLoader",
    "ModelLoadException",
]
