"""Cluster-state records: the shared-registry schema.

Capability parity with the reference's KV-persisted records:
- ModelRecord    (MM/ModelRecord.java:61-126): per-model registry entry —
  type/path/key, instance placements with load timestamps, load failures
  with expiry, refCount/autoDelete for vmodel-managed models, lazily
  persisted lastUsed.
- InstanceRecord (MM/InstanceRecord.java:37-108): per-instance
  advertisement — LRU age, capacity/used, loading threads, request rate,
  shutdown flag, location/zone/labels.
- VModelRecord   (MM/VModelRecord.java:17-45): virtual-model alias state —
  owner, active/target concrete models, transition failure flag.

All are JSON dataclasses with KV-version CAS via kv.table.Record. Time is
epoch millis throughout (matching the cache timestamps).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from modelmesh_tpu.kv.table import Record
# Injectable time source (utils/clock.py): record timestamps follow the
# installed clock so the simulation harness controls them.
from modelmesh_tpu.utils.clock import now_ms  # noqa: F401 — re-export


# Load-failure bookkeeping windows (reference: ModelMesh.java:219-224).
# Overridable via MM_LOAD_FAILURE_EXPIRY_MS (the reference exposes its
# time heuristics as system properties the same way — SURVEY.md section 4);
# read through failure_expiry_ms() (utils/envs registry accessor, live per
# call) so tests/operators can adjust at runtime.
LOAD_FAILURE_EXPIRY_MS = 15 * 60 * 1000


def failure_expiry_ms() -> int:
    from modelmesh_tpu.utils import envs

    # No falsy fallback: an explicit 0 means "failures expire immediately"
    # (re-load exclusion disabled), which must be honored.
    return envs.get_int("MM_LOAD_FAILURE_EXPIRY_MS")


MAX_LOAD_FAILURES = 3
MAX_LOAD_LOCATIONS = 5


@dataclasses.dataclass
class ModelRecord(Record):
    model_type: str = ""
    model_path: str = ""
    model_key: str = ""          # opaque runtime credential/config blob
    # instance_id -> load-completion timestamp (ms): copies that are LOADED
    # and servable. (The reference keeps one map with load-start timestamps
    # and estimates completion via TimeStats, ModelMesh.java:4351; splitting
    # the claim from the completion makes status reporting exact.)
    instance_ids: dict[str, int] = dataclasses.field(default_factory=dict)
    # instance_id -> claim timestamp (ms): copies being loaded right now.
    # Acts as the placement claim so concurrent placements don't double-load.
    loading_instances: dict[str, int] = dataclasses.field(default_factory=dict)
    # instance_id -> demotion timestamp (ms): instances holding a HOST-RAM
    # snapshot of the weights (transfer/ tier) but NO device copy. Not
    # servable — never part of all_placements/copy_count — but valid
    # peer-fetch sources, so a re-scale-up streams from host RAM instead
    # of the model store. Cleared with the instance by remove_instance
    # (reaper pruning of dead instances covers host claims for free).
    host_instances: dict[str, int] = dataclasses.field(default_factory=dict)
    # instance_id -> [failure_ts_ms, message]
    load_failures: dict[str, list] = dataclasses.field(default_factory=dict)
    ref_count: int = 0           # vmodel references
    auto_delete: bool = False    # delete when ref_count drops to 0
    size_units: int = 0          # measured size (cache units); 0 = unknown.
                                 # Piggybacked on load completion; feeds the
                                 # global solver's cost matrix.
    last_used: int = 0           # lazily persisted (see should_persist_last_used)
    last_unload_ms: int = 0
    # Sharded multi-device execution (placement GROUPS): a model too big
    # for one instance is split into shard_count weight shards, each held
    # by a different instance. shard_instances maps instance_id -> shard
    # index (two instances MAY hold the same index transiently — that is
    # exactly the drain pre-copy overlap). The group is routable only
    # while COMPLETE (every index has a servable holder); group_epoch
    # increments on every re-plan / membership change so observers can
    # order group generations. shard_count == 0 means unsharded.
    shard_count: int = 0
    shard_instances: dict[str, int] = dataclasses.field(default_factory=dict)
    group_epoch: int = 0
    version: int = 0

    # -- placements ---------------------------------------------------------

    def claim_loading(self, instance_id: str, ts: Optional[int] = None) -> None:
        self.loading_instances[instance_id] = ts if ts is not None else now_ms()

    def promote_loaded(self, instance_id: str, ts: Optional[int] = None) -> None:
        self.loading_instances.pop(instance_id, None)
        # A device copy supersedes any stale host claim for the same
        # instance (re-warm promoted the host snapshot back to device).
        self.host_instances.pop(instance_id, None)
        self.instance_ids[instance_id] = ts if ts is not None else now_ms()

    def promote_partial(self, instance_id: str, ts: Optional[int] = None) -> None:
        """Mid-transfer (PARTIAL) promotion: the copy becomes routable
        (listed in ``instance_ids``) while the ORIGINAL loading claim is
        kept — the claim tells peers the copy is not yet a valid
        weight-transfer source and preserves the strict claim ordering
        receivers wait on. ``promote_loaded`` at stream completion (or
        ``remove_instance`` on failure) clears it."""
        self.host_instances.pop(instance_id, None)
        if instance_id not in self.loading_instances:
            self.loading_instances[instance_id] = (
                ts if ts is not None else now_ms()
            )
        self.instance_ids[instance_id] = ts if ts is not None else now_ms()

    def remove_instance(self, instance_id: str) -> bool:
        a = self.instance_ids.pop(instance_id, None) is not None
        b = self.loading_instances.pop(instance_id, None) is not None
        c = self.host_instances.pop(instance_id, None) is not None
        idx = self.shard_instances.pop(instance_id, None)
        if idx is not None:
            self.group_epoch += 1
            # Atomic group eviction: losing a shard whose index has no
            # surviving SERVABLE twin (a drain pre-copy leaves one) makes
            # every other shard dead weight — drop the whole group so
            # members observe their vanished claims and tear down,
            # freeing K-1 shards' capacity instead of stranding it. With
            # a twin present (drain), only the leaver is popped.
            twin = any(
                i == idx and other in self.instance_ids
                for other, i in self.shard_instances.items()
            )
            if not twin:
                for other in list(self.shard_instances):
                    self.shard_instances.pop(other, None)
                    self.instance_ids.pop(other, None)
                    self.loading_instances.pop(other, None)
                self.shard_count = 0
        if not self.shard_instances:
            # Last member gone: the group is absent, not half-present.
            self.shard_count = 0
        return a or b or c

    # -- shard groups (sharded multi-device execution) -----------------------

    def begin_shard_group(
        self, assignments: dict[str, int], shard_count: int,
        ts: Optional[int] = None,
    ) -> None:
        """Install (or re-plan) the FULL group atomically inside one CAS:
        shard assignments, loading claims for every member that is not
        already servable, and an epoch bump. ``assignments`` for members
        already holding the right shard are kept as-is (their claims and
        completion timestamps survive a top-up re-plan)."""
        ts = ts if ts is not None else now_ms()
        self.shard_count = int(shard_count)
        self.group_epoch += 1
        for iid, idx in assignments.items():
            prev = self.shard_instances.get(iid)
            self.shard_instances[iid] = int(idx)
            if prev == int(idx) and iid in self.instance_ids:
                continue  # already a servable holder of this very shard
            self.claim_loading(iid, ts)
        # Members no longer assigned any shard lose their claims — their
        # pods observe the vanished claim and tear the local shard down.
        for iid in [i for i in self.shard_instances if i not in assignments]:
            self.shard_instances.pop(iid, None)
            self.instance_ids.pop(iid, None)
            self.loading_instances.pop(iid, None)

    def shard_index_of(self, instance_id: str) -> Optional[int]:
        return self.shard_instances.get(instance_id)

    @property
    def group_complete(self) -> bool:
        """True when every shard index 0..shard_count-1 has at least one
        SERVABLE holder (listed in instance_ids). Unsharded models are
        vacuously complete."""
        if not self.shard_count:
            return True
        held = {
            idx for iid, idx in self.shard_instances.items()
            if iid in self.instance_ids
        }
        return held >= set(range(self.shard_count))

    def missing_shards(self) -> list[int]:
        """Shard indices with no holder AT ALL (neither servable nor
        loading) — the top-up re-plan's work list."""
        if not self.shard_count:
            return []
        held = set(self.shard_instances.values())
        return [i for i in range(self.shard_count) if i not in held]

    def claim_host_copy(self, instance_id: str, ts: Optional[int] = None) -> None:
        """Advertise a host-tier (demoted) snapshot on this instance."""
        self.host_instances[instance_id] = ts if ts is not None else now_ms()

    def drop_host_copy(self, instance_id: str) -> bool:
        return self.host_instances.pop(instance_id, None) is not None

    def placed_on(self, instance_id: str) -> bool:
        return (
            instance_id in self.instance_ids
            or instance_id in self.loading_instances
        )

    @property
    def all_placements(self) -> set[str]:
        return set(self.instance_ids) | set(self.loading_instances)

    @property
    def copy_count(self) -> int:
        return len(self.instance_ids) + len(self.loading_instances)

    # -- failures -------------------------------------------------------------

    def add_load_failure(self, instance_id: str, message: str,
                         ts: Optional[int] = None) -> None:
        self.load_failures[instance_id] = [
            ts if ts is not None else now_ms(), message[:512]
        ]

    def expire_load_failures(
        self, now: Optional[int] = None,
        expiry_ms: Optional[int] = None,
    ) -> bool:
        """Drop stale failure entries; returns True if anything changed."""
        now = now if now is not None else now_ms()
        expiry_ms = expiry_ms if expiry_ms is not None else failure_expiry_ms()
        stale = [
            iid for iid, (ts, _msg) in self.load_failures.items()
            if now - ts > expiry_ms
        ]
        for iid in stale:
            del self.load_failures[iid]
        return bool(stale)

    def active_failures(self, now: Optional[int] = None) -> set[str]:
        """Instance ids with a NON-expired load failure (one expiry read
        for the whole set — the routing hot path calls this per miss)."""
        now = now if now is not None else now_ms()
        expiry = failure_expiry_ms()
        return {
            iid for iid, (ts, _msg) in self.load_failures.items()
            if now - ts <= expiry
        }

    def active_failure_count(self, now: Optional[int] = None) -> int:
        return len(self.active_failures(now))

    def failed_on(self, instance_id: str, now: Optional[int] = None) -> bool:
        entry = self.load_failures.get(instance_id)
        if entry is None:
            return False
        now = now if now is not None else now_ms()
        return now - entry[0] <= failure_expiry_ms()

    def load_exhausted(self, now: Optional[int] = None) -> bool:
        """Too many failures or too many attempted locations
        (reference checkLoadFailureCount/checkLoadLocationCount,
        ModelMesh.java:4590-4607)."""
        return (
            self.active_failure_count(now) >= MAX_LOAD_FAILURES
            or len(self.load_failures) >= MAX_LOAD_LOCATIONS
        )

    # -- lastUsed laziness ---------------------------------------------------

    # The reference persists lastUsed only when >6-7h stale or piggybacked on
    # other updates (ModelRecord.java:96-105) to avoid write storms.
    LAST_USED_PERSIST_STALENESS_MS = 6 * 3600 * 1000

    def should_persist_last_used(self, observed_last_used: int) -> bool:
        return (
            observed_last_used - self.last_used
            > self.LAST_USED_PERSIST_STALENESS_MS
        )


@dataclasses.dataclass
class InstanceRecord(Record):
    start_ts: int = 0
    lru_ts: int = 0              # oldest cache-entry timestamp (0 = empty)
    model_count: int = 0
    capacity_units: int = 0
    used_units: int = 0
    loading_threads: int = 0
    loading_in_progress: int = 0
    req_per_minute: int = 0
    shutting_down: bool = False
    # Admin drain (dynamic config `disable`): excluded from new placements
    # but NOT migrating and NOT holding peers' readiness (unlike
    # shutting_down).
    disabled: bool = False
    # Graceful drain in progress (reconfig/drain.py): excluded from new
    # placements and deprioritized as a serve target (survivor copies are
    # preferred once servable), but still LIVE — already-loaded copies
    # keep serving while the drain pre-copies them to survivors. Unlike
    # shutting_down, a draining instance is still a routable member of
    # the fleet; unlike disabled, it IS migrating and will deregister.
    draining: bool = False
    endpoint: str = ""           # host:port of the instance's internal RPC
    location: str = ""           # node/host for anti-affinity
    zone: str = ""
    labels: list[str] = dataclasses.field(default_factory=list)
    instance_version: str = ""   # deployment version for upgrade tracking
    version: int = 0

    @property
    def free_units(self) -> int:
        return max(self.capacity_units - self.used_units, 0)

    @property
    def full_fraction(self) -> float:
        return self.used_units / self.capacity_units if self.capacity_units else 1.0

    def placement_sort_key(self) -> tuple:
        """The reference's PLACEMENT_ORDER (ModelMesh.java:4646): prefer most
        free space, break ties by oldest LRU (cheapest eviction)."""
        return (-self.free_units, self.lru_ts if self.lru_ts else 0)


@dataclasses.dataclass
class VModelRecord(Record):
    owner: str = ""
    active_model: str = ""
    target_model: str = ""
    target_load_failed: bool = False
    version: int = 0

    @property
    def in_transition(self) -> bool:
        return bool(self.target_model) and self.target_model != self.active_model
