"""TLS for the mesh's gRPC surfaces (server-side TLS + optional mutual TLS).

Parity with the reference's TLS support (ModelMeshApi TLS setup; tested by
ModelMeshClusterTlsTest / ClientAuthTest tiers): the external API, internal
forwarding, and runtime links can all run over TLS with the same
certificate configuration; client-auth mode requires peers to present certs
signed by the trusted CA.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import grpc


@dataclasses.dataclass(frozen=True)
class TlsConfig:
    cert_pem: bytes                 # server certificate chain
    key_pem: bytes                  # server private key
    ca_pem: Optional[bytes] = None  # trust roots (peer verification)
    require_client_auth: bool = False
    # Override the authority used for hostname verification on OUTBOUND
    # connections. None (production default) verifies the peer cert against
    # the dialed hostname; tests with a shared self-signed cert set
    # "localhost". Never hardcoded by callers.
    override_authority: Optional[str] = None

    @classmethod
    def from_files(
        cls, cert_path: str, key_path: str, ca_path: Optional[str] = None,
        require_client_auth: bool = False,
    ) -> "TlsConfig":
        with open(cert_path, "rb") as f:
            cert = f.read()
        with open(key_path, "rb") as f:
            key = f.read()
        ca = None
        if ca_path:
            with open(ca_path, "rb") as f:
                ca = f.read()
        return cls(cert, key, ca, require_client_auth)

    def server_credentials(self) -> grpc.ServerCredentials:
        if self.require_client_auth and not self.ca_pem:
            raise ValueError(
                "client-auth (mTLS) requires trust roots: provide ca_pem "
                "(--tls-ca) alongside require_client_auth"
            )
        return grpc.ssl_server_credentials(
            [(self.key_pem, self.cert_pem)],
            root_certificates=self.ca_pem,
            require_client_auth=self.require_client_auth,
        )

    def channel_credentials(self) -> grpc.ChannelCredentials:
        # For mTLS the same cert/key doubles as the client identity
        # (instance-to-instance links use one identity per pod).
        return grpc.ssl_channel_credentials(
            root_certificates=self.ca_pem,
            private_key=self.key_pem if self.require_client_auth else None,
            certificate_chain=self.cert_pem if self.require_client_auth else None,
        )

    # -- raw-socket (non-gRPC) transports ----------------------------------
    # The ZooKeeper jute protocol rides plain TCP, so its TLS wraps the
    # socket directly (a real ensemble's secureClientPort does the same).
    # Both contexts are derived from the SAME PEM material as the gRPC
    # credentials — one coordination-plane identity per pod.

    def _load_identity(self, ctx) -> None:
        import os
        import tempfile

        # ssl.load_cert_chain only takes file paths; stage the in-memory
        # PEMs in private temp files for the duration of the call.
        cf = tempfile.NamedTemporaryFile("wb", suffix=".pem", delete=False)
        kf = tempfile.NamedTemporaryFile("wb", suffix=".pem", delete=False)
        try:
            cf.write(self.cert_pem)
            cf.close()
            kf.write(self.key_pem)
            kf.close()
            ctx.load_cert_chain(cf.name, kf.name)
        finally:
            os.unlink(cf.name)
            os.unlink(kf.name)

    def ssl_server_context(self):
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        self._load_identity(ctx)
        if self.require_client_auth:
            if not self.ca_pem:
                raise ValueError(
                    "client-auth (mTLS) requires trust roots: provide "
                    "ca_pem alongside require_client_auth"
                )
            ctx.verify_mode = ssl.CERT_REQUIRED
            ctx.load_verify_locations(cadata=self.ca_pem.decode())
        return ctx

    def ssl_client_context(self):
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        if self.ca_pem:
            ctx.load_verify_locations(cadata=self.ca_pem.decode())
        else:
            ctx.load_default_certs()
        if self.require_client_auth:
            self._load_identity(ctx)
        return ctx

    def server_hostname(self, dialed_host: str) -> str:
        """The name the client verifies the server cert against —
        override_authority when set (shared test certs), else the dialed
        host (production default)."""
        return self.override_authority or dialed_host


def secure_channel(endpoint: str, tls: Optional[TlsConfig],
                   override_authority: Optional[str] = None) -> grpc.Channel:
    """``override_authority`` defaults to ``tls.override_authority`` so call
    sites don't have to re-plumb a field the config already carries."""
    from modelmesh_tpu.utils.grpcopts import message_size_options

    if tls is None:
        return grpc.insecure_channel(endpoint, options=message_size_options())
    authority = override_authority or tls.override_authority
    options = message_size_options()
    if authority:
        options.append(("grpc.ssl_target_name_override", authority))
    return grpc.secure_channel(endpoint, tls.channel_credentials(), options)


def generate_self_signed(
    common_name: str = "modelmesh-test", days: int = 1
) -> TlsConfig:
    """Test helper: in-memory self-signed cert (CA == leaf)."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
    )
    now = datetime.datetime.now(datetime.timezone.utc)  #: wall-clock: X.509 validity window — peers check it against REAL time; a virtual timestamp would mint an expired cert
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(
            x509.SubjectAlternativeName([
                x509.DNSName(common_name),
                x509.DNSName("localhost"),
            ]),
            critical=False,
        )
        .add_extension(
            x509.BasicConstraints(ca=True, path_length=None), critical=True
        )
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption(),
    )
    return TlsConfig(
        cert_pem=cert_pem, key_pem=key_pem, ca_pem=cert_pem,
        override_authority="localhost",
    )
