"""Mesh routing/serving exceptions.

Mirrors the reference's exception protocol (modelmesh.thrift:42-84):
ModelNotHere drives retry-at-another-copy, ModelLoadException carries the
timeout flag, ApplierException wraps a downstream gRPC status.
"""

from __future__ import annotations

from modelmesh_tpu.runtime.spi import ModelLoadException  # re-export

__all__ = [
    "ModelLoadException",
    "ModelNotFoundError",
    "ModelNotHereError",
    "NoCapacityError",
    "ApplierError",
    "OverloadShedError",
    "RequestCancelledError",
    "ServiceUnavailableError",
]


class ModelNotFoundError(Exception):
    """Model id is not in the registry."""


class ModelNotHereError(Exception):
    """The addressed instance doesn't (any longer) have the model copy."""

    def __init__(self, instance_id: str, model_id: str):
        super().__init__(f"model {model_id} not present on {instance_id}")
        self.instance_id = instance_id
        self.model_id = model_id


class NoCapacityError(Exception):
    """No instance can accept the load (cluster full / churn guard)."""


class ApplierError(Exception):
    """Downstream runtime returned a gRPC error for an inference call."""

    def __init__(self, grpc_code: str, message: str):
        super().__init__(f"{grpc_code}: {message}")
        self.grpc_code = grpc_code


class ReadOnlyModeError(Exception):
    """Registry mutation rejected: this instance runs in KV-migration
    read-only mode (MM_KV_READ_ONLY=1; reference readOnlyMode,
    ModelMesh.java:200-204) — model addition/removal is blocked while the
    operator migrates between disjoint KV stores."""


class ServiceUnavailableError(Exception):
    """Peer instance unreachable."""


class RequestCancelledError(Exception):
    """Client cancelled the request; abort in-flight work and free slots
    (reference cancellation propagation, ModelMeshApi.java:709-729)."""


class OverloadShedError(Exception):
    """Request deliberately shed by the admission controller
    (serving/admission.py): the class's token bucket was empty and the
    bounded queue window expired while higher-priority classes burn SLO
    budget. Typed so clients can distinguish 'the fleet chose not to
    serve you right now' (back off / retry elsewhere) from a failure —
    mapped to RESOURCE_EXHAUSTED with an mm-overload trailer at the API
    edge ("Load Balanced Demand Distribution under Overload Penalties",
    PAPERS.md: explicit shed penalties at the edge beat queue collapse
    fleet-wide)."""

    def __init__(self, model_class: str, message: str = ""):
        super().__init__(
            message or f"overload: class {model_class!r} shed at admission"
        )
        self.model_class = model_class
