"""Deployable serving-instance entrypoint.

One process = one mesh instance: KV client (remote MeshKV or etcd),
model runtime (in-process JAX server, external sidecar, or the test fake),
the gRPC mesh server (management + internal + inference), background tasks,
vmodels, metrics, optional preStop hook. The equivalent of the reference's
start.sh + litelinks service bootstrap (SURVEY.md section 3.1).

    python -m modelmesh_tpu.serving.main \
        --kv mesh://127.0.0.1:2379 --instance-id i-0 --port 9000 \
        --runtime jax --capacity-mb 512 --metrics-port 2112

Env: MM_STATIC_MODELS (JSON) for startup registration,
MM_PAYLOAD_PROCESSORS (comma-separated URIs), MM_TYPE_CONSTRAINTS (path to
watched JSON file), MM_ZONE / MM_LABELS for placement metadata.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import threading

from modelmesh_tpu.utils import envs

log = logging.getLogger("modelmesh_tpu.main")


def build_store(kv_uri: str, tls=None):
    """mesh://host:port | etcd://host:port | zookeeper://host:port |
    memory:// (single process).

    ``tls`` secures the coordination plane too — registry records carry
    model_key credential blobs, so the KV link deserves the same mTLS as
    the data plane."""
    scheme, _, rest = kv_uri.partition("://")
    if scheme == "memory":
        from modelmesh_tpu.kv.memory import InMemoryKV

        return InMemoryKV()
    if scheme == "mesh":
        from modelmesh_tpu.kv.service import RemoteKV

        return RemoteKV(rest, tls=tls)
    if scheme == "etcd":
        from modelmesh_tpu.kv.etcd import EtcdKV

        return EtcdKV(rest, tls=tls)
    if scheme == "zookeeper":
        from modelmesh_tpu.kv.zookeeper import ZookeeperKV

        return ZookeeperKV(rest, tls=tls)
    raise ValueError(
        f"unknown kv scheme {scheme!r} "
        "(mesh://, etcd://, zookeeper://, memory://)"
    )


def build_loader(runtime: str, capacity_mb: int, tls=None):
    if runtime == "jax":
        from modelmesh_tpu.models.server import InProcessJaxLoader

        return InProcessJaxLoader(capacity_bytes=capacity_mb << 20)
    if runtime == "fake":
        from modelmesh_tpu.runtime.fake import FakeRuntimeServicer, start_fake_runtime
        from modelmesh_tpu.runtime.sidecar import SidecarRuntime

        server, port, _ = start_fake_runtime(
            servicer=FakeRuntimeServicer(capacity_bytes=capacity_mb << 20)
        )
        loader = SidecarRuntime(f"127.0.0.1:{port}", startup_timeout_s=30)
        # Keep the embedded runtime server alive for the loader's lifetime.
        loader._embedded_runtime_server = server
        return loader
    if runtime.startswith("sidecar:"):
        from modelmesh_tpu.runtime.sidecar import SidecarRuntime

        return SidecarRuntime(
            runtime[len("sidecar:"):], startup_timeout_s=300, tls=tls
        )
    raise ValueError(
        f"unknown runtime {runtime!r} "
        "(jax | fake | sidecar:host:port | sidecar:unix:///path.sock)"
    )


def main(argv=None) -> None:
    from modelmesh_tpu.utils import honor_platform_env

    honor_platform_env()
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--kv", default=envs.get("MM_KV_URI") or "memory://"
    )
    parser.add_argument("--instance-id", default=None)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument(
        "--frontdoor-port", type=int, default=-1,
        help="shared SO_REUSEPORT public port for the external surfaces; "
        "start N worker processes with the SAME value to spread one "
        "host's data plane across cores (each worker keeps its own "
        "unique --port for internal forwards)",
    )
    parser.add_argument("--advertise-host", default="127.0.0.1")
    parser.add_argument("--runtime", default="jax")
    parser.add_argument("--capacity-mb", type=int, default=256)
    parser.add_argument("--metrics-port", type=int, default=-1)
    parser.add_argument("--prestop-port", type=int, default=-1)
    parser.add_argument(
        "--strategy", choices=["greedy", "jax", "shadow"], default="greedy",
        help="placement decisions: greedy heuristics, the jax global plan, "
        "or shadow (serve greedy while scoring the jax plan on the side - "
        "read agreement in the ***GETSTATE*** dump before promoting)",
    )
    parser.add_argument("--load-timeout-s", type=float, default=None)
    parser.add_argument("--tls-cert", default="", help="server cert PEM path")
    parser.add_argument("--tls-key", default="", help="server key PEM path")
    parser.add_argument("--tls-ca", default="", help="trust-root PEM path")
    parser.add_argument(
        "--tls-client-auth", action="store_true",
        help="require peer/client certificates signed by --tls-ca (mTLS)",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=envs.get("MM_LOG_LEVEL"),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s %(reqctx)s",
    )
    from modelmesh_tpu.observability.logctx import install_filter

    install_filter()

    from modelmesh_tpu.observability.metrics import NoopMetrics, PrometheusMetrics
    from modelmesh_tpu.observability.payloads import build_processor
    from modelmesh_tpu.serving.api import (
        MeshServer,
        PeerChannels,
        make_grpc_peer_call,
        make_grpc_peer_fetch,
    )
    from modelmesh_tpu.serving.bootstrap import (
        PreStopServer,
        register_static_models,
    )
    from modelmesh_tpu.serving.constraints import (
        ConstraintsFileWatcher,
        TypeConstraints,
        UpgradeTracker,
    )
    from modelmesh_tpu.serving.instance import InstanceConfig, ModelMeshInstance
    from modelmesh_tpu.serving.tasks import BackgroundTasks
    from modelmesh_tpu.serving.vmodels import VModelManager

    tls = None
    if args.tls_cert:
        from modelmesh_tpu.serving.tls import TlsConfig

        tls = TlsConfig.from_files(
            args.tls_cert, args.tls_key, args.tls_ca or None,
            require_client_auth=args.tls_client_auth,
        )

    store = build_store(args.kv, tls=tls)
    loader = build_loader(args.runtime, args.capacity_mb, tls=tls)
    metrics = (
        PrometheusMetrics(
            port=max(args.metrics_port, 0),
            instance_id=args.instance_id or "",
            per_model=envs.get_bool("MM_PER_MODEL_METRICS"),
        )
        if args.metrics_port >= 0
        else NoopMetrics()
    )
    constraints = None
    watcher = None
    constraints_path = envs.get("MM_TYPE_CONSTRAINTS") or ""
    if constraints_path:
        constraints = TypeConstraints()
        watcher = ConstraintsFileWatcher(constraints_path, constraints)

    strategy = None
    if args.strategy == "jax":
        from modelmesh_tpu.placement.jax_engine import JaxPlacementStrategy

        strategy = JaxPlacementStrategy()
    elif args.strategy == "shadow":
        from modelmesh_tpu.placement.greedy import GreedyStrategy
        from modelmesh_tpu.placement.jax_engine import JaxPlacementStrategy
        from modelmesh_tpu.placement.shadow import ShadowStrategy

        # Serve greedy; score the jax plan on every decision. The shadow's
        # own fallback is greedy too, so "agreement" during plan gaps is
        # trivially high — the interesting rate is while a plan is live.
        strategy = ShadowStrategy(GreedyStrategy(), JaxPlacementStrategy())

    from modelmesh_tpu.serving.health import BootstrapProbation

    instance = ModelMeshInstance(
        store,
        loader,
        InstanceConfig(
            instance_id=args.instance_id,
            zone=envs.get("MM_ZONE") or "",
            labels=envs.get_list("MM_LABELS"),
            load_timeout_s=args.load_timeout_s,
        ),
        strategy=strategy,
        # Forward and FetchWeights share one channel cache: both internal
        # surfaces multiplex the same connection per peer.
        peer_call=make_grpc_peer_call(peer_channels := PeerChannels(tls)),
        peer_fetch=make_grpc_peer_fetch(peer_channels),
        metrics=metrics,
        constraints=constraints,
        upgrade_tracker=UpgradeTracker(),
        probation=BootstrapProbation.from_env(),
    )
    vmodels = VModelManager(instance)
    payload_proc = build_processor(envs.get_list("MM_PAYLOAD_PROCESSORS"))
    server = MeshServer(
        instance,
        port=args.port,
        vmodels=vmodels,
        advertise_host=args.advertise_host,
        payload_processor=payload_proc,
        tls=tls,
        frontdoor_port=(
            args.frontdoor_port if args.frontdoor_port >= 0 else None
        ),
    )
    instance.config.endpoint = server.endpoint
    instance.publish_instance_record(force=True)
    tasks = BackgroundTasks(instance)
    tasks.start()
    from modelmesh_tpu.serving.dynamic import ServingConfigBinder

    config_binder = ServingConfigBinder(
        store, instance.config.kv_prefix, instance, tasks.config
    )
    prestop = (
        PreStopServer(instance, port=max(args.prestop_port, 0))
        if args.prestop_port >= 0
        else None
    )
    if prestop is not None:
        log.info("lifecycle http (/ready /live /prestop) on :%d", prestop.port)
    register_static_models(instance, vmodels=vmodels)
    log.info(
        "instance %s serving on %s (kv=%s runtime=%s strategy=%s)",
        instance.instance_id, server.endpoint, args.kv, args.runtime,
        args.strategy,
    )
    print(f"READY {server.endpoint}", flush=True)

    stop = threading.Event()

    def on_term(signum, frame):
        log.info("signal %d: migrating and shutting down", signum)
        try:
            instance.pre_shutdown()
        finally:
            stop.set()

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    stop.wait()
    tasks.stop()
    config_binder.close()
    vmodels.close()
    server.stop()
    if prestop is not None:
        prestop.close()
    if watcher is not None:
        watcher.close()
    instance.shutdown()
    metrics.close()
    store.close()


if __name__ == "__main__":
    main()
