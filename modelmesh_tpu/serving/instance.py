"""ModelMeshInstance: the serving-instance core.

The equivalent of the reference's ModelMesh.java central class, decomposed:
this module owns local model lifecycle + request routing; background tasks
live in serving/tasks.py, the gRPC surfaces in serving/api.py, vmodels in
serving/vmodels.py.

Responsibilities (reference call stacks in SURVEY.md section 3):
- initialize: loader startup -> capacity; KV tables + views; instance
  session node; leader election                      (initialize :524)
- registerModel/unregisterModel/getStatus/ensureLoaded (:3074-3247)
- invoke_model: the routing uber-method — local fast path, cache-hit
  forwarding with exclusion lists, cache-miss placement + local load
  (invokeModel :3421-4001)
- load lifecycle: CAS registry placement, priority queue, space wait,
  sizing, failure bookkeeping                         (loadLocal :5028,
  CacheEntry.run :2145)
- eviction -> unload accounting + deregistration      (onEviction :2867)
- instance-record publishing with change suppression  (publishInstanceRecord
  :5391)
- shutdown migration: deregister, trigger copies elsewhere, drain
  (preShutdown :6959)
"""

from __future__ import annotations

import logging
import threading
import time as _time
import uuid
from typing import Callable, Optional

from modelmesh_tpu.cache.lru import WeightedLRUCache, now_ms
from modelmesh_tpu.kv.session import LeaderElection, SessionNode
from modelmesh_tpu.kv.store import CasFailed, KVStore
from modelmesh_tpu.kv.table import (
    BucketedKVTable,
    KVTable,
    TableEvent,
    TableView,
)
from modelmesh_tpu.placement.greedy import GreedyStrategy
from modelmesh_tpu.placement.strategy import (
    LOAD_HERE,
    ClusterView,
    PlacementRequest,
    PlacementStrategy,
)
from modelmesh_tpu.records import InstanceRecord, ModelRecord
from modelmesh_tpu.runtime.spi import (
    ModelInfo,
    ModelLoader,
    ModelLoadException,
    ModelNotLoadedError,
)
from modelmesh_tpu.serving.batching import BatchCancelled, RequestBatcher
from modelmesh_tpu.serving.entry import (
    CacheEntry,
    EntryState,
    PrioritizedLoadingPool,
    UnloadTracker,
    bytes_to_units,
)
from modelmesh_tpu.serving.errors import (
    ModelNotFoundError,
    ModelNotHereError,
    NoCapacityError,
    ReadOnlyModeError,
    RequestCancelledError,
    ServiceUnavailableError,
)
from modelmesh_tpu.observability.metrics import Metric as MX
from modelmesh_tpu.observability.tracing import Tracer, outgoing_headers
from modelmesh_tpu.serving.rate import RateTracker
from modelmesh_tpu.serving.route_cache import LoadFeedback, RouteCache
from modelmesh_tpu.utils.clock import get_clock
from modelmesh_tpu.utils.lockdebug import mm_lock
from modelmesh_tpu.utils.pool import BoundedDaemonPool

log = logging.getLogger(__name__)

MAX_ITERATIONS = 8          # routing loop bound (reference :283)
# Reject churn: when full, don't evict entries younger than this for a new
# load (reference minChurnAgeMs, :3872-3884).
DEFAULT_MIN_CHURN_AGE_MS = 60_000
# Backdate applied to explicit registrations so fresh-but-unused models are
# first victims (reference backdates 1h/6h, ModelMesh.java:3097-3147).
REGISTRATION_BACKDATE_MS = 3_600_000


class _NoPublishLease(Exception):
    """Session lease not live yet — the merged promote+publish txn cannot
    ride it; fall back to the plain promote CAS."""


class RoutingContext:
    """Per-request routing state (proto mesh_internal.RoutingContext)."""

    __slots__ = (
        "hop", "exclude_serve", "exclude_load", "visited",
        "dest_instance", "chain_load_count", "known_size_bytes",
        "last_used_ms", "cancel_event",
    )

    EXTERNAL = 0
    INTERNAL = 1
    HIT_ONLY = 2
    LOAD_LOCAL_ONLY = 3
    HOP_NAMES = ("external", "internal", "hit-only", "load-local")

    def __init__(
        self,
        hop: int = EXTERNAL,
        exclude_serve: Optional[set[str]] = None,
        exclude_load: Optional[set[str]] = None,
        visited: Optional[set[str]] = None,
        dest_instance: str = "",
        chain_load_count: int = 0,
        known_size_bytes: int = 0,
        last_used_ms: int = 0,
        cancel_event=None,
    ):
        self.hop = hop
        self.exclude_serve = exclude_serve or set()
        self.exclude_load = exclude_load or set()
        self.visited = visited or set()
        self.dest_instance = dest_instance
        self.chain_load_count = chain_load_count
        self.known_size_bytes = known_size_bytes
        self.last_used_ms = last_used_ms
        # threading.Event set when the external client disconnects; checked
        # on routing iterations and inside blocking waits so cancelled
        # requests stop consuming slots (ModelMeshApi.java:709-729).
        self.cancel_event = cancel_event

    @property
    def cancelled(self) -> bool:
        return self.cancel_event is not None and self.cancel_event.is_set()


class InvokeResult:
    __slots__ = ("payload", "served_by", "status", "feedback")

    def __init__(self, payload: bytes, served_by: str, status: str,
                 feedback=None):
        self.payload = payload
        self.served_by = served_by
        self.status = status
        # Piggybacked load feedback (route_cache.LoadFeedback) from the
        # IMMEDIATE peer a Forward was sent to — the mm-load response
        # trailer on the wire, attached directly by the sim/bench
        # transports. None on local results and feedback-less peers.
        self.feedback = feedback


# peer_call(instance_record.endpoint, model_id, method, payload, headers, ctx)
PeerCall = Callable[..., InvokeResult]


class InstanceConfig:
    def __init__(
        self,
        instance_id: Optional[str] = None,
        kv_prefix: str = "mm",
        endpoint: str = "",
        zone: str = "",
        location: str = "",
        labels: Optional[list[str]] = None,
        instance_version: str = "",
        load_timeout_s: Optional[float] = None,
        space_wait_s: float = 30.0,
        min_churn_age_ms: int = DEFAULT_MIN_CHURN_AGE_MS,
        publish_interval_s: float = 8.0,
        read_only: Optional[bool] = None,
        load_fastpath: Optional[bool] = None,
        publish_coalesce_ms: Optional[int] = None,
        peer_fetch: Optional[bool] = None,
        host_tier_bytes: Optional[int] = None,
        drain_on_sigterm: Optional[bool] = None,
        drain_timeout_ms: Optional[int] = None,
        trace_sample: Optional[int] = None,
        slo_spec: Optional[str] = None,
        slo_window_ms: Optional[int] = None,
        batch_max: Optional[int] = None,
        batch_window_us: Optional[int] = None,
        route_d: Optional[int] = None,
        feedback_decay_ms: Optional[int] = None,
        admission: Optional[bool] = None,
        admission_queue_ms: Optional[int] = None,
        sharded: Optional[bool] = None,
        sharded_max_shards: Optional[int] = None,
    ):
        self.instance_id = instance_id or f"i-{uuid.uuid4().hex[:8]}"  # analysis-ok: det-entropy — deliberately unique process identity; every replay-bearing path (sim, scenarios) passes an explicit instance_id
        self.kv_prefix = kv_prefix.rstrip("/")
        self.endpoint = endpoint
        self.zone = zone
        self.location = location
        self.labels = labels or []
        self.instance_version = instance_version
        self.load_timeout_s = load_timeout_s
        self.space_wait_s = space_wait_s
        self.min_churn_age_ms = min_churn_age_ms
        self.publish_interval_s = publish_interval_s
        # KV-migration read-only mode (reference readOnlyMode,
        # ModelMesh.java:200-204): registry mutations are blocked and
        # reaper pruning suppressed while the operator migrates between
        # disjoint KV stores (copies registered in the OTHER store look
        # like dead instances from here and must not be pruned).
        if read_only is None:
            from modelmesh_tpu.utils import envs

            read_only = bool(envs.get_int("MM_KV_READ_ONLY"))
        self.read_only = read_only
        # Cold-start/scale-up fast path (MM_LOAD_FASTPATH): activate an
        # entry as soon as the runtime load returns (sizing becomes an
        # overlapped follow-up correction) and fan secondary chained
        # copies out concurrently at claim time instead of hop-by-hop
        # after each completion.
        if load_fastpath is None:
            from modelmesh_tpu.utils import envs

            load_fastpath = envs.get_bool("MM_LOAD_FASTPATH")
        self.load_fastpath = load_fastpath
        # Trailing-flush window for NON-forced instance-record publishes
        # (MM_PUBLISH_COALESCE_MS, 0 = publish inline): a mass load/unload
        # storm collapses its O(models) advertisement refreshes into O(1)
        # KV puts. force=True always bypasses.
        if publish_coalesce_ms is None:
            from modelmesh_tpu.utils import envs

            publish_coalesce_ms = envs.get_int("MM_PUBLISH_COALESCE_MS")
        self.publish_coalesce_ms = publish_coalesce_ms
        # Live scale-up transfer path (transfer/): peer-to-peer weight
        # streaming (MM_PEER_FETCH) and the host-RAM staging tier budget
        # (MM_HOST_TIER_BYTES, 0 disables the tier). Both are inert
        # unless the loader declares supports_weight_streaming. (Chunk
        # granularity, MM_TRANSFER_CHUNK_BYTES, belongs to the exporting
        # loader's serialization — no per-instance knob here.)
        from modelmesh_tpu.utils import envs as _envs

        if peer_fetch is None:
            peer_fetch = _envs.get_bool("MM_PEER_FETCH")
        self.peer_fetch = peer_fetch
        if host_tier_bytes is None:
            host_tier_bytes = _envs.get_int("MM_HOST_TIER_BYTES")
        self.host_tier_bytes = host_tier_bytes
        # Graceful drain (reconfig/drain.py): pre_shutdown runs the
        # DrainController (DRAINING advertisement, survivor pre-copy over
        # the transfer path, then deregister) instead of the legacy
        # immediate shutting_down migration. MM_DRAIN_TIMEOUT_MS bounds
        # the pre-copy pass.
        if drain_on_sigterm is None:
            drain_on_sigterm = _envs.get_bool("MM_DRAIN_ON_SIGTERM")
        self.drain_on_sigterm = drain_on_sigterm
        if drain_timeout_ms is None:
            drain_timeout_ms = _envs.get_int("MM_DRAIN_TIMEOUT_MS")
        self.drain_timeout_ms = drain_timeout_ms
        # Observability substrate: head-sampling for minted trace roots
        # (MM_TRACE_SAMPLE; 1 = trace every request — the sim pins this
        # so scenario assertions are deterministic) and the declarative
        # per-model-class SLO spec (MM_SLO_SPEC grammar,
        # observability/slo.py).
        if trace_sample is None:
            trace_sample = _envs.get_int("MM_TRACE_SAMPLE")
        self.trace_sample = trace_sample
        if slo_spec is None:
            slo_spec = _envs.get("MM_SLO_SPEC")
        self.slo_spec = slo_spec
        # Sliding attainment window (MM_SLO_WINDOW_MS). Overridable per
        # instance so sims/benches can judge burn over their own (much
        # shorter) timelines without touching process env state.
        if slo_window_ms is None:
            slo_window_ms = _envs.get_int("MM_SLO_WINDOW_MS")
        self.slo_window_ms = slo_window_ms
        # Batched data plane (serving/batching.py): continuous-batching
        # micro-batch queue in front of the runtime call. batch_max <= 1
        # disables the queue; the window (µs) bounds how long a batch
        # leader waits for the batch to fill (0 = dispatch immediately —
        # batches still form behind in-flight dispatches). Only engaged
        # when the loader really batches (supports_batched_dispatch) or
        # a batched runtime call is injected.
        if batch_max is None:
            batch_max = _envs.get_int("MM_BATCH_MAX")
        self.batch_max = batch_max
        if batch_window_us is None:
            batch_window_us = _envs.get_int("MM_BATCH_WINDOW_US")
        self.batch_window_us = batch_window_us
        # Load-aware fused routing (serving/route_cache.py): candidate
        # sampled per pick (MM_ROUTE_D; 1 = the old single-winner cache,
        # regression-pinned) and the staleness horizon for piggybacked
        # load feedback (MM_FEEDBACK_DECAY_MS). Admission control
        # (serving/admission.py): SLO-burn-modulated per-class shedding
        # at the external edge (MM_ADMISSION, default off) with a
        # bounded pre-shed queue window (MM_ADMISSION_QUEUE_MS).
        if route_d is None:
            route_d = _envs.get_int("MM_ROUTE_D")
        self.route_d = route_d
        if feedback_decay_ms is None:
            feedback_decay_ms = _envs.get_int("MM_FEEDBACK_DECAY_MS")
        self.feedback_decay_ms = feedback_decay_ms
        if admission is None:
            admission = _envs.get_bool("MM_ADMISSION")
        self.admission = admission
        if admission_queue_ms is None:
            admission_queue_ms = _envs.get_int("MM_ADMISSION_QUEUE_MS")
        self.admission_queue_ms = admission_queue_ms
        # Sharded execution (MM_SHARDED): models too big for any single
        # instance place as multi-instance GROUPS (one weight shard per
        # member, SHARDED entry state; routing targets only complete
        # groups). MM_SHARDED_MAX_SHARDS bounds the group width. Inert
        # unless the loader declares supports_sharded_execution.
        if sharded is None:
            sharded = _envs.get_bool("MM_SHARDED")
        self.sharded = sharded
        if sharded_max_shards is None:
            sharded_max_shards = _envs.get_int("MM_SHARDED_MAX_SHARDS")
        self.sharded_max_shards = sharded_max_shards


class ModelMeshInstance:
    def __init__(
        self,
        store: KVStore,
        loader: ModelLoader,
        config: Optional[InstanceConfig] = None,
        strategy: Optional[PlacementStrategy] = None,
        peer_call: Optional[PeerCall] = None,
        runtime_call: Optional[Callable[..., bytes]] = None,
        metrics=None,
        constraints=None,
        upgrade_tracker=None,
        probation=None,
        peer_fetch=None,
        runtime_call_batch=None,
    ):
        """``peer_call(endpoint, model_id, method, payload, headers, ctx)``
        forwards to a peer (gRPC in production, direct-call in tests).
        ``runtime_call(entry, method, payload, headers, cancel_event=None)``
        executes inference against the local runtime (defaults to
        SidecarRuntime.call_model when the loader is a SidecarRuntime); a
        callable without the cancel_event parameter is still accepted —
        cancellation then can't interrupt the call itself, only the waits
        around it. ``runtime_call_batch(items, cancel_event=None) ->
        list[bytes | Exception]`` executes a whole micro-batch (aligned
        results; Exception entries fail individual items) — when given,
        or when the loader declares ``supports_batched_dispatch``, the
        continuous-batching queue (serving/batching.py) engages in front
        of the runtime call. ``peer_fetch(endpoint, model_id, chunk_index,
        fingerprint) -> FetchReply`` pulls one weight chunk from a peer
        (the mesh-internal FetchWeights channel; gRPC in production,
        direct-call in the sim/bench) — None disables peer streaming on
        this instance regardless of config.peer_fetch."""
        self.config = config or InstanceConfig()
        self.instance_id = self.config.instance_id
        self.load_fastpath = self.config.load_fastpath
        self.store = store
        self.loader = loader
        self.strategy = strategy or GreedyStrategy()
        self._peer_call = peer_call
        self._runtime_call = runtime_call or self._default_runtime_call
        import inspect as _inspect

        try:
            self._runtime_call_cancellable = (
                "cancel_event"
                in _inspect.signature(self._runtime_call).parameters
            )
        except (TypeError, ValueError):
            self._runtime_call_cancellable = False
        self.shutting_down = False
        # Admin drain via dynamic config `disable` (ModelMesh.java:1008-1061):
        # stop taking NEW loads/placements; keep serving what's loaded.
        self.disabled = False
        # Graceful drain in progress (reconfig/drain.py): advertised in
        # the instance record so peers stop placing here and deprioritize
        # us as a serve target while the drain pre-copies to survivors.
        # Written only through set_draining so every drain-state flip
        # lands in the flight recorder (state-funnel rule).
        #: state-funnel: set_draining
        self.draining = False
        # Dynamic config `log_each_invocation`.
        self.log_each_invocation = False
        self.is_leader = False
        if metrics is None:
            from modelmesh_tpu.observability.metrics import NoopMetrics

            metrics = NoopMetrics()
        self.metrics = metrics
        # Optional placement filters (serving/constraints.py): model-type ->
        # label requirements, and rolling-update replicaset avoidance.
        self.constraints = constraints
        self.upgrade_tracker = upgrade_tracker
        # Bootstrap fail-fast (serving/health.py): early load outcomes are
        # reported while the probation window is armed. The window is
        # re-stamped after loader.startup() below (it can block for minutes
        # on a cold accelerator claim).
        self.probation = probation

        params = loader.startup()
        if probation is not None:
            probation.reset_window()
        self.params = params
        self.load_timeout_s = (
            self.config.load_timeout_s
            if self.config.load_timeout_s is not None
            else params.load_timeout_ms / 1000.0
        )

        from modelmesh_tpu.observability.flightrec import FlightRecorder
        from modelmesh_tpu.observability.slo import SloTracker
        from modelmesh_tpu.serving.timestats import TimeStats

        # A Noop backend gets no sink at all: the SLO tracker's amortized
        # gauge export sorts its window, and the tracer's stage lookup is
        # a dict probe per span — neither belongs on the hot path when
        # nothing renders the result.
        from modelmesh_tpu.observability.metrics import NoopMetrics as _Noop

        sink = None if isinstance(self.metrics, _Noop) else self.metrics
        self.tracer = Tracer(
            self.instance_id, metrics=sink,
            sample_n=self.config.trace_sample,
        )
        self.flightrec = FlightRecorder(instance_id=self.instance_id)
        self.slo = SloTracker(
            spec=self.config.slo_spec, metrics=sink,
            window_ms=self.config.slo_window_ms,
        )
        self.time_stats = TimeStats()
        # Strategies that accept per-type load-time stats (greedy's warming
        # penalty and wait-vs-reroute bound) get this instance's tracker.
        for strat in (self.strategy, getattr(self.strategy, "fallback", None)):
            if strat is None:
                continue
            if hasattr(strat, "time_stats"):
                strat.time_stats = self.time_stats
            if hasattr(strat, "constraints") and strat.constraints is None:
                strat.constraints = self.constraints

        self.cache: WeightedLRUCache[str, CacheEntry] = WeightedLRUCache(
            params.capacity_units, eviction_listener=self._on_eviction
        )
        self.unload_tracker = UnloadTracker(params.capacity_units)
        self.loading_pool = PrioritizedLoadingPool(params.load_concurrency)
        # Bounded pools for janitorial work: a mass unregistration of a
        # full cache must queue behind a few workers, not spawn a thread
        # per model (reference ModelMesh.java:2807-2814 uses a shared
        # pool). Two pools, not one — unload tasks release
        # unload_tracker reservations that wait_for_space depends on, so
        # they must never queue behind KV-heavy deletion cleanups during
        # an outage (KV RPCs are 10 s-deadline-bounded, runtime UnloadModel
        # 30 s-bounded, and CAS loops give up, so tasks cannot wedge
        # forever — but head-of-line delay on accounting would still fail
        # unrelated loads). Daemon workers: a task stuck on a dying KV
        # must not block interpreter exit.
        self._cleanup_pool = BoundedDaemonPool(max_workers=4, name="del-clean")
        self._unload_pool = BoundedDaemonPool(max_workers=4, name="unloads")
        self.rate = RateTracker()
        #: guarded-by: _model_rates_lock
        self._model_rates: dict[str, RateTracker] = {}
        self._model_rates_lock = mm_lock("ModelMeshInstance._model_rates_lock")
        # model_id -> failfast-until timestamp (KV-outage sentinels).
        #: shared-ok: GIL-atomic sentinel map; a lost prune/insert costs one extra registry probe, never a wrong answer
        self._kv_failfast: dict[str, int] = {}
        # Request-path fast path: the epoch-keyed ClusterView snapshot
        # (rebuilt only when the instances view moves) and the per-model
        # candidate-set route memo (serving/route_cache.py) with its
        # load-feedback view. Created before the registry listener below
        # is registered — it invalidates through this cache. The
        # d-choices sampler seed derives from the instance id:
        # deterministic per pod (sim replay) but spread across a fleet.
        import zlib as _zlib

        self.route_cache = RouteCache(
            route_d=self.config.route_d,
            feedback_decay_ms=self.config.feedback_decay_ms,
            seed=_zlib.crc32(self.instance_id.encode()),
        )
        #: shared-ok: benign last-writer-wins memo — concurrent rebuilds install equally-fresh views (see cluster_view)
        self._cluster_view_cache: Optional[ClusterView] = None
        # Local in-flight gauge for the piggybacked feedback trailer:
        # requests currently executing against THIS runtime (between the
        # concurrency-gate acquire and release in _invoke_local). A
        # dedicated lock, not a racy int — feedback drift would
        # permanently skew peers' view of us.
        self._inflight = 0  #: guarded-by: _inflight_lock
        self._inflight_lock = mm_lock("ModelMeshInstance._inflight_lock")
        # Admission controller at the external edge (serving/
        # admission.py): priorities and burn rates come from THIS
        # instance's SLO tracker; sheds are typed, counted, and flight-
        # recorded. Off (the default) it is a single attribute check.
        from modelmesh_tpu.serving.admission import AdmissionController

        self.admission_controller = AdmissionController(
            self.slo,
            enabled=self.config.admission,
            queue_ms=self.config.admission_queue_ms,
            metrics=sink,
            flightrec=self.flightrec,
        )

        # Weight-transfer subsystem (transfer/): host-RAM staging tier +
        # peer-to-peer streaming manager. The host-tier eviction listener
        # only SCHEDULES the registry host-claim cleanup — it runs under
        # the tier's lock.
        from modelmesh_tpu.cache.lru import HostTier
        from modelmesh_tpu.transfer.manager import (
            TransferConfig,
            WeightTransferManager,
        )

        self.transfer_config = TransferConfig(
            peer_fetch=self.config.peer_fetch,
            host_tier_bytes=self.config.host_tier_bytes,
        )
        self.host_tier = HostTier(
            self.transfer_config.host_tier_bytes,
            eviction_listener=self._on_host_tier_evict,
        )
        self.peer_fetch_transport = peer_fetch
        self.transfer = WeightTransferManager(self)

        # Batched data plane (serving/batching.py): engaged only when
        # there is a REAL batched dispatch to gain from — an injected
        # runtime_call_batch (sim/bench twins) or a loader whose
        # call_model_batch executes the micro-batch as one kernel
        # (models/server.py). A loader whose batch path merely loops
        # over singles would SERIALIZE what used to run concurrently,
        # so it keeps the classic one-at-a-time path.
        self._runtime_call_batch = runtime_call_batch or (
            loader.call_model_batch
            if getattr(loader, "supports_batched_dispatch", False)
            else None
        )
        self.batcher: Optional[RequestBatcher] = None
        if self._runtime_call_batch is not None and self.config.batch_max > 1:
            self.batcher = RequestBatcher(
                self._batch_call_one,
                self._batch_call_many,
                group_key=getattr(loader, "batch_group_key", None),
                batch_max=self.config.batch_max,
                window_us=self.config.batch_window_us,
                metrics=self.metrics,
                flightrec=self.flightrec,
            )

        prefix = self.config.kv_prefix
        # Live registry-migration fence (kv/migrate.py): while an
        # operator-run flat->bucketed migration advertises its epoch,
        # the registry table dual-reads (bucketed preferred) and every
        # CAS against a flat-read record moves it — the fleet keeps
        # serving through the layout change.
        from modelmesh_tpu.kv.migrate import MigrationFence

        self.migration_fence = MigrationFence(store, prefix)
        # Bucketed (128): scans page bucket-by-bucket so no range RPC
        # carries the whole 100k-model registry (reference ModelMesh.java:169).
        self.registry: KVTable[ModelRecord] = BucketedKVTable(
            store, f"{prefix}/registry", ModelRecord,
            migration_fence=self.migration_fence,
        )
        self.registry_view: TableView[ModelRecord] = TableView(self.registry)
        self.instances: KVTable[InstanceRecord] = KVTable(
            store, f"{prefix}/instances", InstanceRecord
        )
        self.instances_view: TableView[InstanceRecord] = TableView(self.instances)

        # Cached self-advertisement, reused as the cluster-view fallback
        # until our published record round-trips through the watch —
        # refreshed only on publish, not rebuilt per request. Rebinds are
        # guarded; lock-free reads (cluster_view) see old-or-new whole.
        #: guarded-by: _publish_lock [rebind]
        self._self_record = self._build_instance_record()
        self._session = SessionNode(
            store,
            f"{prefix}/instances/{self.instance_id}",
            self._self_record.to_bytes(),
            ttl_s=10.0,
        )
        self._session.start()
        self._election = LeaderElection(
            store, f"{prefix}/leader", self.instance_id, self._on_leader_change
        )
        self._election.start()
        # Fleet-wide plan distribution: any strategy that can adopt a
        # published GlobalPlan (the JAX strategy) follows the leader's
        # solves via a KV watch — non-leaders serve the central plan too,
        # not just the process that happened to solve it.
        self._plan_follower = None
        if hasattr(self.strategy, "adopt"):
            from modelmesh_tpu.placement.plan_sync import PlanFollower

            self._plan_follower = PlanFollower(store, prefix, self.strategy)
        self._publish_lock = mm_lock("ModelMeshInstance._publish_lock")
        # Serializes standalone advertisement puts in BUILD order (see
        # _publish_now). Only publishers ever take it — never the load
        # or request paths — so a wedged KV round trip convoys at most
        # other publishers, exactly like the pre-fast-path behavior.
        self._publish_io_lock = mm_lock("ModelMeshInstance._publish_io_lock")
        #: guarded-by: _publish_lock
        self._last_published: Optional[InstanceRecord] = None
        # Publish coalescer state (trailing-flush window; see
        # publish_instance_record).
        self._coalesce_lock = mm_lock("ModelMeshInstance._coalesce_lock")
        # cancel()-able one-shot from Clock.call_later (threading.Timer or
        # a virtual timer handle).
        self._publish_timer = None  #: guarded-by: _coalesce_lock
        self._shutdown_publishes = False  #: guarded-by: _coalesce_lock
        # Watch-driven deletion cleanup (reference registers a registry
        # listener at ModelMesh.java:629; the deletion handler at :2807
        # removes local copies at :2814): when a model is unregistered
        # ANYWHERE, every holder drops its copy within watch latency
        # instead of serving a deleted model until the next janitor pass.
        self.registry_view.add_listener(self._on_registry_event)
        log.info(
            "instance %s up: %d units capacity, %d load threads",
            self.instance_id, params.capacity_units, params.load_concurrency,
        )

    # ------------------------------------------------------------------ #
    # cluster views                                                      #
    # ------------------------------------------------------------------ #

    def cluster_view(self) -> ClusterView:
        """Epoch-cached immutable snapshot: the instances table is copied
        only when the watch-fed view actually moved, not per request —
        steady-state routing shares one ClusterView object (and its
        cached live/placeable/live_map derivations) across requests."""
        view = self._cluster_view_cache
        if view is not None and view.epoch == self.instances_view.epoch:
            return view
        epoch, items = self.instances_view.snapshot()
        self_rec = None
        if not any(iid == self.instance_id for iid, _ in items):
            # A node always knows itself: right after startup our own
            # published record may not have round-tripped through the async
            # KV watch yet, and an empty view would make placement reject
            # the first request (NoCapacityError) instead of loading here.
            # The fallback record is the cached self-advertisement
            # (refreshed on publish), not a per-request rebuild.
            self_rec = self._self_record
            items.append((self.instance_id, self_rec))
        view = ClusterView(instances=tuple(items), epoch=epoch)
        # Benign race: concurrent rebuilds both install a view at-least-as
        # fresh as the epoch they recorded; last writer wins.
        self._cluster_view_cache = view
        if self_rec is not None and self_rec is not self._self_record:
            # A publish slipped between reading the fallback and installing
            # the view; its cache invalidation may have fired BEFORE our
            # install and been overwritten (the epoch alone can't catch
            # this — our own unreflected publishes don't move it). Drop
            # the just-installed view; every interleaving converges: a
            # publish after this re-check invalidates after our install.
            self._cluster_view_cache = None
        return view

    # KV outage fail-fast: after a registry read error, requests for THAT
    # model fail immediately (UNAVAILABLE) for a cooldown window instead of
    # hammering the dead store, then self-heal — per-model sentinels like
    # the reference's KVSTORE_LOAD_FAILURE cache entries
    # (ModelMesh.java:5295-5350). Models already in the local cache or the
    # watch-fed view are unaffected (serving continues through an outage).
    KV_FAILFAST_COOLDOWN_MS = 30_000

    def _registry_get_failfast(self, model_id: str):
        until = self._kv_failfast.get(model_id, 0)
        if now_ms() < until:
            raise ServiceUnavailableError(
                f"registry unavailable for {model_id} (cooling down)"
            )
        try:
            mr = self.registry.get(model_id)
            self._kv_failfast.pop(model_id, None)
            return mr
        except Exception as e:  # noqa: BLE001 — any store error trips it
            now = now_ms()
            # Prune expired sentinels on insert so externally-driven id
            # churn can't grow the dict without bound.
            if len(self._kv_failfast) > 1024:
                self._kv_failfast = {
                    k: v for k, v in self._kv_failfast.items() if v > now
                }
            self._kv_failfast[model_id] = now + self.KV_FAILFAST_COOLDOWN_MS
            log.error("registry read of %s failed; failing fast for %ds: %s",
                      model_id, self.KV_FAILFAST_COOLDOWN_MS // 1000, e)
            raise ServiceUnavailableError(f"registry unavailable: {e}") from e

    def _model_rate(self, model_id: str) -> RateTracker:
        with self._model_rates_lock:
            rt = self._model_rates.get(model_id)
            if rt is None:
                rt = self._model_rates[model_id] = RateTracker()
            return rt

    def model_rpm(self, model_id: str, window_minutes: int = 5) -> int:
        with self._model_rates_lock:
            rt = self._model_rates.get(model_id)
        return rt.rpm(window_minutes) if rt else 0

    def _drop_model_rate(self, model_id: str) -> None:
        with self._model_rates_lock:
            self._model_rates.pop(model_id, None)

    def _on_leader_change(self, is_leader: bool) -> None:
        self.is_leader = is_leader
        log.info("instance %s leader=%s", self.instance_id, is_leader)

    # ------------------------------------------------------------------ #
    # instance record publishing                                         #
    # ------------------------------------------------------------------ #

    def _build_instance_record(self) -> InstanceRecord:
        cache = getattr(self, "cache", None)
        return InstanceRecord(
            start_ts=now_ms(),
            lru_ts=(cache.oldest_time() or 0) if cache else 0,
            model_count=len(cache) if cache else 0,
            capacity_units=self.params.capacity_units if hasattr(self, "params") else 0,
            used_units=(cache.weight if cache else 0)
            + (self.unload_tracker.pending_units if hasattr(self, "unload_tracker") else 0),
            loading_in_progress=0,
            req_per_minute=self.rate.rpm() if hasattr(self, "rate") else 0,
            shutting_down=self.shutting_down,
            disabled=self.disabled,
            draining=self.draining,
            endpoint=self.config.endpoint,
            location=self.config.location,
            zone=self.config.zone,
            labels=list(self.config.labels),
            instance_version=self.config.instance_version,
        )

    def set_draining(self, value: bool) -> None:
        """The ONE write funnel for the ``draining`` flag (state-funnel
        rule, like ``CacheEntry._transition_locked``): every drain-state
        flip lands in the flight recorder, so a shutdown investigation
        can see exactly when the instance stopped accepting placements.
        Callers publish the record themselves — flipping and advertising
        are separate steps by design (the drain controller forces the
        publish so the epoch bump is immediate)."""
        prev = self.draining
        self.draining = value
        if prev != value:
            self.flightrec.record("drain-flag", to=str(value).lower())

    def publish_instance_record(self, force: bool = False) -> None:
        """Refresh our advertisement; suppress no-op updates (reference
        change-suppression, ModelMesh.java:5440-5468).

        Non-forced publishes coalesce behind a trailing-flush window
        (``publish_coalesce_ms``): the first request arms a one-shot
        flush timer, later requests inside the window ride it, and the
        flush publishes the freshest record — a mass load/unload storm
        issues O(1) puts instead of O(models). ``force=True`` bypasses
        the window (and disarms any pending flush: the forced publish
        already carries the freshest state)."""
        window_ms = self.config.publish_coalesce_ms
        if not force and window_ms > 0:
            with self._coalesce_lock:
                if self._shutdown_publishes:
                    return
                if self._publish_timer is None:
                    # Clock-injected one-shot: a threading.Timer under
                    # SystemClock; a virtual-deadline timer under the sim.
                    self._publish_timer = get_clock().call_later(
                        window_ms / 1000.0, self._publish_flush,
                        name="publish-coalesce",
                    )
            return
        if force:
            with self._coalesce_lock:
                t, self._publish_timer = self._publish_timer, None
            if t is not None:
                t.cancel()
        self._publish_now(force)

    def _publish_flush(self) -> None:
        """Trailing edge of the coalesce window (timer thread)."""
        with self._coalesce_lock:
            self._publish_timer = None
        try:
            self._publish_now(force=False)
        except Exception:  # noqa: BLE001 — periodic publisher will retry
            log.warning("coalesced publish flush failed", exc_info=True)

    def _build_publish_record_locked(self) -> InstanceRecord:
        """Build the advertisement (start_ts carried from the last
        publish) and refresh the cluster-view self-fallback — on every
        publish ATTEMPT, suppressed/coalesced or not: the fallback should
        carry the freshest self-observation without per-request rebuilds,
        and the cached view must be dropped too — while the fallback is
        in use (our record not yet in the watch-fed table) our own
        publishes don't move the table epoch, so the epoch check alone
        would pin the startup-era self record indefinitely. Shared by
        the standalone publish and the promote-piggybacked publish so
        the bookkeeping cannot fork. Callers hold _publish_lock."""
        rec = self._build_instance_record()
        prev = self._last_published
        if prev is not None:
            rec.start_ts = prev.start_ts
        self._self_record = rec
        self._cluster_view_cache = None
        return rec

    def _publish_now(self, force: bool = False) -> None:
        # The KV put runs OUTSIDE _publish_lock (the PR-3 promote-txn
        # rule generalized): a slow advertisement round trip must not
        # convoy load completions (_promote_loaded's bookkeeping) or the
        # record-build fast path on that lock — it guards only the
        # suppression/self-record bookkeeping. Publishers instead
        # serialize with EACH OTHER on _publish_io_lock, taken BEFORE the
        # build: build order == put order == install order, so the final
        # KV state and the _last_published suppression reference always
        # carry the newest build (two racing publishers can never commit
        # out of order and then suppress the repair forever).
        with self._publish_io_lock:
            with self._publish_lock:
                prev = self._last_published
                rec = self._build_publish_record_locked()
                if not force and prev is not None and self._adverts_close(
                    prev, rec
                ):
                    # Suppression cross-check: _promote_loaded's
                    # piggybacked publish commits OUTSIDE the io lock
                    # (its txn must never convoy on a wedged
                    # advertisement put), so an interleave can leave the
                    # committed KV record older than _last_published.
                    # Before suppressing, verify the advertisement the
                    # cluster actually sees (watch-fed self record)
                    # matches too — if it diverged, OR the record is
                    # gone entirely (an expired/deleted ephemeral the
                    # watch reported), publish to repair instead of
                    # suppressing the repair forever. `seen is None`
                    # before the first publish round-trips the watch
                    # just costs a redundant put in a tiny window.
                    seen = self.instances_view.get(self.instance_id)
                    if seen is not None and self._adverts_close(seen, rec):
                        return
            self._session.update(rec.to_bytes())  # analysis-ok: blocking-under-lock — _publish_io_lock exists to serialize advertisement puts in build order; only publishers take it, never the load/request path
            with self._publish_lock:
                self._last_published = rec
        self._publish_gauges()

    @staticmethod
    def _adverts_close(prev: InstanceRecord, rec: InstanceRecord) -> bool:
        """Change-suppression equivalence for two advertisements
        (reference ModelMesh.java:5440-5468): no material movement in
        the fields placement decisions read."""
        return (
            prev.model_count == rec.model_count
            and abs(prev.used_units - rec.used_units) < 8
            and prev.shutting_down == rec.shutting_down
            and abs(prev.req_per_minute - rec.req_per_minute)
            < max(10, prev.req_per_minute // 10)
        )

    def _publish_gauges(self) -> None:
        self.metrics.set_gauge(MX.MODELS_LOADED, len(self.cache))
        self.metrics.set_gauge(MX.CACHE_USED_UNITS, self.cache.weight)
        self.metrics.set_gauge(MX.CACHE_CAPACITY_UNITS, self.cache.capacity)
        self.metrics.set_gauge(
            MX.PENDING_UNLOAD_UNITS, self.unload_tracker.pending_units
        )
        self.metrics.set_gauge(MX.INSTANCE_RPM, self.rate.rpm())
        oldest = self.cache.oldest_time()
        self.metrics.set_gauge(
            MX.LRU_AGE_SECONDS, (now_ms() - oldest) / 1000.0 if oldest else 0
        )
        # Load-feedback view (route_cache.LoadView): per-peer decayed
        # scores + worst staleness, exported on the publisher cadence —
        # never from the request path. Prune fully-decayed slots first
        # AND retire their gauge series: rolling restarts mint fresh
        # instance ids, and either the map or the exported series would
        # otherwise grow without bound.
        lv = self.route_cache.load_view
        now = now_ms()
        for iid in lv.prune(now):
            self.metrics.clear_gauge(
                MX.ROUTE_LOAD_SCORE, label=f'instance="{iid}"'
            )
        for iid in list(lv._slots):
            self.metrics.set_gauge(
                MX.ROUTE_LOAD_SCORE, round(lv.score(iid, now), 3),
                label=f'instance="{iid}"',
            )
        stale = lv.staleness_ms(now)
        self.metrics.set_gauge(
            MX.ROUTE_FEEDBACK_AGE_MS, stale if stale is not None else 0
        )
        # Sharded placement groups this instance participates in, and how
        # many of those are incomplete (routing blocked until the group
        # fills) — per the local registry view, so the numbers converge
        # within watch latency rather than costing KV reads per scrape.
        groups = incomplete = 0
        for _mid, ce, _ts in self.cache.descending_items():
            if not ce.is_shard:
                continue
            groups += 1
            gmr = self.registry_view.get(ce.model_id)
            if gmr is None or not gmr.group_complete:
                incomplete += 1
        self.metrics.set_gauge(MX.SHARDED_GROUP_COUNT, groups)
        self.metrics.set_gauge(MX.SHARDED_GROUP_INCOMPLETE, incomplete)

    # ------------------------------------------------------------------ #
    # management API                                                     #
    # ------------------------------------------------------------------ #

    def register_model(
        self, model_id: str, info: ModelInfo, load_now: bool = False,
        sync: bool = False,
    ) -> ModelRecord:
        if self.config.read_only:
            # Migration read-only mode: re-register of an EXISTING model is
            # tolerated as a no-op read (reference: the existing-record
            # branch skips the readOnly rejection, ModelMesh.java:3112-3131);
            # creating a NEW record is rejected.
            existing = self.registry.get(model_id)
            if existing is None:
                raise ReadOnlyModeError(
                    f"registerModel({model_id}) rejected in read-only mode"
                )
            log.warning(
                "read-only mode: registerModel(%s) served as no-op", model_id
            )
            if load_now:
                self.ensure_loaded(model_id, sync=sync)
                existing = self.registry.get(model_id) or existing
            return existing

        def create(cur: Optional[ModelRecord]) -> ModelRecord:
            if cur is not None:
                # Idempotent re-register with same info keeps the record.
                cur.model_type = info.model_type
                cur.model_path = info.model_path
                cur.model_key = info.model_key
                return cur
            mr = ModelRecord(
                model_type=info.model_type,
                model_path=info.model_path,
                model_key=info.model_key,
                last_used=now_ms() - REGISTRATION_BACKDATE_MS,
            )
            return mr

        mr = self.registry.update_or_create(model_id, create)
        if load_now:
            self.ensure_loaded(model_id, sync=sync)
            mr = self.registry.get(model_id) or mr
        return mr

    def unregister_model(self, model_id: str) -> bool:
        if self.config.read_only:
            raise ReadOnlyModeError(
                f"unregisterModel({model_id}) rejected in read-only mode"
            )
        mr = self.registry.get(model_id)
        if mr is None:
            return False
        # Evict local copy first, then remove the registration. Remote
        # holders clean up via the registry deletion watch
        # (_on_registry_event) within watch latency — the analog of the
        # reference's registry-listener deletion handler
        # (ModelMesh.java:2807-2814).
        self._remove_local(model_id)
        return self.registry.delete(model_id)

    def get_status(self, model_id: str) -> tuple[str, ModelRecord | None]:
        """-> (status, record): status in NOT_FOUND/NOT_LOADED/LOADING/
        LOADED/LOADING_FAILED."""
        ce = self.cache.get_quietly(model_id)
        # Authoritative read: the watch-fed view lags mutations (e.g. an
        # unregister a moment ago would still show LOADED); management
        # status RPCs are rare enough to pay the direct KV get.
        mr = self.registry.get(model_id)
        if mr is None:
            return "NOT_FOUND", None
        if mr.shard_count:
            # Sharded group: LOADED means the WHOLE group is complete and
            # live — a single landed shard (even ours) is not servable.
            live = {iid for iid, _ in self.instances_view.items()}
            held = {
                idx for iid, idx in mr.shard_instances.items()
                if iid in mr.instance_ids and iid in live
            }
            if held >= set(range(mr.shard_count)):
                return "LOADED", mr
            if any(iid in live for iid in mr.loading_instances):
                return "LOADING", mr
            if mr.load_exhausted():
                return "LOADING_FAILED", mr
            return "NOT_LOADED", mr
        if ce is not None and ce.state.is_servable:
            # PARTIAL counts as LOADED: the copy is admitting requests.
            return "LOADED", mr
        if ce is not None and ce.state.is_loading:
            return "LOADING", mr
        # Cross-check placements against LIVE instances: a record whose
        # every holder died seconds ago must not report LOADED for up to
        # the 10-min reaper prune (round-1 verdict weak item 6; the
        # reference checks liveness in getStatus).
        live = {iid for iid, _ in self.instances_view.items()}
        if any(iid in live for iid in mr.instance_ids):
            return "LOADED", mr
        if any(iid in live for iid in mr.loading_instances):
            return "LOADING", mr
        if mr.load_exhausted():
            return "LOADING_FAILED", mr
        return "NOT_LOADED", mr

    def ensure_loaded(
        self, model_id: str, last_used_ms: int = 0, sync: bool = False,
        exclude: Optional[set[str]] = None, chain: int = 0,
    ) -> str:
        """Place/load a copy somewhere (no inference). Returns final status."""
        ctx = RoutingContext(
            hop=RoutingContext.INTERNAL,
            exclude_load=set(exclude or ()),
            last_used_ms=last_used_ms or now_ms(),
            chain_load_count=chain,
        )
        result = self.invoke_model(model_id, None, b"", [], ctx, sync=sync)
        return result.status

    # ------------------------------------------------------------------ #
    # the routing uber-method                                            #
    # ------------------------------------------------------------------ #

    def invoke_model(
        self,
        model_id: str,
        method: Optional[str],
        payload: bytes,
        headers: list[tuple[str, str]],
        ctx: Optional[RoutingContext] = None,
        sync: bool = True,
    ) -> InvokeResult:
        ctx = ctx or RoutingContext()
        ctx.visited.add(self.instance_id)
        # Per-request thread renaming (reference names handler threads
        # invoke-<hoptype>-<modelId>, ModelMesh.java:3462) — makes py-spy /
        # faulthandler / load-timeout stack dumps self-describing. Restored
        # on exit: gRPC server threads are pooled.
        _thread = threading.current_thread()
        _prev_name = _thread.name
        hop_name = (
            RoutingContext.HOP_NAMES[ctx.hop]
            if 0 <= ctx.hop < len(RoutingContext.HOP_NAMES)
            else str(ctx.hop)
        )
        _thread.name = f"invoke-{hop_name}-{model_id}"
        try:
            if ctx.hop != RoutingContext.EXTERNAL:
                return self._invoke_model_inner(
                    model_id, method, payload, headers, ctx, sync
                )
            # External completion feeds the SLO attainment window (one
            # sample per request, never per hop). Latency through the
            # injectable clock so the sim's windows carry virtual time.
            # The admission gate runs BEFORE the window opens and a shed
            # never records into it: the controller's burn signal must
            # judge the health of SERVED traffic — counting its own
            # sheds as breach would latch the throttle on forever.
            cls = self._model_class(model_id)
            self.admission_controller.admit(
                cls, cancel_event=ctx.cancel_event
            )
            clock = get_clock()
            t0 = clock.monotonic()
            ok = False
            try:
                result = self._invoke_model_inner(
                    model_id, method, payload, headers, ctx, sync
                )
                ok = True
                return result
            finally:
                self.slo.record(cls, (clock.monotonic() - t0) * 1e3, ok)
        finally:
            _thread.name = _prev_name

    def _model_class(self, model_id: str) -> str:
        """SLO class of a model = its model_type (watch-fed view read;
        unknown models fall to the spec's default class)."""
        mr = self.registry_view.get(model_id)
        return mr.model_type if mr is not None else ""

    def _invoke_model_inner(
        self,
        model_id: str,
        method: Optional[str],
        payload: bytes,
        headers: list[tuple[str, str]],
        ctx: RoutingContext,
        sync: bool,
    ) -> InvokeResult:
        if self.log_each_invocation:
            log.info(
                "invoke model=%s method=%s bytes=%d hop=%d visited=%s",
                model_id, method, len(payload), ctx.hop, sorted(ctx.visited),
            )

        if ctx.hop == RoutingContext.HIT_ONLY:
            ce = self.cache.get(model_id)
            if ce is None or ce.state in (EntryState.FAILED, EntryState.REMOVED):
                raise ModelNotHereError(self.instance_id, model_id)
            return self._invoke_local(
                ce, method, payload, headers, sync=sync,
                chain_count=ctx.chain_load_count,
                cancel_event=ctx.cancel_event,
            )

        last_exc: Optional[Exception] = None
        # A pure placement op (method None) with ourselves excluded must not
        # be satisfied by our own copy — the caller wants a copy elsewhere
        # (ensureLoaded-with-exclusions, reference ModelMesh.java:3348).
        skip_local = method is None and self.instance_id in ctx.exclude_load
        for _ in range(MAX_ITERATIONS):
            if ctx.cancelled:
                raise RequestCancelledError(model_id)
            # 1. local fast path
            ce = None if skip_local else self.cache.get(model_id)
            if ce is not None and ce.is_shard and method is not None:
                # Group atomicity: a shard serves inference only while
                # its group is COMPLETE — a partial group is never
                # routable, so fall through to routing (which re-plans).
                gmr = self.registry_view.get(model_id)
                if gmr is None or not gmr.group_complete:
                    # The watch-fed view lags a group that JUST
                    # completed — one authoritative read lets the local
                    # member serve instead of bouncing the request.
                    try:
                        gmr = self._registry_get_failfast(model_id)
                    except ServiceUnavailableError:
                        gmr = None
                    if gmr is None or not gmr.group_complete:
                        ce = None
            if ce is not None and ce.state not in (
                EntryState.FAILED, EntryState.REMOVED
            ):
                try:
                    return self._invoke_local(
                        ce, method, payload, headers, sync=sync,
                        chain_count=ctx.chain_load_count,
                        cancel_event=ctx.cancel_event,
                    )
                except ModelNotHereError as e:
                    last_exc = e  # runtime lost it; cleanup already done
                except ModelLoadException as e:
                    last_exc = e
                    ctx.exclude_load.add(self.instance_id)

            mr = self.registry_view.get(model_id)
            if mr is None:
                mr = self._registry_get_failfast(model_id)
            if mr is None:
                raise ModelNotFoundError(model_id)
            if mr.shard_count and not mr.group_complete:
                # Same view-lag heal for routing: a stale record for a
                # group that already completed would send the request
                # back through the miss loop (which re-plans the same
                # group and spins out the iteration budget). A genuinely
                # incomplete group is unchanged by the re-read.
                try:
                    amr = self._registry_get_failfast(model_id)
                except ServiceUnavailableError:
                    amr = None
                if amr is not None and amr.group_complete:
                    mr = amr

            # Registration-out-of-date self-heal: the record lists a copy
            # on THIS instance but the cache has none (lost to a KV-outage
            # load crash, an eviction race, or a restart under a preserved
            # registry). Unpruned, the serve loop skips self and the miss
            # loop hard-excludes self via all_placements — a one-instance
            # cluster could never serve the model again. The reference's
            # hit loop prunes its own stale registration the same way.
            if (
                not skip_local
                and (
                    self.instance_id in mr.instance_ids
                    or self.instance_id in mr.loading_instances
                )
                and (ce is None or ce.state is EntryState.REMOVED)
            ):
                # Covers stale LOADING claims too: a load that crashed
                # into a KV outage leaves its claim in loading_instances
                # with no cache entry behind it. The cache insert precedes
                # the registry claim in _load_local, so a genuinely
                # in-flight local load (ce present) is never pruned.
                mr = self._prune_stale_self(model_id) or mr

            if ctx.hop == RoutingContext.LOAD_LOCAL_ONLY:
                ce = self._load_local(model_id, mr, ctx)
                if ce is None:
                    raise NoCapacityError(self.instance_id)
                return self._invoke_local(
                    ce, method, payload, headers, sync=sync,
                    chain_count=ctx.chain_load_count,
                    cancel_event=ctx.cancel_event,
                )

            # 2. cache-hit loop: forward to a loaded copy
            with self.tracer.span("route-select", model=model_id) as _sp:
                target = self._choose_serve_target(model_id, mr, ctx)
                _sp["target"] = target or ""
            if target is not None:
                try:
                    return self._forward(
                        target, model_id, method, payload, headers, ctx,
                        hop=RoutingContext.INTERNAL,
                    )
                except (ModelNotHereError, ServiceUnavailableError) as e:
                    # The routed candidate just failed in practice —
                    # demote it WITHIN the cached set (d>1: survivors
                    # keep their ranking, so the thundering retry
                    # spreads over them instead of re-herding at one
                    # recomputed winner; d=1 keeps the old invalidate)
                    # and stamp the decaying LoadView penalty so every
                    # model's picks avoid the instance while fresh.
                    self._demote_route(model_id, target, type(e).__name__)
                    ctx.exclude_serve.add(target)
                    last_exc = e
                    continue
                except ModelLoadException as e:
                    # Serve target was a LOADING copy whose load failed (or
                    # timed out) — exclude it on both axes and re-route.
                    self._demote_route(model_id, target, "ModelLoadException")
                    ctx.exclude_serve.add(target)
                    ctx.exclude_load.add(target)
                    last_exc = e
                    continue

            # 3. cache-miss loop: place a new copy.
            if mr.load_exhausted():
                raise ModelLoadException(
                    f"{model_id}: load failed on "
                    f"{sorted(mr.load_failures)}: "
                    f"{[m for _, m in mr.load_failures.values()][:2]}"
                )
            # Hard exclusions forbid loading there at all; visited peers are
            # additionally excluded from *forward* targets (loop prevention)
            # but do not forbid loading on ourselves.
            # Failure exclusion is time-aware: an entry past
            # MM_LOAD_FAILURE_EXPIRY_MS stops excluding immediately,
            # without waiting for the leader reaper to prune the record.
            hard_exclude = (
                ctx.exclude_load | mr.all_placements | mr.active_failures()
            )
            views = self.cluster_view().instances
            if self.constraints is not None:
                hard_exclude |= self.constraints.non_candidates(
                    mr.model_type, views
                )
            strategy_exclude = hard_exclude | (ctx.visited - {self.instance_id})
            if self.upgrade_tracker is not None:
                strategy_exclude |= self.upgrade_tracker.likely_replaced(views)
            if not ctx.known_size_bytes:
                ctx.known_size_bytes = self._predict_size_bytes(model_id, mr)
            req = PlacementRequest(
                model_id=model_id,
                model=mr,
                required_units=bytes_to_units(ctx.known_size_bytes),
                requesting_instance=self.instance_id,
                exclude=frozenset(strategy_exclude),
                last_used_ms=ctx.last_used_ms or now_ms(),
            )
            # Sharded-execution branch: a model too big for ANY single
            # placeable instance (or one already carrying a shard group)
            # is placed as a multi-instance placement GROUP instead of a
            # single copy — the single-copy path below could only fail.
            if self._sharded_applicable(mr, req.required_units):
                status = self._place_sharded_group(
                    model_id, mr, req, ctx,
                    wait=sync or method is not None,
                )
                if status is None:
                    raise NoCapacityError(
                        f"no placement group can host sharded {model_id} "
                        f"(excluded: {sorted(strategy_exclude)})"
                    )
                if method is None:
                    return InvokeResult(b"", self.instance_id, status)
                if status != "LOADED":
                    raise ModelLoadException(
                        f"{model_id}: placement group did not complete "
                        f"in time", timeout=True,
                    )
                continue  # group complete: the serve loop routes to it
            target = self.strategy.choose_load_target(req, self.cluster_view())
            self.flightrec.record(
                "placement", model=model_id, target=target or "",
                hop=ctx.hop,
            )
            if target in (LOAD_HERE, self.instance_id):
                ce = self._load_local(model_id, mr, ctx)
                if ce is not None:
                    return self._invoke_local(
                        ce, method, payload, headers, sync=sync,
                        chain_count=ctx.chain_load_count,
                        cancel_event=ctx.cancel_event,
                    )
                ctx.exclude_load.add(self.instance_id)
                last_exc = last_exc or NoCapacityError(self.instance_id)
                continue
            if target is None:
                raise NoCapacityError(
                    f"no instance can load {model_id} "
                    f"(excluded: {sorted(strategy_exclude)})"
                )
            try:
                return self._forward(
                    target, model_id, method, payload, headers, ctx,
                    hop=RoutingContext.LOAD_LOCAL_ONLY,
                )
            except (
                ModelNotHereError, NoCapacityError, ServiceUnavailableError
            ) as e:
                ctx.exclude_load.add(target)
                last_exc = e
                continue

        raise last_exc or ModelLoadException(
            f"{model_id}: routing iterations exhausted"
        )

    def _demote_route(self, model_id: str, target: str, err: str) -> None:
        """Failed-forward demotion bookkeeping (ONE funnel for both
        except branches above: cache demotion + metric + flightrec)."""
        self.route_cache.demote(model_id, target)
        self.metrics.inc(MX.ROUTE_DEMOTE_COUNT, model_id=model_id)
        self.flightrec.record(
            "route-demote", model=model_id, target=target, err=err,
        )

    def _choose_serve_target(
        self, model_id: str, mr: ModelRecord, ctx: RoutingContext
    ) -> Optional[str]:
        """Serve-target selection: candidate-set memo + d-choices pick.

        The memo is consulted only when the request carries no serve
        exclusions — the forward-failure retry loop must always re-decide
        (and it also demotes, see the except branches above). A hit is
        valid only while the registry record version, the instances-view
        epoch, and the warming-clock bucket all match what the ranking
        was derived from; the exclusion signature is the cache key, so a
        hit can never return an excluded instance. The pick samples
        MM_ROUTE_D candidates against the piggybacked LoadView scores
        (route_cache.pick); strategies without a candidate-set export
        keep the old single-winner flow.
        """
        if mr.shard_count and not mr.group_complete:
            # Sharded model with an incomplete group: no member may serve
            # (group atomicity) — the miss loop re-plans instead.
            return None
        exclude = ctx.exclude_serve | ctx.visited | {self.instance_id}
        cache = self.route_cache
        rank = getattr(self.strategy, "rank_serve_candidates", None)
        if not cache.enabled or ctx.exclude_serve or rank is None:
            return self.strategy.choose_serve_target(
                mr, self.cluster_view(), frozenset(exclude)
            )
        sig = frozenset(exclude)
        cands = cache.lookup(
            model_id, sig, mr.version, self.instances_view.epoch
        )
        if cands is not None:
            return cache.pick(cands)
        view = self.cluster_view()
        cands = rank(mr, view, sig)
        if not cands:
            return None
        # Keyed on the snapshot actually used (view.epoch), not the
        # live epoch — if the view moved mid-decision the entry is
        # already stale and the next lookup recomputes.
        cache.store(model_id, sig, mr.version, view.epoch, cands)
        return cache.pick(cands)

    # ------------------------------------------------------------------ #
    # local invocation                                                   #
    # ------------------------------------------------------------------ #

    def _invoke_local(
        self, ce: CacheEntry, method: Optional[str], payload: bytes,
        headers: list[tuple[str, str]], sync: bool = True,
        chain_count: int = 0, cancel_event=None,
    ) -> InvokeResult:
        if not sync and ce.state.is_loading:
            # The chain must propagate even when the async request rides
            # an IN-FLIGHT load it didn't start: this entry's own
            # chain_load_count is whatever its original request carried,
            # so a later ensure(chain=N) landing mid-load would silently
            # truncate the fan-out (fresh loads fire in _load_local,
            # servable hits below — this was the remaining gap). The
            # fan-out excludes all current placements including our
            # loading claim, and _chain_fired keeps every path
            # single-shot.
            if chain_count > 0 and ce.claim_chain_fire():
                self._spawn_chain(ce.model_id, ce.last_used, chain_count)
            return InvokeResult(b"", self.instance_id, "LOADING")
        if not ce.state.is_servable:
            # The request is riding a load (cache miss): track how long it
            # waited (reference cache-miss-delay metric). A PARTIAL
            # streamed copy is already servable — no miss recorded.
            self.metrics.inc(MX.CACHE_MISS_COUNT, model_id=ce.model_id)
            t_wait = _time.perf_counter()  #: wall-clock: perf_counter latency metric (load-wait stage)
            with self.tracer.span("load-wait", model=ce.model_id):
                ok = self._wait_entry_active(ce, cancel_event=cancel_event)
            self.metrics.observe(
                MX.CACHE_MISS_DELAY,
                (_time.perf_counter() - t_wait) * 1e3, ce.model_id,  #: wall-clock: perf_counter latency metric
            )
            if not ok:
                raise ModelLoadException(
                    f"{ce.model_id}: timed out waiting for load", timeout=True
                )
        elif not self._wait_entry_active(ce, cancel_event=cancel_event):
            raise ModelLoadException(
                f"{ce.model_id}: timed out waiting for load", timeout=True
            )
        if not ce.state.is_servable:
            raise ModelNotHereError(self.instance_id, ce.model_id)
        if method is None:
            # ensure-loaded op: presence is the result. A chain count must
            # still propagate even though the copy already exists here —
            # otherwise ensure_loaded(chain=N) silently truncates whenever
            # the first target is already a holder (the fresh-load path
            # fires its own chain in _run_load; the _chain_fired flag
            # prevents double-fire).
            if chain_count > 0 and ce.claim_chain_fire():
                self._spawn_chain(ce.model_id, ce.last_used, chain_count)
            return InvokeResult(b"", self.instance_id, "LOADED")
        if not ce.before_invoke(cancel_event=cancel_event):
            if cancel_event is not None and cancel_event.is_set():
                raise RequestCancelledError(ce.model_id)
            raise ModelLoadException(f"{ce.model_id}: concurrency gate timeout")
        with self._inflight_lock:
            self._inflight += 1
        try:
            t0 = _time.perf_counter()  #: wall-clock: perf_counter latency metric (runtime invoke)
            with self.tracer.span("runtime-call", model=ce.model_id):
                if self.batcher is not None:
                    # Batched data plane: ride (or lead) a micro-batch.
                    # The span stays open on THIS thread for the whole
                    # submit, so a request executed by a batch leader
                    # still assembles its own span tree. A PARTIAL
                    # streamed copy is batchable only solo.
                    try:
                        out = self.batcher.submit(
                            ce.model_id, method, payload, headers,
                            cancel_event=cancel_event,
                            solo_only=ce.state is EntryState.PARTIAL,
                            ctx=ce,
                        )
                    except BatchCancelled:
                        raise RequestCancelledError(ce.model_id) from None
                elif self._runtime_call_cancellable:
                    out = self._runtime_call(
                        ce, method, payload, headers,
                        cancel_event=cancel_event,
                    )
                else:
                    out = self._runtime_call(ce, method, payload, headers)
            ce.record_latency((_time.perf_counter() - t0) * 1e3)  #: wall-clock: perf_counter latency metric
            self.rate.record()
            self._model_rate(ce.model_id).record()
            self.cache.get(ce.model_id)  # LRU touch
            self.metrics.inc(MX.INVOKE_LOCAL_COUNT, model_id=ce.model_id)
            return InvokeResult(out, self.instance_id, "LOADED")
        except ModelNotHereError:
            # Runtime claims NOT_FOUND for a model we think is loaded — the
            # Triton refresh quirk: purge and let the caller retry elsewhere
            # (reference cleanup-unload, SidecarModelMesh.java:961-988).
            self._remove_local(ce.model_id)
            raise
        finally:
            with self._inflight_lock:
                self._inflight -= 1
            ce.after_invoke()

    def load_feedback(self) -> "LoadFeedback":
        """This instance's current load, in the shape peers piggyback on
        Forward responses (route_cache.LoadFeedback): locally-executing
        request count, batch-queue depth (PR-13's RequestBatcher), and
        the drain flag. Cheap enough for every response — two attribute
        reads and a lock-free counter."""
        with self._inflight_lock:
            inflight = self._inflight
        depth = self.batcher.queue_depth() if self.batcher is not None else 0
        return LoadFeedback(
            self.instance_id, inflight, depth,
            draining=self.draining or self.shutting_down,
        )

    def _map_runtime_error(self, exc: Exception, model_id: str):
        """THE runtime-error-to-serving-exception mapping, shared by the
        single-call and batched data planes (per-item and collective):
        NOT_FOUND — as ModelNotLoadedError or a gRPC status — becomes
        ModelNotHereError (the purge-and-retry trigger), other gRPC
        errors become ApplierError, anything else passes through."""
        import grpc

        from modelmesh_tpu.serving.errors import ApplierError

        if isinstance(exc, ModelNotLoadedError):
            return ModelNotHereError(self.instance_id, model_id)
        if isinstance(exc, grpc.RpcError):
            if exc.code() == grpc.StatusCode.NOT_FOUND:
                return ModelNotHereError(self.instance_id, model_id)
            return ApplierError(exc.code().name, exc.details() or "")
        return exc

    def _default_runtime_call(
        self, ce: CacheEntry, method: str, payload: bytes,
        headers: list[tuple[str, str]], cancel_event=None,
    ) -> bytes:
        call_model = getattr(self.loader, "call_model", None)
        if call_model is None:
            raise NotImplementedError(
                "loader has no call_model; pass runtime_call to the instance"
            )
        try:
            return call_model(
                ce.model_id, method, payload, headers,
                cancel_event=cancel_event,
            )
        except Exception as e:
            mapped = self._map_runtime_error(e, ce.model_id)
            if mapped is e:
                raise
            raise mapped from e

    # -- batched dispatch plumbing (serving/batching.py) ----------------- #

    def _batch_call_one(self, req) -> bytes:
        """Zero-copy passthrough for an uncontended request: the exact
        single-call runtime path the unbatched data plane takes."""
        ce = req.ctx
        if self._runtime_call_cancellable:
            return self._runtime_call(
                ce, req.method, req.payload, req.headers,
                cancel_event=req.cancel_event,
            )
        return self._runtime_call(ce, req.method, req.payload, req.headers)

    def _batch_call_many(self, items, cancel_event=None) -> list:
        """Batched dispatch: hand the micro-batch to the loader (or the
        injected batched runtime call) and run _map_runtime_error over
        the outcome — per-item entries and collectively-raised failures
        alike — so the batched and unbatched data planes can never
        diverge in retry vocabulary (NOT_FOUND triggers purge-and-retry
        for every affected member)."""
        try:
            outs = self._runtime_call_batch(items, cancel_event=cancel_event)
        except Exception as e:
            mapped = self._map_runtime_error(e, items[0].model_id)
            if mapped is e:
                raise
            raise mapped from e
        return [
            self._map_runtime_error(out, item.model_id)
            if isinstance(out, Exception) else out
            for item, out in zip(items, outs)
        ]

    def _trigger_chained_load(self, ce: CacheEntry) -> None:
        """Chained copy loads: each instance that completes a chained load
        triggers the NEXT copy with itself and all existing placements
        excluded (reference triggerChainedLoadIfNecessary,
        ModelMesh.java:4560-4585) — distributing an N-copy ensureLoaded
        across the fleet one hop at a time instead of hammering one caller.
        Under ``load_fastpath`` the chain already fanned out concurrently
        at claim time (``_load_local``); the ``_chain_fired`` flag keeps
        this completion-time trigger from double-firing it.
        """
        remaining = getattr(ce, "chain_load_count", 0)
        if remaining <= 0 or not ce.claim_chain_fire():
            return
        self._spawn_chain(ce.model_id, ce.last_used, remaining)

    def _spawn_chain(self, model_id: str, last_used: int, remaining: int) -> None:
        """Dispatch ``remaining`` secondary copies on a background thread.

        Fast path (``load_fastpath``): ``_fanout_chain`` — all
        ``remaining`` placements are issued CONCURRENTLY, so the copies
        load in parallel across the fleet and time-to-N-copies approaches
        max(load) instead of N x load.

        Legacy path: one hop that propagates ``chain=remaining-1`` to the
        target, which fires the next copy only after ITS load completes —
        the reference's hop-by-hop distribution.
        """
        if self.load_fastpath:
            threading.Thread(
                target=self._fanout_chain,
                args=(model_id, last_used, remaining),
                name=f"chain-{model_id}", daemon=True,
            ).start()
            return

        def chain():
            try:
                mr = self.registry.get(model_id)
                if mr is None:
                    return
                self.ensure_loaded(
                    model_id,
                    last_used_ms=last_used,
                    sync=False,
                    exclude=set(mr.all_placements) | {self.instance_id},
                    chain=remaining - 1,
                )
            except Exception as e:  # noqa: BLE001 — chain is best-effort
                log.debug("chained load of %s stopped: %s", model_id, e)

        threading.Thread(
            target=chain, name=f"chain-{model_id}", daemon=True
        ).start()

    def _fanout_chain(self, model_id: str, last_used: int, remaining: int) -> None:
        """Concurrent chained fan-out (runs on the chain thread).

        ``sync`` does not traverse the internal Forward hop (a forwarded
        placement blocks until the remote load completes), so concurrency
        comes from DIRECTED parallel placements: a sequential pre-pass
        picks ``remaining`` distinct targets with the strategy (local,
        no KV writes), then one worker per target places a copy with
        every OTHER known instance excluded — concurrent placements can
        never collapse onto one instance, and each worker places at most
        one copy, so the chain budget is a hard ceiling on fan-out copies
        even when the first load later fails. A top-up pass repairs
        under-delivery (a directed placement that failed, or collapsed
        onto an instance that joined mid-fan-out and absorbed several
        workers) — but the chain owes ``remaining`` NEW copies beyond
        the surviving original placements only: a first-load failure (or
        an original copy evicted meanwhile) shrinks the target instead
        of baiting the top-up into replacing copies it never owed.
        """
        try:
            mr = self.registry.get(model_id)
            if mr is None or mr.load_exhausted():
                return
            originals = set(mr.all_placements)
            view = self.cluster_view()
            known = {iid for iid, _ in view.instances}
            units = bytes_to_units(self._predict_size_bytes(model_id, mr))
            exclude = set(mr.all_placements) | {self.instance_id}
            targets: list[str] = []
            for _ in range(remaining):
                req = PlacementRequest(
                    model_id=model_id,
                    model=mr,
                    required_units=units,
                    requesting_instance=self.instance_id,
                    exclude=frozenset(exclude),
                    last_used_ms=last_used or now_ms(),
                )
                target = self.strategy.choose_load_target(req, view)
                if target is None or target in (LOAD_HERE, self.instance_id):
                    break
                targets.append(target)
                exclude.add(target)

            def place(target: str) -> None:
                try:
                    self.ensure_loaded(
                        model_id,
                        last_used_ms=last_used,
                        sync=False,
                        exclude=(known | {self.instance_id}) - {target},
                        chain=0,
                    )
                except Exception as e:  # noqa: BLE001 — best-effort
                    log.debug(
                        "fan-out placement of %s on %s failed: %s",
                        model_id, target, e,
                    )

            workers = [
                threading.Thread(
                    target=place, args=(t,),
                    name=f"chain-{model_id}-{t}", daemon=True,
                )
                for t in targets
            ]
            for w in workers:
                w.start()
            for w in workers:
                w.join()
            # Top-up: place until the fleet holds `remaining` copies
            # beyond the SURVIVING originals. The target is recomputed
            # per attempt, so an original that failed or was evicted
            # shrinks it (never replaced), while a worker collapse onto
            # a mid-fan-out joiner (copies short, all budget nominally
            # spent) is repaired. Bounded attempts; each gated on a
            # fresh authoritative read.
            for _ in range(remaining):
                mr = self.registry.get(model_id)
                if mr is None or mr.load_exhausted():
                    return
                placements = set(mr.all_placements)
                want = remaining + len(originals & placements)
                if len(placements) >= want:
                    return
                self.ensure_loaded(
                    model_id,
                    last_used_ms=last_used,
                    sync=False,
                    exclude=placements | {self.instance_id},
                    chain=0,
                )
        except Exception as e:  # noqa: BLE001 — chain is best-effort
            log.debug("chained fan-out of %s stopped: %s", model_id, e)

    # ------------------------------------------------------------------ #
    # sharded placement groups                                           #
    # ------------------------------------------------------------------ #

    def _sharded_applicable(self, mr: ModelRecord, required_units: int) -> bool:
        """Should the miss loop plan a placement GROUP for this model?
        Yes for models already carrying a group (keep coordinating it)
        and for layer-streamable models too big for ANY single placeable
        instance — gated on the knob and the loader capability, so a
        store-only deployment keeps the old fail-to-place behavior."""
        if not self.config.sharded:
            return False
        if not getattr(self.loader, "supports_sharded_execution", False):
            return False
        if mr.shard_count:
            return True
        from modelmesh_tpu.transfer.protocol import is_layer_streamable

        if not is_layer_streamable(mr.model_type, mr.model_path):
            return False
        caps = [
            rec.capacity_units for _, rec in self.cluster_view().placeable()
        ]
        return bool(caps) and required_units > max(caps)

    @staticmethod
    def _group_missing(mr: ModelRecord, live: set) -> list[int]:
        """Shard indices with no LIVE holder or claimer — the signal that
        a group needs (re-)planning rather than just more patience."""
        if not mr.shard_count:
            return []
        missing = []
        for idx in range(mr.shard_count):
            if not any(
                i == idx and iid in live
                and (iid in mr.instance_ids or iid in mr.loading_instances)
                for iid, i in mr.shard_instances.items()
            ):
                missing.append(idx)
        return missing

    def _place_sharded_group(
        self, model_id: str, mr: ModelRecord, req: PlacementRequest,
        ctx: RoutingContext, wait: bool,
    ) -> Optional[str]:
        """Coordinate a sharded placement group: pick K distinct members
        with the strategy (smallest K whose per-shard share fits, up to
        MM_SHARDED_MAX_SHARDS), commit the WHOLE group in ONE registry
        CAS (``begin_shard_group`` — assignments, claims, epoch bump),
        then poke each member with a normal LOAD_LOCAL_ONLY placement op
        (no new wire surface; each member reads its own shard index from
        the record). Returns "LOADED" once the group is complete,
        "LOADING" when placed but not yet complete (or wait=False), None
        when the fleet cannot host the group."""
        view = self.cluster_view()
        live = set(view.live_map)
        shard_count = mr.shard_count
        if not shard_count or self._group_missing(mr, live):
            choose = getattr(self.strategy, "choose_group_targets", None)
            if choose is None:
                return None
            caps = [rec.capacity_units for _, rec in view.placeable()]
            if not caps:
                return None
            max_shards = max(int(self.config.sharded_max_shards), 2)
            k_lo = max(2, -(-req.required_units // max(caps)), shard_count)
            assignments: Optional[dict[str, int]] = None
            for k in range(k_lo, max_shards + 1):
                shard_units = max(1, -(-req.required_units // k))
                plan = choose(req, view, k, shard_units)
                if plan:
                    assignments, shard_count = plan, k
                    break
            if assignments is None:
                return None
            ts = now_ms()

            def mutate(cur: Optional[ModelRecord]) -> Optional[ModelRecord]:
                if cur is None:
                    return None
                cur.begin_shard_group(assignments, shard_count, ts)
                return cur

            try:
                mr = self.registry.update_or_create(model_id, mutate)
            except CasFailed:
                # A concurrent coordinator committed its own plan — ride
                # that group instead of fighting over epochs.
                self.flightrec.record("cas-failed", op="shard-group",
                                      model=model_id)
                mr = self.registry.get(model_id)
            if mr is None:
                raise ModelNotFoundError(model_id)
            if mr.shard_count:
                shard_count = mr.shard_count
            self.metrics.inc(MX.SHARDED_GROUP_PLAN_COUNT, model_id=model_id)
            self.flightrec.record(
                "sharded-group", op="plan", model=model_id,
                shards=shard_count, epoch=mr.group_epoch,
                members=",".join(sorted(mr.shard_instances)),
            )
        # Poke every member that is not yet a servable holder of its
        # shard. Remote pokes block until the remote shard is servable,
        # so they run concurrently; the self-poke only enqueues.
        pending = [
            iid for iid in mr.shard_instances
            if iid not in mr.instance_ids
        ]
        record = mr

        def poke_ctx() -> RoutingContext:
            return RoutingContext(
                hop=RoutingContext.INTERNAL,
                known_size_bytes=ctx.known_size_bytes,
                last_used_ms=ctx.last_used_ms,
            )

        if self.instance_id in pending:
            self._load_local(model_id, record, poke_ctx())
            pending.remove(self.instance_id)

        def poke(target: str) -> None:
            try:
                self._forward(
                    target, model_id, None, b"", [], poke_ctx(),
                    hop=RoutingContext.LOAD_LOCAL_ONLY,
                )
            except Exception as e:  # noqa: BLE001 — group converges via re-plan
                self.flightrec.record(
                    "sharded-group", op="poke-failed", model=model_id,
                    target=target, err=type(e).__name__,
                )
                log.debug("shard poke of %s to %s failed: %s",
                          model_id, target, e)

        for target in pending:
            threading.Thread(
                target=poke, args=(target,),
                name=f"shard-poke-{model_id}-{target}", daemon=True,
            ).start()
        if not wait:
            return "LOADING"
        clock = get_clock()
        budget_s = (self.params.load_timeout_ms or 120_000) / 1000.0
        deadline = clock.monotonic() + budget_s
        while True:
            cur = self.registry.get(model_id)
            if cur is None:
                raise ModelNotFoundError(model_id)
            if cur.shard_count and cur.group_complete:
                return "LOADED"
            if cur.load_exhausted():
                raise ModelLoadException(
                    f"{model_id}: shard load failed on "
                    f"{sorted(cur.load_failures)}"
                )
            if clock.monotonic() >= deadline:
                return "LOADING"
            clock.sleep(0.05)

    def replan_shard_for_drain(
        self, model_id: str, deadline_mono: float,
    ) -> bool:
        """Drain-time group move: pre-copy OUR shard to a survivor before
        this member drops it — the group keeps a servable holder of every
        index throughout, so a half-drained group never stops serving.
        The survivor is CASed in as a SECOND holder of our shard index
        (``shard_instances`` allows the overlap); only after it is
        servable does the caller drop the local copy, whose
        ``remove_instance`` then pops just us (the twin keeps the group
        alive). Returns True when the survivor copy is servable."""
        mr = self.registry.get(model_id)
        if mr is None or not mr.shard_count:
            return False
        my_idx = mr.shard_index_of(self.instance_id)
        if my_idx is None:
            return True  # re-planned away already: nothing to hand off
        shard_units = max(
            1,
            -(-bytes_to_units(self._predict_size_bytes(model_id, mr))
              // mr.shard_count),
        )
        view = self.cluster_view()
        members = set(mr.shard_instances)
        cands = sorted(
            (
                (iid, rec) for iid, rec in view.placeable()
                if iid not in members and iid != self.instance_id
                and rec.free_units >= shard_units
            ),
            key=lambda p: (-p[1].free_units, p[0]),
        )
        if not cands:
            return False
        survivor = cands[0][0]
        ts = now_ms()

        def mutate(cur: Optional[ModelRecord]) -> Optional[ModelRecord]:
            if cur is None:
                return None
            if cur.shard_index_of(self.instance_id) != my_idx:
                return cur  # group re-planned mid-drain; nothing owed
            cur.shard_instances[survivor] = my_idx
            cur.claim_loading(survivor, ts)
            return cur

        try:
            if self.registry.update_or_create(model_id, mutate) is None:
                return True  # unregistered: nothing to hand off
        except CasFailed:
            return False
        self.flightrec.record(
            "sharded-group", op="drain-replan", model=model_id,
            shard=my_idx, target=survivor,
        )
        try:
            self._forward(
                survivor, model_id, None, b"", [],
                RoutingContext(hop=RoutingContext.INTERNAL),
                hop=RoutingContext.LOAD_LOCAL_ONLY,
            )
        except Exception as e:  # noqa: BLE001 — poll below decides
            log.debug("drain shard poke of %s to %s failed: %s",
                      model_id, survivor, e)
        clock = get_clock()
        while clock.monotonic() < deadline_mono:
            cur = self.registry.get(model_id)
            if cur is None:
                return True
            if cur.shard_index_of(self.instance_id) != my_idx:
                return True  # re-planned away mid-wait
            if (
                survivor in cur.instance_ids
                and cur.shard_index_of(survivor) == my_idx
            ):
                return True
            clock.sleep(0.05)
        return False

    # ------------------------------------------------------------------ #
    # local load lifecycle                                               #
    # ------------------------------------------------------------------ #

    def _predict_size_bytes(self, model_id: str, mr: ModelRecord) -> int:
        predicted = self.loader.predict_size(
            model_id, ModelInfo(mr.model_type, mr.model_path, mr.model_key)
        )
        return predicted or self.params.default_model_size_bytes

    def _local_load_allowed(self, required_units: int) -> bool:
        """Churn guard: when full, don't evict recently-used entries
        (reference :3872-3884)."""
        if self.shutting_down or self.disabled or self.draining:
            return False
        free = self.cache.capacity - self.cache.weight
        if free >= required_units:
            return True
        oldest = self.cache.oldest_time()
        return oldest is not None and (
            now_ms() - oldest >= self.config.min_churn_age_ms
        )

    def _load_local(
        self, model_id: str, mr: ModelRecord, ctx: RoutingContext
    ) -> Optional[CacheEntry]:
        """Insert a cache entry and enqueue the load. Returns the (possibly
        pre-existing) entry, or None if loading here isn't allowed."""
        existing = self.cache.get_quietly(model_id)
        if existing is not None and existing.state not in (
            EntryState.FAILED, EntryState.REMOVED
        ):
            return existing

        info = ModelInfo(mr.model_type, mr.model_path, mr.model_key)
        if not ctx.known_size_bytes:
            ctx.known_size_bytes = self._predict_size_bytes(model_id, mr)
        units = bytes_to_units(ctx.known_size_bytes)
        # Sharded group member: this instance loads ONE SHARD, accounted
        # at its share of the model. The watch-fed view can lag the group
        # CAS that assigned us — when the full size would not even fit,
        # one authoritative re-read closes that window before the load is
        # misrecorded as a capacity failure.
        shard_index = (
            mr.shard_index_of(self.instance_id) if mr.shard_count else None
        )
        if shard_index is None and units > self.cache.capacity:
            try:
                fresh = self.registry.get(model_id)
            except Exception:  # noqa: BLE001 — KV hiccup: keep the view
                fresh = None
            if fresh is not None:
                mr = fresh
                shard_index = (
                    mr.shard_index_of(self.instance_id)
                    if mr.shard_count else None
                )
        shard_count = mr.shard_count if shard_index is not None else 0
        if shard_index is not None:
            units = max(1, -(-units // shard_count))
        if not self._local_load_allowed(units):
            return None
        if units > self.cache.capacity:
            self._record_load_failure(
                model_id, f"model size {units}u exceeds instance capacity"
            )
            return None

        last_used = ctx.last_used_ms or now_ms()
        ce = CacheEntry(model_id, info, weight_units=units, last_used=last_used)
        ce.chain_load_count = ctx.chain_load_count
        if shard_index is not None:
            ce.shard_index = shard_index
            ce.shard_count = shard_count
            ce.group_epoch = mr.group_epoch
            # Chains place extra FULL copies; a shard scales by group
            # re-planning instead.
            ce.chain_load_count = 0
        # Observability linkage: state transitions flow into the flight
        # recorder, and the load (which runs on a pool thread with no
        # request context) inherits the initiating request's trace id +
        # open span so the load's trace record joins the same tree.
        ce.recorder = self.flightrec
        ce.trace_id = Tracer.current_trace_id()
        ce.trace_parent = Tracer.current_span_id()
        prev = self.cache.put_if_absent(model_id, ce, units, last_used=last_used)
        if prev is not None:
            return prev

        # CAS our loading claim into the registry (reference loadLocal
        # conflict analysis, ModelMesh.java:5199-5255); promoted to a loaded
        # placement when the load completes.
        try:
            def place(cur: Optional[ModelRecord]) -> Optional[ModelRecord]:
                if cur is None:
                    return None  # unregistered concurrently
                cur.claim_loading(self.instance_id, now_ms())
                return cur

            if self.registry.update_or_create(model_id, place) is None:
                self.cache.remove_if_value(model_id, ce)
                ce.remove()
                raise ModelNotFoundError(model_id)
        except CasFailed:
            self.flightrec.record("cas-failed", op="claim-loading",
                                  model=model_id)
            self.cache.remove_if_value(model_id, ce)
            ce.remove()
            raise

        ce.queued_ms = now_ms()
        # Guarded transition, NOT a bare state write: a registry-deletion
        # cleanup racing this insert can have already REMOVED the entry
        # (remove_if_value succeeded between put_if_absent and here), and
        # clobbering REMOVED -> QUEUED would let _run_load load and
        # re-promote a model that was just unregistered. On failure the
        # submit below is harmless: _run_load's own guarded transitions
        # abandon a terminal entry immediately.
        ce.try_transition(EntryState.QUEUED)
        urgent = ctx.hop != RoutingContext.INTERNAL
        self.loading_pool.submit(
            lambda: self._run_load(ce), urgent=urgent, last_used=last_used
        )
        # Concurrent chained fan-out: secondary copies start placing as
        # soon as the FIRST copy's loading claim is in the registry (just
        # CASed above) rather than after its load completes — the whole
        # chain loads in parallel across the fleet.
        if (
            self.load_fastpath
            and ctx.chain_load_count > 0
            and ce.claim_chain_fire()
        ):
            self._spawn_chain(model_id, last_used, ctx.chain_load_count)
        return ce

    def _run_load(self, ce: CacheEntry) -> None:
        """Loading-pool task. All state advances go through the entry's
        guarded transitions so a concurrent eviction (-> REMOVED) is never
        clobbered; if the entry is removed after the runtime load happened,
        the runtime copy is released here.

        Pipelined fast path (``load_fastpath``): the entry activates and
        serves traffic as soon as ``loader.load`` returns a usable handle
        — the predicted size keeps holding the cache slot — and the
        ``model_size`` RPC runs as an overlapped follow-up whose guarded
        weight correction (``_correct_sizing``) can never touch an entry
        a concurrent eviction removed. Serial path (fastpath off, or the
        loader reported its size inline): size first, then activate."""
        model_id = ce.model_id
        # Anchor the queue-delay at submit time (set in _load_local), not at
        # worker pickup — otherwise the metric reads ~0 exactly when the
        # loading pool is saturated.
        queued_ms = getattr(ce, "queued_ms", None) or now_ms()
        # The load runs on a pool thread: re-open the initiating request's
        # trace (ce.trace_id, parented under its open span) so cache-miss
        # wait, peer stream, and activation appear in ONE tree. An
        # untraced origin mints (sampled) its own load trace.
        with self.tracer.trace(
            getattr(ce, "trace_id", ""), model_id, "load",
            parent_span=getattr(ce, "trace_parent", ""),
        ):
            self._run_load_traced(ce, queued_ms)

    def _run_load_traced(self, ce: CacheEntry, queued_ms: int) -> None:
        model_id = ce.model_id
        try:
            if self.loader.requires_unload:
                if not ce.try_transition(EntryState.WAITING):
                    return
                if not self._wait_space(ce):
                    raise ModelLoadException(
                        f"{model_id}: timed out waiting for unload space",
                        timeout=True,
                    )
            # Stamp the load start BEFORE the LOADING broadcast: waiters
            # wake on that transition to re-base onto the per-type load
            # budget, and must never observe LOADING with no start time
            # (they would silently fall back to the flat cap).
            ce.load_started_ms = now_ms()
            if not ce.try_transition(EntryState.LOADING):
                return
            self.metrics.observe(
                MX.QUEUE_DELAY, ce.load_started_ms - queued_ms, model_id
            )
            if ce.is_shard:
                # Sharded group member: materialize OUR shard (same-shard
                # peer stream, sliced full snapshot, or store) and settle
                # in SHARDED. No sizing phase — the shard share is the
                # loader's deterministic fraction of the measured total.
                loaded, _source = self.transfer.load_shard_weights(ce)
                if self.probation is not None:
                    self.probation.record_success()
                if loaded.size_bytes:
                    new_units = bytes_to_units(loaded.size_bytes)
                    if new_units != ce.weight_units and (
                        self.cache.update_weight(model_id, new_units)
                        is not None
                    ):
                        ce.weight_units = new_units
                self._activate_shard(ce, loaded)
                return
            # Weight-source resolution (transfer/): host-tier re-warm or
            # peer stream when available, model store otherwise — with
            # in-manager fallback to the store on any mid-transfer error.
            loaded, _source = self.transfer.load_weights(ce)
            # The runtime demonstrably works — disarm bootstrap probation
            # even if this entry is removed before activation below.
            if self.probation is not None:
                self.probation.record_success()
            size_bytes = loaded.size_bytes
            if not size_bytes and self.load_fastpath:
                # Serve-before-sizing: waiters wake NOW; the sizing RPC
                # and weight/registry correction overlap live traffic.
                if self._activate(ce, loaded):
                    self._correct_sizing(ce, loaded)
                return
            if not size_bytes and ce.try_transition(EntryState.SIZING):
                t_size = _time.perf_counter()  #: wall-clock: perf_counter latency metric (sizing)
                size_bytes = self.loader.model_size(model_id, loaded.handle)
                self.metrics.observe(
                    MX.SIZING_TIME, (_time.perf_counter() - t_size) * 1e3,  #: wall-clock: perf_counter latency metric
                    model_id,
                )
            if size_bytes:
                new_units = bytes_to_units(size_bytes)
                if new_units != ce.weight_units:
                    if self.cache.update_weight(model_id, new_units) is not None:
                        ce.weight_units = new_units
                    loaded = type(loaded)(
                        handle=loaded.handle,
                        size_bytes=size_bytes,
                        max_concurrency=loaded.max_concurrency,
                    )
            self._activate(ce, loaded)
        except ModelLoadException as e:
            self._load_failed(ce, str(e))
        except Exception as e:  # noqa: BLE001 — any load error is a failure
            self._load_failed(ce, f"{type(e).__name__}: {e}")

    def _activate(self, ce: CacheEntry, loaded) -> bool:
        """Finalize a runtime load: ACTIVE (unless removed meanwhile — then
        the runtime copy is released), registry promotion with the
        instance-record publish riding the same txn, chained-load trigger,
        load metrics. Returns True when the entry activated."""
        model_id = ce.model_id
        if not ce.complete_load(loaded):
            # Removed (evicted/unregistered) while we were loading.
            self.loader.unload(model_id)
            return False
        published = self._promote_loaded(model_id, size_units=ce.weight_units)
        self._trigger_chained_load(ce)
        self.metrics.inc(MX.LOAD_COUNT, model_id=model_id)
        if ce.load_started_ms:
            elapsed = now_ms() - ce.load_started_ms
            self.metrics.observe(MX.LOAD_TIME, elapsed, model_id)
            self.time_stats.record(ce.info.model_type, elapsed)
        if not published:
            self.publish_instance_record()
        return True

    def _activate_shard(self, ce: CacheEntry, loaded) -> bool:
        """Finalize a shard load: SHARDED state (terminal and servable —
        but routable only once the whole group is complete), registry
        promotion (which is what completes the group when the last shard
        lands), load metrics. No chained loads: groups scale by re-plan."""
        model_id = ce.model_id
        if not ce.complete_shard(loaded):
            self.loader.unload(model_id)
            return False
        published = self._promote_loaded(model_id, size_units=ce.weight_units)
        self.metrics.inc(MX.LOAD_COUNT, model_id=model_id)
        self.metrics.inc(MX.SHARDED_SHARD_LOAD_COUNT, model_id=model_id)
        if ce.load_started_ms:
            elapsed = now_ms() - ce.load_started_ms
            self.metrics.observe(MX.LOAD_TIME, elapsed, model_id)
            self.time_stats.record(ce.info.model_type, elapsed)
        self.flightrec.record(
            "sharded-group", op="shard-loaded", model=model_id,
            shard=ce.shard_index, shards=ce.shard_count,
            epoch=ce.group_epoch,
        )
        if not published:
            self.publish_instance_record()
        return True

    def begin_partial_serve(self, ce: CacheEntry, loaded) -> None:
        """Serve-before-fully-loaded: a streamed transfer has landed
        enough layers for this layer-streamable copy to admit requests.
        Move the entry to PARTIAL (waiters wake immediately) and promote
        the copy into the registry so the partial copy is advertised and
        routable mid-transfer; the stream's completion finalizes it to
        ACTIVE through the normal ``_activate`` path."""
        if not ce.begin_partial(loaded):
            return  # evicted/failed mid-stream: the stream outcome decides
        self.metrics.inc(MX.PARTIAL_SERVE_COUNT, model_id=ce.model_id)
        log.info(
            "%s serving partially-streamed (promoting mid-transfer)",
            ce.model_id,
        )
        # partial=True keeps our loading claim beside the promotion:
        # routable for requests, but flagged to peers as not-yet-a-
        # transfer-source (and their pending waits keep their anchor).
        if not self._promote_loaded(
            ce.model_id, size_units=ce.weight_units, partial=True
        ):
            self.publish_instance_record()

    def _correct_sizing(self, ce: CacheEntry, loaded) -> None:
        """Overlapped follow-up of a serve-before-sizing activation: run
        the ``model_size`` RPC and re-account the entry from its predicted
        weight to the measured one. Guarded throughout — the entry is
        already ACTIVE and serving, so a sizing failure only keeps the
        prediction, and the correction applies through
        ``update_weight_if_value`` so a concurrently evicted (or replaced)
        copy is never touched."""
        model_id = ce.model_id
        try:
            t_size = _time.perf_counter()  #: wall-clock: perf_counter latency metric (overlapped sizing)
            size_bytes = self.loader.model_size(model_id, loaded.handle)
            self.metrics.observe(
                MX.SIZING_TIME, (_time.perf_counter() - t_size) * 1e3,  #: wall-clock: perf_counter latency metric
                model_id,
            )
        except Exception as e:  # noqa: BLE001 — keep serving on prediction
            log.warning(
                "post-activation sizing of %s failed (serving continues "
                "on the predicted size): %s", model_id, e,
            )
            return
        if not size_bytes:
            return
        new_units = bytes_to_units(size_bytes)
        if new_units == ce.weight_units:
            return
        if not self.cache.update_weight_if_value(model_id, ce, new_units):
            return  # evicted/replaced during sizing: nothing to correct
        ce.weight_units = new_units
        ce.loaded = type(loaded)(
            handle=loaded.handle,
            size_bytes=size_bytes,
            max_concurrency=loaded.max_concurrency,
        )

        # The promotion advertised the predicted units to the global
        # solver — correct the record only when the measurement moved it.
        def mutate(cur: Optional[ModelRecord]) -> Optional[ModelRecord]:
            if cur is None:
                return None
            cur.size_units = new_units
            return cur

        try:
            self.registry.update_or_create(model_id, mutate)
        except CasFailed:
            log.warning("size-correction CAS gave up for %s", model_id)
        self.publish_instance_record()

    def _promote_loaded(
        self, model_id: str, size_units: int = 0, partial: bool = False,
    ) -> bool:
        """CAS the loaded promotion into the registry, with the refreshed
        instance-record advertisement riding the SAME store txn (the
        batched-mutation fast path: one KV round trip where the serial
        pipeline paid a promote CAS plus a separate publish put). Returns
        True when the publish rode the txn — the caller can then skip its
        standalone publish entirely. ``partial``: a mid-transfer (PARTIAL)
        promotion keeps the loading claim so peers know the copy is not a
        transfer source yet (records.promote_partial)."""

        def mutate(cur: Optional[ModelRecord]) -> Optional[ModelRecord]:
            if cur is None:
                return None
            if partial:
                cur.promote_partial(self.instance_id, now_ms())
            else:
                cur.promote_loaded(self.instance_id, now_ms())
            if size_units:
                cur.size_units = size_units
            return cur

        try:
            if not self.load_fastpath:
                raise _NoPublishLease  # serial baseline: plain CAS below
            with self._publish_lock:
                rec = self._build_publish_record_locked()
                op = self._session.publish_op(rec.to_bytes())
            if op is None:
                raise _NoPublishLease
            # The txn runs OUTSIDE _publish_lock: CAS retries are KV
            # round trips, and concurrent load completions must not
            # convoy on the lock (it guards only the bookkeeping).
            # Interleaved publishes are each self-consistent — the
            # suppression state follows whichever record committed last.
            self.registry.batch_mutate([(model_id, mutate)], [op])
            with self._publish_lock:
                self._last_published = rec
            self._publish_gauges()
            return True
        except CasFailed:
            # The record mutation gave up AND the piggybacked publish
            # never committed — let the caller's coalesced publish carry
            # the advertisement on its own.
            self.flightrec.record("cas-failed", op="promote-txn",
                                  model=model_id)
            log.warning("promote-loaded CAS gave up for %s", model_id)
            return False
        except _NoPublishLease:
            pass
        except Exception as e:  # noqa: BLE001 — e.g. session lease died
            log.warning(
                "merged promote+publish txn for %s failed (%s); "
                "falling back to a plain promote", model_id, e,
            )
        try:
            self.registry.update_or_create(model_id, mutate)
        except CasFailed:
            log.warning("promote-loaded CAS gave up for %s", model_id)
        return False

    def _wait_entry_active(self, ce: CacheEntry, cancel_event=None) -> bool:
        """Wait for an entry to activate, with a per-type bound on the LOAD
        phase only (reference TimeStats at ModelMesh.java:4351).

        Event-driven: the entry's condition variable broadcasts on every
        state transition, so the waiter sleeps for exactly its remaining
        budget and wakes at activation / failure / removal with
        notification latency — no polling-cadence slack. Intermediate
        transitions (QUEUED -> LOADING sets ``load_started_ms``) also wake
        it, re-basing the per-type load budget the moment the runtime load
        actually starts. Only a request carrying a ``cancel_event`` still
        slices its sleep: cancellation arrives on a foreign Event that
        cannot notify this condition.

        The overall wait is capped by the flat load_timeout*1.5 bound — it
        covers queueing behind a saturated loading pool, where per-type
        stats say nothing. Once the runtime load actually starts
        (load_started_ms set), a healthy load of this type should finish
        within mean+3σ; allow twice that (floored for cold starts) from
        the load start before declaring it stuck.
        """
        clock = get_clock()
        cap_s = self.load_timeout_s * 1.5
        mtype = ce.info.model_type
        if self.time_stats.samples(mtype) >= self.time_stats.min_samples:
            expect_s = self.time_stats.expect_ms(mtype) / 1000.0
            load_budget_s = min(cap_s, max(5.0, expect_s * 2.0))
        else:
            # Cold start: no per-type evidence yet — only the flat bound
            # applies (a 10s default budget would abort healthy slow first
            # loads and cascade duplicate copies).
            load_budget_s = cap_s
        deadline = clock.monotonic() + cap_s
        state = ce.state
        while True:
            if state.is_servable:
                return True
            if state is EntryState.FAILED:
                raise ModelLoadException(ce.error or "load failed")
            if state is EntryState.REMOVED:
                return False
            if cancel_event is not None and cancel_event.is_set():
                # The client is gone: stop pinning this handler thread on
                # the load (the load itself continues for other waiters).
                raise RequestCancelledError(ce.model_id)
            now = clock.monotonic()
            remaining = deadline - now
            started = ce.load_started_ms
            if started:
                remaining = min(
                    remaining,
                    load_budget_s - (now_ms() - started) / 1000.0,
                )
            if remaining <= 0:
                self.metrics.inc(MX.LOAD_TIMEOUT_COUNT, model_id=ce.model_id)
                self._log_loader_stacks(ce.model_id)
                return False
            if cancel_event is not None:
                remaining = min(remaining, 0.25)
            state = ce.await_transition(state, remaining)

    def _log_loader_stacks(self, model_id: str) -> None:
        """On a load timeout, capture the loading-pool threads' live stacks
        (the reference captures the stuck thread's stacktrace on load
        timeout, ModelMesh.java:2313-2318) — the single most useful
        artifact for diagnosing a wedged runtime."""
        ce = self.cache.get_quietly(model_id)
        if ce is not None and getattr(ce, "_stacks_logged", False):
            return  # N waiters timing out on one load: dump once
        if ce is not None:
            ce._stacks_logged = True
        import sys
        import traceback

        frames = sys._current_frames()
        stacks = []
        for t in threading.enumerate():
            if not t.name.startswith("loader-") or t.ident not in frames:
                continue
            frame = frames[t.ident]
            # Idle pool threads park in threading's cv.wait — only busy
            # (potentially stuck) threads are diagnostic signal.
            if frame.f_code.co_filename.endswith("threading.py"):
                continue
            stack = "".join(traceback.format_stack(frame))
            stacks.append(f"--- {t.name} ---\n{stack}")
        if stacks:
            log.warning(
                "load timeout for %s; loading-thread stacks:\n%s",
                model_id, "\n".join(stacks),
            )

    def _wait_space(self, ce: CacheEntry) -> bool:
        # The entry's weight is already inserted in the cache; what we wait
        # for is pending unloads to drain so that total (cache + pending)
        # fits capacity.
        return self.unload_tracker.wait_for_space(
            lambda: self.cache.weight, 0, timeout_s=self.config.space_wait_s
        )

    def _load_failed(self, ce: CacheEntry, message: str) -> None:
        log.warning("load of %s failed: %s", ce.model_id, message)
        # An entry that BEGAN partial serving has a provisional runtime
        # copy resident (the partial_ready contract: servable = device
        # memory allocated) — the terminal failure must release it like
        # _activate's removed-entry branch does, or the partially-
        # streamed weights leak with no entry left to ever trigger the
        # unload. Sticky flag, not the state: a concurrent eviction may
        # have moved a PARTIAL entry to REMOVED already (the eviction
        # skipped the unload — the copy was never was_active).
        was_partial = getattr(ce, "partial_started", False)
        if self.probation is not None:
            self.probation.record_failure(ce.model_id, message)
        self.metrics.inc(MX.LOAD_FAILED_COUNT, model_id=ce.model_id)
        ce.fail(message)
        self.cache.remove_if_value(ce.model_id, ce)
        if was_partial:
            if self.loader.requires_unload:
                self._async_unload(ce.model_id, ce.weight_units)
            else:
                model_id = ce.model_id
                self._submit_unload(lambda: self.loader.unload(model_id))
        self._record_load_failure(ce.model_id, message)
        self.publish_instance_record()

    def _record_load_failure(self, model_id: str, message: str) -> None:
        def mutate(cur: Optional[ModelRecord]) -> Optional[ModelRecord]:
            if cur is None:
                return None
            cur.remove_instance(self.instance_id)
            cur.add_load_failure(self.instance_id, message)
            return cur

        try:
            self.registry.update_or_create(model_id, mutate)
        except CasFailed:
            log.warning("failure-record CAS gave up for %s", model_id)

    # ------------------------------------------------------------------ #
    # eviction / removal                                                 #
    # ------------------------------------------------------------------ #

    def _on_eviction(self, model_id: str, ce: CacheEntry, last_used: int) -> None:
        """Cache evicted an entry (capacity pressure). Called under the
        eviction lock — NO blocking work here: KV deregistration and the
        runtime unload run on a separate thread so the inference hot path
        (which takes the same lock) never stalls on KV round trips."""
        log.info("evicting %s (last used %d)", model_id, last_used)
        self.metrics.inc(MX.EVICT_COUNT, model_id=model_id)
        if last_used:
            self.metrics.observe(
                MX.EVICT_AGE, (now_ms() - last_used) / 1000.0, model_id
            )
        was_active = ce.state is EntryState.ACTIVE
        # SHARDED holds device memory like ACTIVE does (its shard of the
        # group) — eviction must unload it; it just never demotes into the
        # host tier (a shard snapshot under the full-model fingerprint
        # would poison peer fetches).
        was_resident = was_active or ce.state is EntryState.SHARDED
        ce.remove()
        units = ce.weight_units
        do_unload = was_resident and self.loader.requires_unload
        if do_unload:
            self.unload_tracker.unload_started(units)

        self._drop_model_rate(model_id)

        def post_evict():
            try:
                # Flush the batch queue before the runtime copy drops:
                # parked requests ride a final (drain-flagged) dispatch
                # against the still-live handle instead of racing the
                # unload below. Runs on the unload pool — never under
                # the eviction lock.
                if self.batcher is not None:
                    self.batcher.flush(model_id, timeout_s=2.0)
                # Demote-to-host ahead of the full drop: export the
                # weights into the host tier BEFORE the runtime unload
                # releases the handle, so a re-warm is a device copy and
                # peers can keep fetching from this host. Only full
                # (was-ACTIVE) copies demote; best-effort by design.
                demoted = was_active and self.transfer.demote_evicted(
                    model_id, ce
                )
                self._deregister(
                    model_id, record_unload_time=True, demoted=demoted
                )
            finally:
                if do_unload:
                    try:
                        self.loader.unload(model_id)
                    finally:
                        self.unload_tracker.unload_finished(units)
                        self.publish_instance_record()

        self._submit_unload(post_evict)

    def _on_host_tier_evict(self, model_id: str, snap, size_bytes: int) -> None:
        """Host tier evicted a snapshot (host-capacity pressure). Called
        under the tier's lock — schedule the registry host-claim cleanup,
        never CAS inline."""
        self.metrics.inc(MX.HOST_TIER_EVICT_COUNT, model_id=model_id)
        self._cleanup_pool.submit(self._drop_host_claim, model_id)

    def _drop_host_claim(self, model_id: str) -> None:
        def mutate(cur: Optional[ModelRecord]) -> Optional[ModelRecord]:
            if cur is None:
                return None
            cur.drop_host_copy(self.instance_id)
            return cur

        try:
            self.registry.update_or_create(model_id, mutate)
        except CasFailed:
            log.warning("host-claim drop CAS gave up for %s", model_id)
        except Exception:  # noqa: BLE001 — stale claims are reaper-pruned
            pass

    def _claim_host_copy(self, model_id: str) -> bool:
        """Advertise this instance as a host-tier holder (the pre-warm
        twin of _drop_host_claim): receivers rank advertised holders as
        peer-fetch sources and re-warm targets."""
        def mutate(cur: Optional[ModelRecord]) -> Optional[ModelRecord]:
            if cur is None:
                return None
            cur.claim_host_copy(self.instance_id)
            return cur

        try:
            self.registry.update_or_create(model_id, mutate)
            return True
        except CasFailed:
            log.warning("host-claim CAS gave up for %s", model_id)
            return False
        except Exception:  # noqa: BLE001 — an unadvertised snapshot is
            # harmless; the next pre-warm pass (or demote) re-claims
            return False

    def demote_surplus_copy(self, model_id: str) -> bool:
        """Autoscale scale-down actuation (autoscale/controller.py):
        drop the local device copy but demote its weights into the host
        tier first, so a demand reversal re-warms with a host->device
        copy (~9 ms) instead of re-paying the cold store load (~82 ms).
        The host claim is advertised with the deregistration, exactly
        like a drain's cold-copy demotion."""
        ce = self.cache.get_quietly(model_id)
        if ce is None or ce.state is not EntryState.ACTIVE:
            return False
        if not self._remove_local(model_id, demote=True):
            return False
        self.metrics.inc(MX.SCALE_DOWN_COUNT, model_id=model_id)
        return True

    def prewarm_host_copy(self, model_id: str) -> bool:
        """Predictive pre-warm actuation (autoscale/controller.py):
        stage a host-tier snapshot of ``model_id`` streamed from a live
        holder (never the store) and advertise the host claim, so the
        forecast ramp is absorbed by the re-warm path. Best-effort; the
        snapshot is speculative and never evicts demoted copies
        (HostTier.put_if_room)."""
        if not self.transfer.prewarm_host(model_id):
            return False
        self._claim_host_copy(model_id)
        return True

    def handle_weight_fetch(
        self, model_id: str, chunk_index: int, fingerprint: str = "",
    ):
        """Sender side of the mesh-internal FetchWeights channel (served
        beside Forward): one chunk of this instance's snapshot of the
        model, from the host tier (exporting a live copy on first
        demand)."""
        # Deliberately NOT gated on shutting_down: graceful drain
        # (pre_shutdown migration) is exactly when peers streaming our
        # copies is most valuable, and the runtime handle is still alive
        # for the whole migration pass. A copy torn down mid-fetch just
        # yields NOT_AVAILABLE / a transport error — the receiver's
        # store fallback covers it like any other mid-stream fault.
        reply = self.transfer.handle_fetch(model_id, chunk_index, fingerprint)
        if not reply.ok and self.host_tier.peek(model_id) is None:
            # Self-heal a dangling host claim: a receiver dialed us as an
            # advertised host-tier source but the snapshot is gone (the
            # demote/evict CAS race) and we have nothing else to serve —
            # drop the claim so the fleet stops ranking us.
            mr = self.registry_view.get(model_id)
            if mr is not None and self.instance_id in getattr(
                mr, "host_instances", {}
            ):
                self._cleanup_pool.submit(self._drop_host_claim, model_id)
        return reply

    def _on_registry_event(self, event, model_id: str, record) -> None:
        """Registry watch listener: prompt local-copy cleanup on deletion.

        Runs on the KV watch dispatcher thread, which must never block on
        KV round-trips — the actual cleanup (CAS deregister + runtime
        unload) is queued onto the bounded cleanup pool.
        """
        # Any registry movement (copy added/removed/promoted, load failed,
        # deletion) drops the memoized route for the model. The version
        # check in _choose_serve_target already rejects stale entries once
        # the VIEW catches up; this eagerly frees the slot and keeps the
        # cache from holding routes for deleted models.
        self.route_cache.invalidate(model_id)
        if event is not TableEvent.DELETED:
            # Sharded-group membership is registry-authoritative: if an
            # update shows OUR shard claim gone or re-indexed (group torn
            # down by atomic eviction, re-planned to another holder), the
            # local shard is dead weight — queue its teardown. Keyed on
            # the claim itself, not the group epoch: epoch is advisory.
            ce = self.cache.get_quietly(model_id)
            if (
                ce is not None and ce.is_shard and record is not None
                and (
                    not record.shard_count
                    or record.shard_index_of(self.instance_id)
                    != ce.shard_index
                )
            ):
                self._cleanup_pool.submit(
                    self._teardown_stale_shard, model_id, ce
                )
            return
        # A deleted model's host-tier snapshot is dead weight (the record
        # that advertised it is gone): release the RAM promptly.
        self.transfer.drop_host_copy(model_id)
        if self.cache.get_quietly(model_id) is None:
            return
        self._cleanup_pool.submit(self._cleanup_deleted_model, model_id)
        # False return (pool shut down) means the instance is stopping —
        # nothing left worth cleaning.

    def _cleanup_deleted_model(self, model_id: str) -> None:
        # Re-registration may race the delete event: authoritative re-read —
        # only drop the copy if the model is still gone from the registry.
        try:
            if self.registry.get(model_id) is not None:
                return
        except Exception:  # noqa: BLE001 — KV outage: janitor will retry
            return
        if not self._remove_local(model_id):
            return
        log.info(
            "unloaded %s: deleted from registry (watch-driven cleanup)",
            model_id,
        )
        self.publish_instance_record()
        # The pre-read narrows but cannot close the delete/re-register race:
        # a re-registration landing between the read and the removal just
        # had a fresh copy torn down. Converge instead of trying to be
        # atomic — if the record is back, restore a copy somewhere.
        try:
            if self.registry.get(model_id) is not None:
                log.info(
                    "%s re-registered during deletion cleanup; re-placing",
                    model_id,
                )
                self.ensure_loaded(model_id)
        except Exception:  # noqa: BLE001 — best-effort; demand-load covers
            pass

    def _teardown_stale_shard(self, model_id: str, ce: CacheEntry) -> None:
        """Drop a local shard whose registry claim vanished or moved.

        Watch events lag and re-plans race: re-read the authoritative
        record and keep the shard if our claim is intact after all."""
        try:
            mr = self.registry.get(model_id)
        except Exception:  # noqa: BLE001 — KV hiccup: next event retries
            return
        if (
            mr is not None
            and mr.shard_count == ce.shard_count
            and mr.shard_index_of(self.instance_id) == ce.shard_index
        ):
            return  # claim intact — the watch event was stale
        if self.cache.get_quietly(model_id) is not ce:
            return  # entry already replaced/removed; nothing owed
        if self._remove_local(model_id):
            self.flightrec.record(
                "sharded-group", op="teardown", model=model_id,
                shard=ce.shard_index, shards=ce.shard_count,
            )
            self.publish_instance_record()

    def _remove_local(self, model_id: str, demote: bool = False) -> bool:
        # Deliberate removal (unregister / deletion cleanup / shutdown
        # migration) drops the host-tier snapshot too — unlike capacity
        # eviction, which demotes into it. The registry host claim falls
        # with remove_instance in _deregister below. ``demote=True``
        # (drain of a cold copy, reconfig/drain.py) follows the eviction
        # convention instead: snapshot into the host tier BEFORE the
        # runtime unload releases the handle and advertise the host claim
        # with the deregistration, so the copy stays a peer-fetch source
        # for the rest of the drain window.
        ce = self.cache.get_quietly(model_id)
        if ce is None:
            if not demote:
                self.transfer.drop_host_copy(model_id)
            return False
        # Batch-queue drain integration (PR 7): flush parked requests
        # through a final dispatch BEFORE the copy drops, so a drain's
        # zero-gap guarantee extends to requests already queued behind
        # an in-flight micro-batch.
        if self.batcher is not None and ce.state.is_servable:
            self.batcher.flush(model_id, timeout_s=2.0)
        demoted = False
        if demote:
            demoted = ce.state is EntryState.ACTIVE and (
                self.transfer.demote_evicted(model_id, ce)
            )
        else:
            self.transfer.drop_host_copy(model_id)
        if not self.cache.remove_if_value(model_id, ce):
            return False
        was_resident = ce.state in (EntryState.ACTIVE, EntryState.SHARDED)
        ce.remove()
        self._drop_model_rate(model_id)
        self._deregister(model_id, demoted=demoted)
        if was_resident and self.loader.requires_unload:
            self._async_unload(model_id, ce.weight_units)
        return True

    def _async_unload(self, model_id: str, units: int) -> None:
        self.unload_tracker.unload_started(units)

        def do_unload():
            try:
                self.loader.unload(model_id)
            finally:
                self.unload_tracker.unload_finished(units)
                self.metrics.inc(MX.UNLOAD_COUNT, model_id=model_id)
                self.publish_instance_record()

        self._submit_unload(do_unload)

    def _submit_unload(self, fn) -> None:
        """Run ``fn`` on the unload pool; after shutdown, fall back to a
        one-off daemon thread so accounting started by the caller (the
        unload_tracker reservation) still completes during shutdown
        migration. (Deletion cleanup deliberately has no such fallback —
        after shutdown there is nothing left worth cleaning.)"""
        if not self._unload_pool.submit(fn):
            threading.Thread(target=fn, daemon=True).start()

    # How young a loading claim survives the stale-self prune: a fresh
    # claim with no cache entry behind it is far more likely a concurrent
    # load racing this prune (its CAS landed between our trigger read and
    # the mutate below) than a crashed load — those are minutes stale by
    # the time the serve loop trips over them.
    _PRUNE_CLAIM_GRACE_MS = 2_000

    def _prune_stale_self(self, model_id: str) -> Optional["ModelRecord"]:
        """Drop OUR stale entry from a record's loaded set (cache disagrees
        with the registry about us). Returns the updated record, or None
        when the CAS gave up — the caller keeps its current view and the
        next iteration (or the reaper) retries."""

        class _NothingToPrune(Exception):
            pass

        def mutate(cur: Optional[ModelRecord]) -> Optional[ModelRecord]:
            if cur is None:
                return None
            # Re-read the cache INSIDE the CAS callback: the prune was
            # triggered from a pre-CAS cache read, and _load_local inserts
            # the cache entry before CAS'ing its registry claim — so a
            # load that started since the trigger is visible here. Without
            # this, the freshly CAS'd claim would be transiently dropped
            # and concurrent placements could double-load the model.
            # get_quietly: a registry-repair probe must not refresh the
            # entry's LRU recency (same as the trigger-path check).
            ce = self.cache.get_quietly(model_id)
            if ce is not None and ce.state is not EntryState.REMOVED:
                raise _NothingToPrune(cur)
            was_loaded = cur.instance_ids.pop(self.instance_id, None)
            claim_ts = cur.loading_instances.get(self.instance_id)
            was_loading = None
            if claim_ts is not None and (
                now_ms() - claim_ts >= self._PRUNE_CLAIM_GRACE_MS
            ):
                was_loading = cur.loading_instances.pop(
                    self.instance_id, None
                )
            if was_loaded is None and was_loading is None:
                # The trigger came from a lagging watch view; the REAL
                # record is already clean. Abort instead of CAS-writing
                # identical content (version bump + spurious cluster-wide
                # watch event), and hand the fresh record back.
                raise _NothingToPrune(cur)
            log.info(
                "pruned stale self-%s of %s (registry disagrees with "
                "the local cache)",
                "registration" if was_loaded is not None
                else "loading claim", model_id,
            )
            return cur

        try:
            return self.registry.update_or_create(model_id, mutate)
        except _NothingToPrune as e:
            return e.args[0]
        except CasFailed:
            log.warning("stale-self prune CAS gave up for %s", model_id)
            return None
        except Exception:  # noqa: BLE001 - KV outage: fail-fast covers it
            return None

    def _deregister(
        self, model_id: str, record_unload_time: bool = False,
        demoted: bool = False,
    ) -> None:
        def mutate(cur: Optional[ModelRecord]) -> Optional[ModelRecord]:
            if cur is None:
                return None
            cur.remove_instance(self.instance_id)
            # Re-check snapshot residency INSIDE the CAS callback: a
            # concurrent demotion of another model can have already
            # evicted ours from the host tier, and its scheduled
            # _drop_host_claim may have run (as a no-op) before this
            # claim commits — advertising a claim with nothing behind it
            # would strand receivers on NOT_AVAILABLE until we reload.
            # (handle_fetch self-heals the residual CAS-in-flight window.)
            if demoted and self.host_tier.peek(model_id) is not None:
                # The device copy is gone but a host-tier snapshot stays:
                # advertise it as a peer-fetch source (transfer/ tier).
                cur.claim_host_copy(self.instance_id, now_ms())
            if record_unload_time:
                cur.last_unload_ms = now_ms()
            return cur

        try:
            self.registry.update_or_create(model_id, mutate)
        except CasFailed:
            self.flightrec.record("cas-failed", op="deregister",
                                  model=model_id)
            log.warning("deregister CAS gave up for %s", model_id)

    # ------------------------------------------------------------------ #
    # forwarding                                                         #
    # ------------------------------------------------------------------ #

    def _forward(
        self, target: str, model_id: str, method: Optional[str],
        payload: bytes, headers: list[tuple[str, str]],
        ctx: RoutingContext, hop: int,
    ) -> InvokeResult:
        rec = self.instances_view.get(target)
        if rec is None:
            raise ServiceUnavailableError(target)
        if self._peer_call is None:
            raise ServiceUnavailableError(
                f"no peer transport configured (target {target})"
            )
        fwd_ctx = RoutingContext(
            hop=hop,
            exclude_serve=set(ctx.exclude_serve),
            exclude_load=set(ctx.exclude_load),
            visited=set(ctx.visited),
            dest_instance=target,
            chain_load_count=ctx.chain_load_count,
            known_size_bytes=ctx.known_size_bytes,
            last_used_ms=ctx.last_used_ms,
            cancel_event=ctx.cancel_event,
        )
        self.metrics.inc(MX.INVOKE_FORWARD_COUNT, model_id=model_id)
        # Own-outstanding accounting brackets the dispatch: the sender's
        # zero-staleness half of the load score (concurrent picks from
        # THIS instance spread immediately instead of herding on the
        # last piggybacked report).
        lv = self.route_cache.load_view
        lv.begin(target)
        try:
            with self.tracer.span("forward", target=target, hop=hop):
                result = self._peer_call(
                    rec.endpoint or target, model_id, method, payload,
                    outgoing_headers(headers), fwd_ctx,
                )
        finally:
            lv.end(target)
        # Piggybacked load feedback from the IMMEDIATE peer (the one we
        # route to — served_by may be a further hop, but the queue we
        # would join is the peer's): decays into the LoadView driving
        # every subsequent d-choices pick. getattr: stub transports in
        # older tests return bare InvokeResult-shaped objects.
        fb = getattr(result, "feedback", None)
        if fb is not None:
            lv.note(fb)
        return result

    # ------------------------------------------------------------------ #
    # shutdown                                                           #
    # ------------------------------------------------------------------ #

    def pre_shutdown(self, deadline_s: Optional[float] = None) -> None:
        """Graceful shutdown migration. Default path (MM_DRAIN_ON_SIGTERM):
        the reconfig DrainController — advertise DRAINING while still
        serving, pre-copy hot models to survivors over the transfer path
        (each local copy is dropped only after its survivor is servable:
        zero serving gap), host-tier demote the cold ones, then flip
        shutting_down and deregister. Legacy path (knob off): the
        reference preShutdown shape (ModelMesh.java:6959-7143) — flip
        shutting_down first, then migrate best-effort."""
        if deadline_s is None:
            deadline_s = self.config.drain_timeout_ms / 1000.0
        if self.config.drain_on_sigterm:
            from modelmesh_tpu.reconfig.drain import DrainController

            DrainController(self, deadline_s=deadline_s).drain()
            return
        clock = get_clock()
        self.shutting_down = True
        self.publish_instance_record(force=True)
        deadline = clock.monotonic() + deadline_s
        recent_cutoff = now_ms() - 3_600_000
        items = list(self.cache.descending_items())  # MRU -> LRU
        for model_id, ce, last_used in items:
            remaining = deadline - clock.monotonic()
            if remaining <= 0:
                break
            if last_used >= recent_cutoff and not self.shutdown_skip_migration:
                try:
                    self.ensure_loaded(
                        model_id, last_used_ms=last_used, sync=True,
                        exclude={self.instance_id},
                    )
                except Exception as e:  # noqa: BLE001 — best-effort migration
                    log.warning("migration of %s failed: %s", model_id, e)
            self._remove_local(model_id)
        for model_id, _, _ in list(self.cache.descending_items()):
            self._remove_local(model_id)

    shutdown_skip_migration = False

    def shutdown(self) -> None:
        # Disarm the publish coalescer first: a trailing flush firing
        # after the session closes would republish a dead instance.
        with self._coalesce_lock:
            self._shutdown_publishes = True
            timer, self._publish_timer = self._publish_timer, None
        if timer is not None:
            timer.cancel()
        self.loading_pool.shutdown()
        self._cleanup_pool.shutdown()
        self._unload_pool.shutdown()
        if self._plan_follower is not None:
            self._plan_follower.close()
        self._election.close()
        self._session.close()
        self.registry_view.close()
        self.instances_view.close()
        self.migration_fence.close()
        close = getattr(self.loader, "close", None)
        if close:
            close()
