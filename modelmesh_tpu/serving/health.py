"""Readiness gating + bootstrap fail-fast probation.

Reference behaviors re-derived (not transcribed):

- Readiness (ModelMesh.java:1310-1331): an instance answers NOT ready while
  any peer in the fleet advertises shutting-down. A rolling update's
  readiness probe then holds the rollout — the next pod isn't torn down
  until migrations off the draining pod finish (its record disappears when
  its session lease is revoked).
- Bootstrap probation (ModelMesh.java:1335-1419): during a startup window,
  repeated early load failures with zero successful loads mean the runtime
  or image is poisoned; the process aborts non-zero so the rollout FAILS at
  pod 1 instead of the bad image absorbing the whole fleet model-by-model
  as each migration lands on it.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

log = logging.getLogger(__name__)

DEFAULT_PROBATION_WINDOW_S = 360.0
DEFAULT_PROBATION_MAX_FAILURES = 3


class ReadinessGate:
    """Answers the /ready probe from live cluster state."""

    def __init__(self, instance) -> None:
        self.instance = instance

    def is_ready(self) -> tuple[bool, str]:
        inst = self.instance
        if inst.shutting_down:
            return False, "shutting down"
        for iid, rec in inst.instances_view.items():
            if iid != inst.instance_id and rec.shutting_down:
                return False, f"peer {iid} draining (rolling update in flight)"
        return True, "ok"


def _default_abort(reason: str) -> None:
    log.critical("bootstrap probation abort: %s", reason)
    # Raw exit: the process is declared unfit; supervisors (k8s) see a
    # non-zero exit and halt the rollout.
    os._exit(3)


class BootstrapProbation:
    """Counts early load outcomes; aborts a poisoned bootstrap.

    Armed for ``window_s`` after construction. Any successful load disarms
    it (the runtime demonstrably works); ``max_failures`` failures with no
    success abort via ``abort_fn``. Thread-safe — loads complete on pool
    threads.
    """

    def __init__(
        self,
        window_s: float = DEFAULT_PROBATION_WINDOW_S,
        max_failures: int = DEFAULT_PROBATION_MAX_FAILURES,
        abort_fn: Callable[[str], None] = _default_abort,
    ) -> None:
        self.window_s = window_s
        self.max_failures = max(1, max_failures)
        self.abort_fn = abort_fn
        self._started = time.monotonic()
        self._lock = threading.Lock()
        self._failures = 0
        self._disarmed = False

    @classmethod
    def from_env(cls) -> Optional["BootstrapProbation"]:
        """MM_PROBATION_S (0 disables) / MM_PROBATION_FAILURES."""
        from modelmesh_tpu.utils.envs import get_float, get_int

        window = get_float("MM_PROBATION_S")
        if window <= 0:
            return None
        return cls(
            window_s=window, max_failures=get_int("MM_PROBATION_FAILURES")
        )

    def reset_window(self) -> None:
        """Re-stamp the window start. Called after slow runtime/accelerator
        initialization so probation guards the load-serving period, not the
        (potentially minutes-long) TPU claim that precedes it."""
        with self._lock:
            self._started = time.monotonic()

    def record_success(self) -> None:
        with self._lock:
            self._disarmed = True

    def record_failure(self, model_id: str, message: str) -> None:
        with self._lock:
            if self._disarmed:
                return
            if time.monotonic() - self._started > self.window_s:
                self._disarmed = True
                return
            self._failures += 1
            n = self._failures
        if n >= self.max_failures:
            self.abort_fn(
                f"{n} load failures with no success within {self.window_s:.0f}s "
                f"of startup (last: {model_id}: {message}) — runtime looks "
                f"poisoned; failing the rollout"
            )
