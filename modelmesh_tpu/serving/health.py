"""Readiness gating + bootstrap fail-fast probation.

Reference behaviors re-derived (not transcribed):

- Readiness (ModelMesh.java:1310-1331): an instance that has NEVER yet
  reported ready holds while any peer in the fleet advertises
  shutting-down; once an instance reports ready the state LATCHES
  (reference reportReady) and only a local shutdown un-readies it. A
  rolling update's readiness probe then holds the rollout at the new pod —
  the next pod isn't torn down until migrations off the draining pod
  finish (its record disappears when its session lease is revoked) —
  without flipping established pods out of the Service.
- Bootstrap probation (ModelMesh.java:1335-1419): during a startup window,
  repeated early load failures with zero successful loads mean the runtime
  or image is poisoned; the process aborts non-zero so the rollout FAILS at
  pod 1 instead of the bad image absorbing the whole fleet model-by-model
  as each migration lands on it.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Optional

from modelmesh_tpu.utils.clock import get_clock

log = logging.getLogger(__name__)

DEFAULT_PROBATION_WINDOW_S = 360.0
DEFAULT_PROBATION_MAX_FAILURES = 3


class ReadinessGate:
    """Answers the /ready probe from live cluster state.

    Readiness LATCHES after the first successful report, mirroring the
    reference's one-way ``reportReady`` flag (ModelMesh.java:1310-1331):
    only pods that have never been ready are held back by a draining
    peer. Without the latch, one draining pod would flip every
    established pod to 503 and Kubernetes would empty the Service's
    endpoints — a fleet-wide outage on every rolling-update step.
    A local shutdown still un-readies this pod regardless of the latch.
    """

    def __init__(self, instance) -> None:
        self.instance = instance
        self._latched = False

    def is_ready(self) -> tuple[bool, str]:
        inst = self.instance
        if inst.shutting_down:
            return False, "shutting down"
        if self._latched:
            return True, "ok (latched)"
        # Don't latch off an UNSYNCED view: at bootstrap the kubelet can
        # probe before the KV watch has populated instances_view — an
        # empty view shows no draining peer and would latch ready while a
        # migration off a draining pod is still in flight. Our own record
        # appearing proves the view has caught up to at least our own
        # registration, which pre_start publishes before serving.
        if inst.instance_id not in inst.instances_view:
            return False, "cluster view not yet synced"
        for iid, rec in inst.instances_view.items():
            if iid != inst.instance_id and rec.shutting_down:
                return False, f"peer {iid} draining (rolling update in flight)"
        self._latched = True
        return True, "ok"


def _default_abort(reason: str) -> None:
    log.critical("bootstrap probation abort: %s", reason)
    # Raw exit: the process is declared unfit; supervisors (k8s) see a
    # non-zero exit and halt the rollout.
    os._exit(3)


class BootstrapProbation:
    """Counts early load outcomes; aborts a poisoned bootstrap.

    Armed for ``window_s`` after construction. Any successful load disarms
    it (the runtime demonstrably works); ``max_failures`` failures with no
    success abort via ``abort_fn``. Thread-safe — loads complete on pool
    threads.
    """

    def __init__(
        self,
        window_s: float = DEFAULT_PROBATION_WINDOW_S,
        max_failures: int = DEFAULT_PROBATION_MAX_FAILURES,
        abort_fn: Callable[[str], None] = _default_abort,
    ) -> None:
        self.window_s = window_s
        self.max_failures = max(1, max_failures)
        self.abort_fn = abort_fn
        self._started = get_clock().monotonic()
        self._lock = threading.Lock()
        self._failures = 0
        self._disarmed = False

    @classmethod
    def from_env(cls) -> Optional["BootstrapProbation"]:
        """MM_PROBATION_S (0 disables) / MM_PROBATION_FAILURES."""
        from modelmesh_tpu.utils.envs import get_float, get_int

        window = get_float("MM_PROBATION_S")
        if window <= 0:
            return None
        return cls(
            window_s=window, max_failures=get_int("MM_PROBATION_FAILURES")
        )

    def reset_window(self) -> None:
        """Re-stamp the window start. Called after slow runtime/accelerator
        initialization so probation guards the load-serving period, not the
        (potentially minutes-long) TPU claim that precedes it."""
        with self._lock:
            self._started = get_clock().monotonic()

    def record_success(self) -> None:
        with self._lock:
            self._disarmed = True

    def record_failure(self, model_id: str, message: str) -> None:
        with self._lock:
            if self._disarmed:
                return
            if get_clock().monotonic() - self._started > self.window_s:
                self._disarmed = True
                return
            self._failures += 1
            n = self._failures
        if n >= self.max_failures:
            self.abort_fn(
                f"{n} load failures with no success within {self.window_s:.0f}s "
                f"of startup (last: {model_id}: {message}) — runtime looks "
                f"poisoned; failing the rollout"
            )
