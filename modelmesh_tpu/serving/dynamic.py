"""Live serving configuration: KV-watched knobs applied without restart.

Binds the DynamicConfig tier (kv/config.py, watched ``<prefix>/config``) to
running serving state — the reference applies these live from its watched
config map (ModelMesh.java:174-180, 1008-1061):

- ``scaleup_rpm_threshold`` — the rate task's per-copy scale-up threshold
  (and, symmetrically, the janitor's scale-down fraction base).
- ``log_each_invocation`` — per-request logging on the routing path.
- ``disable`` — admin drain. The value is a comma/space-separated list of
  instance ids (``*`` or ``all`` drains every instance): each listed
  instance advertises ``disabled`` so no new placements land on it (local
  loads refused, placement views exclude it) while already-loaded models
  keep serving.
"""

from __future__ import annotations

import logging

from modelmesh_tpu.kv.config import DynamicConfig
from modelmesh_tpu.kv.store import KVStore

log = logging.getLogger(__name__)

KEY_SCALEUP_RPM = "scaleup_rpm_threshold"
KEY_LOG_EACH_INVOCATION = "log_each_invocation"
KEY_DISABLE = "disable"


class ServingConfigBinder:
    """Applies watched config keys to an instance + its task config."""

    def __init__(self, store: KVStore, kv_prefix: str, instance, task_config):
        self.instance = instance
        self.task_config = task_config
        # Defaults to restore when a key is deleted.
        self._default_scale_up_rpm = task_config.scale_up_rpm
        self.config = DynamicConfig(store, f"{kv_prefix.rstrip('/')}/config")
        self.config.add_listener(self._on_change)
        for key in (KEY_SCALEUP_RPM, KEY_LOG_EACH_INVOCATION, KEY_DISABLE):
            self._apply(key)

    def _on_change(self, key: str, _value) -> None:
        self._apply(key)

    def _apply(self, key: str) -> None:
        if key == KEY_SCALEUP_RPM:
            new = self.config.get_int(KEY_SCALEUP_RPM, self._default_scale_up_rpm)
            if new < 1:
                log.warning(
                    "dynamic config: rejecting scaleup_rpm_threshold=%d "
                    "(must be >= 1); keeping %d",
                    new, self.task_config.scale_up_rpm,
                )
                return
            if new != self.task_config.scale_up_rpm:
                log.info("dynamic config: scale_up_rpm %d -> %d",
                         self.task_config.scale_up_rpm, new)
                self.task_config.scale_up_rpm = new
        elif key == KEY_LOG_EACH_INVOCATION:
            self.instance.log_each_invocation = self.config.get_bool(
                KEY_LOG_EACH_INVOCATION, False
            )
        elif key == KEY_DISABLE:
            raw = (self.config.get(KEY_DISABLE) or "").replace(",", " ")
            ids = {tok for tok in raw.split() if tok}
            disabled = (
                self.instance.instance_id in ids or bool(ids & {"*", "all"})
            )
            if disabled != self.instance.disabled:
                log.warning("dynamic config: instance %s disabled=%s",
                            self.instance.instance_id, disabled)
                self.instance.disabled = disabled
                # Re-advertise immediately so peers' placement views update
                # on the watch rather than the next publisher tick.
                try:
                    self.instance.publish_instance_record(force=True)
                except Exception:  # noqa: BLE001 — advisory re-publish
                    log.exception("republish after disable flip failed")

    def close(self) -> None:
        self.config.close()
