"""Dataplane API configuration: inference-RPC allow-list + id extraction.

Parity with the reference's DataplaneApiConfig (DataplaneApiConfig.java:
51-119): JSON declaring which arbitrary inference RPCs are permitted and,
per RPC, where in the request protobuf the model id lives (so clients that
put the id in the message body instead of metadata still route), plus
whether that id is a vmodel.

{
  "rpcs": {
    "/pkg.Service/Predict": {"idExtractionPath": [1, 2], "vmodel": false},
    "/pkg.Service/Admin": {"allowed": false}
  },
  "allowOtherRpcs": true
}
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Sequence


@dataclasses.dataclass(frozen=True)
class RpcConfig:
    allowed: bool = True
    id_extraction_path: tuple[int, ...] = ()
    vmodel: bool = False


class DataplaneApiConfig:
    def __init__(self, rpcs: Optional[dict[str, RpcConfig]] = None,
                 allow_other_rpcs: bool = True):
        self.rpcs = rpcs or {}
        self.allow_other_rpcs = allow_other_rpcs

    @classmethod
    def from_json(cls, text: str) -> "DataplaneApiConfig":
        cfg = json.loads(text) if text.strip() else {}
        rpcs = {}
        for method, spec in (cfg.get("rpcs") or {}).items():
            rpcs[method] = RpcConfig(
                allowed=spec.get("allowed", True),
                id_extraction_path=tuple(spec.get("idExtractionPath", ())),
                vmodel=spec.get("vmodel", False),
            )
        return cls(rpcs, cfg.get("allowOtherRpcs", True))

    def rpc(self, method: str) -> Optional[RpcConfig]:
        c = self.rpcs.get(method)
        if c is not None:
            return c
        return RpcConfig() if self.allow_other_rpcs else None

    def is_allowed(self, method: str) -> bool:
        c = self.rpc(method)
        return c is not None and c.allowed

    def extraction_path(self, method: str) -> tuple[int, ...]:
        c = self.rpc(method)
        return c.id_extraction_path if c else ()
