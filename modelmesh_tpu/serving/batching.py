"""Continuous micro-batching in front of the runtime call (the batched
multi-model data plane, ROADMAP item 5).

Everything before this sat AROUND the model call — routing, load
lifecycle, the placement solve — while the runtime SPI executed one
request against one model at a time. This module is the execution layer
between ``ModelMeshInstance._invoke_local`` and the loader:

- **Per-group micro-batch queues.** A request arriving at an idle group
  executes immediately as a zero-copy passthrough (the single-call
  runtime path, byte-identical to the unbatched data plane — no added
  p50 at low load). Requests arriving while a dispatch is in flight park
  in the group's queue; when the in-flight dispatch completes, the head
  of the queue is promoted to batch leader, collects up to
  ``MM_BATCH_MAX`` parked requests (optionally waiting
  ``MM_BATCH_WINDOW_US`` for the batch to fill), and executes the whole
  micro-batch as ONE batched runtime dispatch. That is continuous
  batching: batch size adapts to instantaneous concurrency with no
  timer on the uncontended path.

- **Groups, not just models.** The queue key comes from the loader's
  ``batch_group_key`` — by default the model id (per-model batching);
  a fused-dispatch-capable loader (models/server.py) maps co-located
  same-architecture models of one family onto a shared key, so a
  micro-batch can span MODELS and execute as one stacked
  expert-parallel-style kernel with a per-request model-index route.

- **Exotic entry states.** A PARTIAL (serve-before-fully-loaded) copy is
  batchable only solo: its request never shares a dispatch with
  batch-mates (`solo_only`), mirroring how the rest of the stack treats
  partial copies as not-yet-first-class. Drain (reconfig/drain.py)
  flushes a model's queue before the copy drops so parked requests
  never execute against a released runtime handle.

The queue state machine is deliberately event-driven and leader-based:
the completing dispatcher never executes strangers' requests (its own
caller is waiting on it); it only designates the next leader. Every
parked request is therefore executed by exactly one thread that is
already inside ``submit`` for a request of the same group, and every
dispatch path signals completion in a ``finally`` — a request can wait
only on a live leader chain, never on nothing.

Instrumentation: batch occupancy and fused-group-size histograms, flush
reason counters (full / window / drain + solo passthroughs), and a
flight-recorder event per dispatched batch.
"""

from __future__ import annotations

import logging
import threading
import time as _time
from typing import Callable, Optional

from modelmesh_tpu.observability.metrics import Metric as MX
from modelmesh_tpu.runtime.spi import BatchItem
from modelmesh_tpu.utils.clock import get_clock
from modelmesh_tpu.utils.lockdebug import mm_condition, mm_lock

log = logging.getLogger(__name__)

# Cancellation poll slice while parked (same cadence as the load-wait
# slicing in _wait_entry_active): a foreign cancel Event cannot notify
# our per-request Event.
_CANCEL_SLICE_S = 0.25


class BatchCancelled(Exception):
    """The parked request's client disconnected before a leader claimed
    it into a batch. Mapped to RequestCancelledError by the caller."""


class _BatchRequest:
    __slots__ = (
        "model_id", "method", "payload", "headers", "cancel_event",
        "solo_only", "ctx", "event", "result", "err", "lead", "done",
    )

    def __init__(self, model_id, method, payload, headers, cancel_event,
                 solo_only, ctx=None):
        self.ctx = ctx  # opaque caller context (the serving CacheEntry)
        self.model_id = model_id
        self.method = method
        self.payload = payload
        self.headers = headers
        self.cancel_event = cancel_event
        self.solo_only = solo_only
        self.event = threading.Event()
        self.result: Optional[bytes] = None
        self.err: Optional[Exception] = None
        self.lead = False   # guarded by the owning _GroupQueue.lock
        self.done = False

    def to_item(self) -> BatchItem:
        return BatchItem(
            model_id=self.model_id, method=self.method or "",
            payload=self.payload, headers=self.headers,
        )


class _GroupQueue:
    """One micro-batch queue (one model, or one fused family group)."""

    __slots__ = ("key", "lock", "idle_cv", "pending", "in_flight",
                 "in_flight_ids", "drain_flush", "dead")

    def __init__(self, key: str):
        self.key = key
        self.lock = mm_lock("_GroupQueue.lock")
        # Broadcast on every dispatch completion — the drain flush waits
        # on this instead of polling: queue drain progresses in real
        # thread time, so a virtual-clock poll would deadlock
        # direct-tick sims.
        self.idle_cv = mm_condition("_GroupQueue.idle_cv", self.lock)
        self.pending: list[_BatchRequest] = []  #: guarded-by: lock
        self.in_flight = False  #: guarded-by: lock
        # Model ids riding the current dispatch (a fused group serves
        # several models; a flush must wait only for ITS model, not for
        # sibling traffic to stop).
        self.in_flight_ids: list[str] = []  #: guarded-by: lock
        # Count of drain flushes in progress (a drain can flush several
        # fused-sibling models concurrently): while non-zero, leaders
        # skip the fill window so the queue empties as fast as
        # dispatches complete.
        self.drain_flush = 0  #: guarded-by: lock
        # Set when the idle prune removed this queue from the registry.
        # A submit that fetched the queue just before the prune must
        # NOT run on the orphan — a drain flush looks queues up by key
        # and would miss the orphan's traffic, reporting the model
        # quiesced while a request is still in flight.
        self.dead = False  #: guarded-by: lock

    def await_drained(self, model_id: str, timeout_s: float) -> bool:
        """Drain flush: wait until no parked or in-flight request for
        ``model_id`` remains. Bounded by actual dispatch progress, NOT
        whole-queue idleness — a fused group's sibling models may keep
        the queue busy forever. The deadline is REAL time — queue drain
        is driven by live threads, not the (possibly virtual)
        injectable clock; a virtual wait here would deadlock
        direct-tick sims whose queues drain in wall microseconds."""
        deadline = _time.monotonic() + timeout_s  #: wall-clock: real-thread queue drain; a virtual wait would deadlock direct-tick sims
        with self.idle_cv:
            self.drain_flush += 1
            try:
                while (
                    model_id in self.in_flight_ids
                    or any(r.model_id == model_id for r in self.pending)
                ):
                    remaining = deadline - _time.monotonic()  #: wall-clock: real-thread queue drain deadline
                    if remaining <= 0:
                        return False
                    self.idle_cv.wait(remaining)
                return True
            finally:
                self.drain_flush -= 1


class RequestBatcher:
    """The continuous-batching execution layer.

    ``call_one(req) -> bytes`` is the zero-copy passthrough (the
    original single-request runtime call, cancel-capable); ``call_many
    (list[BatchItem], cancel_event) -> list[bytes | Exception]`` is the
    batched dispatch. ``group_key(model_id) -> str`` maps a model onto
    its queue (identity = per-model batching).
    """

    def __init__(
        self,
        call_one: Callable[[_BatchRequest], bytes],
        call_many: Callable[..., list],
        group_key: Optional[Callable[[str], str]] = None,
        batch_max: int = 8,
        window_us: int = 0,
        metrics=None,
        flightrec=None,
    ):
        self._call_one = call_one
        self._call_many = call_many
        self._group_key = group_key or (lambda mid: mid)
        self.batch_max = max(int(batch_max), 1)
        self.window_s = max(int(window_us), 0) / 1e6
        self.metrics = metrics
        self.flightrec = flightrec
        self._queues: dict[str, _GroupQueue] = {}  #: guarded-by: _qlock
        self._qlock = mm_lock("RequestBatcher._qlock")
        # Idle queues are RETAINED up to this bound so steady
        # non-overlapping traffic reuses its queue object instead of
        # paying an allocation plus two global-lock acquisitions per
        # request; past the bound, each completion prunes its own idle
        # queue (the JaxModelStore bounded-cache pattern).
        self.max_idle_queues = 256
        # Counters exposed for tests/benches (monotonic, approximate
        # under concurrency is fine — they feed assertions about "did a
        # batch form", not accounting).
        self.solo_count = 0
        self.batch_count = 0
        self.batched_requests = 0
        # Requests currently parked behind an in-flight dispatch across
        # ALL groups — the queue-depth surface the load-feedback trailer
        # (serving/route_cache.LoadFeedback) reports to routing peers.
        # Parks/claims/withdrawals run under different GROUP locks, so a
        # plain += would drift permanently; the dedicated lock costs one
        # acquire per parked (already-contended-path) request. Read
        # lock-free — it is a point-in-time load signal, not accounting.
        self._depth_lock = mm_lock("RequestBatcher._depth_lock")
        self.parked_total = 0  #: guarded-by: _depth_lock

    # ------------------------------------------------------------------ #
    # submission                                                         #
    # ------------------------------------------------------------------ #

    def submit(
        self, model_id: str, method: Optional[str], payload: bytes,
        headers, cancel_event=None, solo_only: bool = False, ctx=None,
    ) -> bytes:
        """Execute one request through the batch queue. Blocks until the
        request's (possibly shared) dispatch completes; raises whatever
        the dispatch raised for this request."""
        req = _BatchRequest(
            model_id, method, payload, headers, cancel_event, solo_only,
            ctx=ctx,
        )
        key = self._group_key(model_id)
        while True:
            q = self._queue_for(key)
            with q.lock:
                if q.dead:
                    # Lost the race with the idle prune: this object is
                    # no longer reachable by key (flush would miss it) —
                    # fetch the live replacement.
                    continue
                if not q.in_flight and not q.pending:
                    # Idle group: zero-copy passthrough, no queueing, no
                    # window — the uncontended path is byte-identical to
                    # the unbatched data plane.
                    q.in_flight = True
                    q.in_flight_ids = [model_id]
                    passthrough = True
                else:
                    q.pending.append(req)
                    passthrough = False
                    # Nested inside q.lock by convention (every
                    # parked_total adjustment is) so the acquisition
                    # order can never invert.
                    with self._depth_lock:
                        self.parked_total += 1
            break
        if passthrough:
            self.solo_count += 1
            try:
                return self._call_one(req)
            finally:
                self._complete(q)
        return self._park(q, req)

    # ------------------------------------------------------------------ #
    # queue state machine                                                #
    # ------------------------------------------------------------------ #

    def _queue_for(self, key: str) -> _GroupQueue:
        with self._qlock:
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = _GroupQueue(key)
            return q

    def _complete(self, q: _GroupQueue) -> None:
        """A dispatch finished: hand leadership to the queue head (its
        thread wakes inside _park and runs the next micro-batch), or
        prune the now-idle queue so model churn can't grow the dict
        without bound."""
        head = None
        with q.lock:
            q.in_flight = False
            q.in_flight_ids = []
            if q.pending:
                head = q.pending[0]
                head.lead = True
            # Every completion moves per-model membership — wake drain
            # flushes so they can re-check THEIR model, not just full
            # idleness.
            q.idle_cv.notify_all()
        if head is not None:
            head.event.set()
            return
        # Idle queues are retained below the bound (steady low-QPS
        # traffic reuses its queue with no global-lock churn); only
        # under model-churn pressure does the completion prune its own
        # idle entry. The unlocked len() read is a benign race — worst
        # case one slightly early/late prune. Lock order is _qlock ->
        # q.lock (same as flush). The dead flag closes the submit race:
        # a submit that fetched this q before the prune re-checks under
        # q.lock and retries on the live replacement, so flush's by-key
        # lookup always sees every in-flight request.
        if len(self._queues) <= self.max_idle_queues:
            return
        with self._qlock:
            with q.lock:
                if (
                    not q.pending and not q.in_flight
                    and self._queues.get(q.key) is q
                ):
                    q.dead = True
                    del self._queues[q.key]

    def _park(self, q: _GroupQueue, req: _BatchRequest) -> bytes:
        """Follower path: wait to be batched by a leader, or to be
        promoted to leader ourselves."""
        while True:
            if req.cancel_event is not None:
                req.event.wait(_CANCEL_SLICE_S)
            else:
                req.event.wait()
            with q.lock:
                if req.lead:
                    break  # promoted: run the next batch (below)
                if req.done:
                    return self._finish(req)
                if (
                    req.cancel_event is not None
                    and req.cancel_event.is_set()
                    and req in q.pending
                ):
                    # Not yet claimed by any leader: withdraw cleanly.
                    # The withdrawal moves per-model membership, so a
                    # drain flush waiting on this model must re-check —
                    # without the notify it would sleep out its full
                    # timeout.
                    q.pending.remove(req)
                    q.idle_cv.notify_all()
                    with self._depth_lock:
                        self.parked_total -= 1
                    raise BatchCancelled(req.model_id)
            if req.done:
                return self._finish(req)
        return self._lead(q, req)

    def _lead(self, q: _GroupQueue, req: _BatchRequest) -> bytes:
        """Leader path: optionally wait out the fill window, collect a
        micro-batch (self at the head), dispatch it, distribute results,
        then hand off to the next leader."""
        if self.window_s > 0 and not req.solo_only:
            with q.lock:
                need_fill = (
                    not q.drain_flush and len(q.pending) < self.batch_max
                )
            if need_fill:
                # Injectable clock: the sim's virtual window is what the
                # queue/flush scenarios exercise deterministically.
                get_clock().sleep(self.window_s)
        with q.lock:
            assert q.pending and q.pending[0] is req
            q.pending.pop(0)
            batch = [req]
            if not req.solo_only:
                while q.pending and len(batch) < self.batch_max:
                    nxt = q.pending[0]
                    if nxt.solo_only:
                        break  # PARTIAL copies batch only solo
                    batch.append(q.pending.pop(0))
            q.in_flight = True
            q.in_flight_ids = [r.model_id for r in batch]
            with self._depth_lock:
                self.parked_total -= len(batch)
            if len(batch) >= self.batch_max:
                reason = "full"
            elif q.drain_flush:
                reason = "drain"
            else:
                reason = "window"
        try:
            self._dispatch(batch, reason)
        finally:
            for r in batch[1:]:
                r.event.set()
            self._complete(q)
        return self._finish(req)

    def _dispatch(self, batch: list[_BatchRequest], reason: str) -> None:
        """Execute one micro-batch and ALWAYS mark every member done
        (result or error) — an exception escaping with members undone
        would leave their threads spinning on already-set events,
        breaking the completion-in-finally invariant."""
        try:
            self._dispatch_inner(batch, reason)
        except Exception as e:  # noqa: BLE001 — e.g. a raising sink
            for r in batch:
                if not r.done:
                    r.err = e
                    r.done = True

    def _dispatch_inner(self, batch: list[_BatchRequest], reason: str) -> None:
        self.batch_count += 1
        self.batched_requests += len(batch)
        if self.metrics is not None:
            self.metrics.observe(MX.BATCH_OCCUPANCY, float(len(batch)))
            counter = {
                "full": MX.BATCH_FLUSH_FULL_COUNT,
                "window": MX.BATCH_FLUSH_WINDOW_COUNT,
                "drain": MX.BATCH_FLUSH_DRAIN_COUNT,
            }[reason]
            self.metrics.inc(counter)
            models = len({r.model_id for r in batch})
            if models > 1:
                self.metrics.observe(MX.FUSED_GROUP_SIZE, float(models))
        if self.flightrec is not None:
            self.flightrec.record(
                "batch-flush", model=batch[0].model_id, reason=reason,
                size=len(batch),
                models=len({r.model_id for r in batch}),
            )
        # A batch member's cancel event can no longer withdraw it, and a
        # collective dispatch must never be aborted by ONE member's
        # disconnect (it would fail every innocent batch-mate) — so a
        # cancel event reaches the runtime only for a singleton batch,
        # where cancellation can't hurt anyone else.
        cancel = batch[0].cancel_event if len(batch) == 1 else None
        try:
            outs = self._call_many(
                [r.to_item() for r in batch],
                cancel_event=cancel,
            )
            if len(outs) != len(batch):
                raise RuntimeError(
                    f"batched dispatch returned {len(outs)} results "
                    f"for {len(batch)} requests"
                )
        except Exception as e:  # noqa: BLE001 — collective failure
            for r in batch:
                r.err = e
                r.done = True
            return
        for r, out in zip(batch, outs):
            if isinstance(out, Exception):
                r.err = out
            else:
                r.result = out
            r.done = True

    @staticmethod
    def _finish(req: _BatchRequest) -> bytes:
        if req.err is not None:
            raise req.err
        return req.result

    # ------------------------------------------------------------------ #
    # drain integration                                                  #
    # ------------------------------------------------------------------ #

    def flush(self, model_id: str, timeout_s: float = 5.0) -> bool:
        """Quiesce THIS model's requests before its copy drops (the
        drain / deliberate-removal hook): mark the group draining so
        leaders skip the fill window, then wait until no parked or
        in-flight request for the model remains — sibling models of a
        fused group may keep the queue busy throughout. Returns False
        on timeout (the removal proceeds anyway — parked requests then
        fail like any request racing an unload)."""
        key = self._group_key(model_id)
        with self._qlock:
            q = self._queues.get(key)
        if q is None:
            return True
        return q.await_drained(model_id, timeout_s)

    def queue_depth(self) -> int:
        """Parked requests across ALL groups right now — the batch-queue
        component of the piggybacked load feedback. Lock-free read of a
        lock-maintained counter: a point-in-time signal for routing
        peers, momentarily stale by design."""
        return self.parked_total

    def depth(self, model_id: str) -> int:
        """Parked requests for the model's group (tests/diagnostics)."""
        with self._qlock:
            q = self._queues.get(self._group_key(model_id))
        if q is None:
            return 0
        with q.lock:
            return len(q.pending) + (1 if q.in_flight else 0)
