"""Instance bootstrap helpers: static registration, preStop hook, diagnostics.

- StaticModelRegistration (reference StaticModelRegistration.java:57):
  register models/vmodels declared in env-var JSON at startup and verify
  they load.
- PreStopServer (reference RuntimeContainersPreStopServer, port 8090): an
  HTTP hook the runtime container's preStop probe blocks on until shutdown
  migration has finished, so k8s doesn't kill the model server while models
  are still being handed off.
- debug_dump: the state-dump diagnostic facility (reference "secret"
  ***LOGCACHE***/***GETSTATE*** ids, ModelMesh.java:3248-3253, 5552-5608)
  — full local cache + cluster placement state as JSON, reachable through
  GetModelStatus with the reserved id ``***STATE***``.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading
from typing import Optional

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.utils import envs
from modelmesh_tpu.runtime.spi import ModelInfo
from modelmesh_tpu.serving.errors import ReadOnlyModeError
from modelmesh_tpu.serving.instance import ModelMeshInstance

log = logging.getLogger(__name__)

STATE_DUMP_ID = "***STATE***"
# The reference's reserved diagnostic ids (ModelMesh.java:3248-3253) are
# accepted as aliases so migrated runbooks keep working.
STATE_DUMP_ALIASES = frozenset(
    {STATE_DUMP_ID, "***GETSTATE***", "***LOGCACHE***"}
)
STATIC_MODELS_ENV = "MM_STATIC_MODELS"


def register_static_models(
    instance: ModelMeshInstance,
    vmodels=None,
    config_json: Optional[str] = None,
    verify: bool = True,
) -> list[str]:
    """Register models/vmodels from JSON (env MM_STATIC_MODELS by default).

    {"models": [{"modelId": "m1", "type": "mlp", "path": "mlp://in=8"}],
     "vmodels": [{"vModelId": "alias", "targetModelId": "m1",
                  "type": "mlp", "path": "..."}]}
    Returns the list of registered model ids; raises RuntimeError if
    ``verify`` and any declared model fails to load.
    """
    text = (
        config_json if config_json is not None
        else envs.get(STATIC_MODELS_ENV) or ""
    )
    if not text.strip():
        return []
    cfg = json.loads(text)
    registered: list[str] = []
    failed: list[str] = []
    for spec in cfg.get("models", ()):  # concrete models
        mid = spec["modelId"]
        info = ModelInfo(
            model_type=spec.get("type", ""),
            model_path=spec.get("path", ""),
            model_key=spec.get("key", ""),
        )
        try:
            instance.register_model(mid, info, load_now=True, sync=verify)
        except ReadOnlyModeError as e:
            # KV-migration read-only: the registration will arrive with
            # the store copy — a crash-looping pod would defeat "serving
            # continues" for the whole migration window.
            log.warning("static model %s skipped: %s", mid, e)
            continue
        registered.append(mid)
        if verify and instance.get_status(mid)[0] != "LOADED":
            failed.append(mid)
    for spec in cfg.get("vmodels", ()):
        if vmodels is None:
            raise RuntimeError("static vmodels declared but vmodels disabled")
        from modelmesh_tpu.proto import mesh_api_pb2 as apb

        req = apb.SetVModelRequest(
            vmodel_id=spec["vModelId"],
            target_model_id=spec["targetModelId"],
            info=apb.ModelInfo(
                model_type=spec.get("type", ""),
                model_path=spec.get("path", ""),
                model_key=spec.get("key", ""),
            ),
            auto_delete_target=spec.get("autoDeleteTarget", True),
            load_now=True,
            sync=verify,
            owner=spec.get("owner", ""),
        )
        vmodels.set_vmodel(req, _AbortRaiser(), lambda mid: None)
        registered.append(spec["targetModelId"])
    if failed:
        raise RuntimeError(f"static models failed to load: {failed}")
    return registered


class _AbortRaiser:
    """Minimal grpc-context stand-in for internal vmodel calls."""

    def abort(self, code, details):
        raise RuntimeError(f"{code}: {details}")


def debug_dump(instance: ModelMeshInstance) -> dict:
    """Full cache + cluster placement state (the ***STATE*** dump)."""
    cache_entries = [
        {
            "modelId": mid,
            "state": ce.state.value,
            "weightUnits": ce.weight_units,
            "lastUsed": ts,
            "inflight": ce.inflight,
            "totalInvocations": ce.total_invocations,
            "error": ce.error,
        }
        for mid, ce, ts in instance.cache.descending_items()
    ]
    instances = [
        {
            "instanceId": iid,
            "capacityUnits": rec.capacity_units,
            "usedUnits": rec.used_units,
            "modelCount": rec.model_count,
            "rpm": rec.req_per_minute,
            "lruTs": rec.lru_ts,
            "shuttingDown": rec.shutting_down,
            "endpoint": rec.endpoint,
            "zone": rec.zone,
            "labels": list(rec.labels),
        }
        for iid, rec in instance.instances_view.items()
    ]
    registry = [
        {
            "modelId": mid,
            "type": mr.model_type,
            "loaded": dict(mr.instance_ids),
            "loading": dict(mr.loading_instances),
            "failures": {k: v[1] for k, v in mr.load_failures.items()},
            "refCount": mr.ref_count,
            "sizeUnits": mr.size_units,
        }
        for mid, mr in instance.registry.items()
    ]
    return {
        "instanceId": instance.instance_id,
        "now": now_ms(),
        "isLeader": instance.is_leader,
        "shuttingDown": instance.shutting_down,
        "cache": {
            "capacityUnits": instance.cache.capacity,
            "usedUnits": instance.cache.weight,
            "pendingUnloadUnits": instance.unload_tracker.pending_units,
            "entries": cache_entries,
        },
        "cluster": instances,
        "registry": registry,
        # Windowed SLO attainment per tracked class (observability/slo.py)
        # — the operator's "are we meeting objectives RIGHT NOW" read.
        "slo": {
            cls: {
                "requests": s.requests,
                "availability": round(s.availability, 5),
                "p99Ms": round(s.p99_ms, 1),
                "attained": s.attained,
                "burnRate": round(s.burn_rate, 3),
                "violations": s.violations,
            }
            for cls, s in (
                (c, instance.slo.attainment(c))
                for c in instance.slo.classes()
            )
        },
        # Shadow-mode evaluation report, when the strategy runs one
        # (placement/shadow.py): agreement rates + recent divergences.
        **(
            {"shadow": instance.strategy.shadow_stats()}
            if hasattr(instance.strategy, "shadow_stats")
            else {}
        ),
    }


class PreStopServer:
    """Lifecycle HTTP endpoints: preStop hook + kubelet probes.

    - GET /prestop — blocks until migration completes (k8s preStop hook).
    - GET /ready — 200 only when the ReadinessGate passes: not shutting
      down, cluster view synced, and (until first-ready LATCHES, reference
      reportReady) no peer draining — holds a rolling update at not-yet-
      ready pods while migrations are in flight without 503ing established
      pods (reference isReady(), ModelMesh.java:1310-1331).
    - GET /live — 200 while the process serves HTTP at all.
    """

    def __init__(self, instance: ModelMeshInstance, port: int = 8090,
                 max_wait_s: float = 120.0):
        from modelmesh_tpu.serving.health import ReadinessGate

        self.instance = instance
        self.migrated = threading.Event()
        self.gate = ReadinessGate(instance)
        inst = self.instance
        migrated = self.migrated
        gate = self.gate

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib API
                path = self.path.rstrip("/")
                if path == "/live":
                    self.send_response(200)
                    self.end_headers()
                    self.wfile.write(b"live\n")
                    return
                if path == "/ready":
                    ok, reason = gate.is_ready()
                    self.send_response(200 if ok else 503)
                    self.end_headers()
                    self.wfile.write(reason.encode() + b"\n")
                    return
                if path != "/prestop":
                    self.send_response(404)
                    self.end_headers()
                    return
                if not inst.shutting_down:
                    # The hook firing IS the shutdown signal.
                    threading.Thread(
                        target=self._migrate, daemon=True
                    ).start()
                migrated.wait(max_wait_s)
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"migrated\n")

            def _migrate(self):
                try:
                    inst.pre_shutdown()
                finally:
                    migrated.set()

            def log_message(self, *args):
                pass

        self._server = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever, name="prestop", daemon=True
        ).start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
