"""Per-model serve-route memoization for the request hot path.

The cache-hit forwarding loop pays a full ``choose_serve_target`` per
request: a pass over the model's copies against the cluster view, with
warming/busyness ranking. At steady state the inputs barely move, so the
chosen target is memoized per ``(model_id, exclusion-signature)`` and a
hit costs two dict lookups — no view walk, no candidate ranking. The
RouteBalance observation (PAPERS.md) is exactly this: fused routing+LB
scales only when the per-request decision cost is amortized off the
request path.

A cached entry is only served while every input it was derived from is
provably unchanged:

- ``record_version`` — the registry record's KV CAS version. Any copy
  added/removed/promoted/failed bumps it, so placement changes miss.
- ``view_epoch`` — the instances TableView epoch (kv/table.py). Any
  instance joining/leaving/republishing (rpm, shutdown, drain) misses.
- warming-clock bucket — the greedy ranking depends on wall time through
  the per-type warming penalty and the loading-copy ride-the-load bound,
  so entries expire with the ``ttl_ms`` clock bucket (default 1 s).

Callers additionally bypass the cache whenever the request carries serve
exclusions (the forward-failure retry loop) and invalidate on registry
watch events and observed forward failures — see
ModelMeshInstance._choose_serve_target.

Knobs (utils/envs.py): ``MM_ROUTE_CACHE`` (default on) and
``MM_ROUTE_CACHE_TTL_MS`` (warming-clock bucket width).
"""

from __future__ import annotations

from typing import Optional

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.utils.lockdebug import mm_lock

DEFAULT_TTL_MS = 1_000
# Distinct model ids cached before a wholesale reset; a cache, not a
# registry mirror — resets only cost the next request per model one
# recompute.
DEFAULT_MAX_MODELS = 8_192


class RouteCache:
    """Lock-free on the hit path: reads/writes are single dict operations
    (GIL-atomic); the lock only guards the rare size-cap reset. Validity
    is carried in the entry and checked against caller-supplied inputs,
    so a racing store can never make a lookup return a target whose
    inputs don't match."""

    __slots__ = (
        "enabled", "ttl_ms", "max_models",
        "_by_model", "_lock", "hits", "misses", "invalidations",
    )

    def __init__(
        self,
        enabled: Optional[bool] = None,
        ttl_ms: Optional[int] = None,
        max_models: int = DEFAULT_MAX_MODELS,
    ):
        if enabled is None or ttl_ms is None:
            from modelmesh_tpu.utils import envs

            if enabled is None:
                enabled = envs.get_bool("MM_ROUTE_CACHE")
            if ttl_ms is None:
                ttl_ms = envs.get_int("MM_ROUTE_CACHE_TTL_MS")
        self.enabled = enabled
        self.ttl_ms = max(int(ttl_ms), 1)
        self.max_models = max_models
        # model_id -> {exclusion_sig: (target, record_version, view_epoch,
        #                              clock_bucket)}
        # [rebind]: inner-map writes are deliberately lock-free (GIL-
        # atomic dict ops; validity is carried in the entry) — only the
        # wholesale resets rebind the dict, and those are guarded.
        #: guarded-by: _lock [rebind]
        self._by_model: dict[str, dict[frozenset, tuple]] = {}
        self._lock = mm_lock("RouteCache._lock")
        # Plain-int stats (racy under contention, monotone enough for
        # bench/diagnostics — not billing).
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    def _bucket(self, now: Optional[int]) -> int:
        return (now if now is not None else now_ms()) // self.ttl_ms

    def lookup(
        self,
        model_id: str,
        sig: frozenset,
        record_version: int,
        view_epoch: int,
        now: Optional[int] = None,
    ) -> Optional[str]:
        """Cached target, or None when absent/any validity input moved."""
        sigs = self._by_model.get(model_id)
        entry = sigs.get(sig) if sigs is not None else None
        if (
            entry is not None
            and entry[1] == record_version
            and entry[2] == view_epoch
            and entry[3] == self._bucket(now)
        ):
            self.hits += 1
            return entry[0]
        self.misses += 1
        return None

    def store(
        self,
        model_id: str,
        sig: frozenset,
        record_version: int,
        view_epoch: int,
        target: str,
        now: Optional[int] = None,
    ) -> None:
        if len(self._by_model) >= self.max_models:
            with self._lock:
                if len(self._by_model) >= self.max_models:
                    self._by_model = {}
        entry = (target, record_version, view_epoch, self._bucket(now))
        sigs = self._by_model.setdefault(model_id, {})
        # Signatures per model stay tiny (the trivial external signature
        # plus a handful of multi-hop variants); cap defensively so a
        # pathological exclusion churn can't grow one model's map.
        if len(sigs) >= 16:
            sigs.clear()
        sigs[sig] = entry

    def invalidate(self, model_id: str) -> None:
        if self._by_model.pop(model_id, None) is not None:
            self.invalidations += 1

    def clear(self) -> None:
        with self._lock:
            self._by_model = {}

    def __len__(self) -> int:
        return len(self._by_model)
