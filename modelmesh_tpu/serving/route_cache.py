"""Load-aware fused routing: candidate-set memo + power-of-d choices.

The PR-2 route cache memoized a *single* greedy winner per model. Under
skewed (Zipf) traffic that herds every request at the cached target
while sibling copies idle — the winner only changes when the registry
record version, instances epoch, or warming bucket moves, none of which
react to load on the sub-second timescale queues build at. RouteBalance
(PAPERS.md) is the fix this module implements: routing and load
balancing fused at the per-request decision, still amortized off the
request path.

Structure:

- ``RouteCache`` now caches the ranked candidate *set* (greedy order,
  as exported by ``GreedyStrategy.rank_serve_candidates``) under the
  same validity keys as before: registry record version × instances
  epoch × warming-clock bucket. A hit costs two dict lookups plus the
  d-choices pick below.
- ``LoadView`` holds per-instance load feedback piggybacked on Forward
  responses (the responder's in-flight count, its batch-queue depth,
  and a drain flag — serving/instance.py captures it in ``_forward``).
  Scores DECAY with staleness (``MM_FEEDBACK_DECAY_MS``): an instance
  we haven't heard from recently scores toward 0, so the pick degrades
  gracefully toward the greedy prior instead of acting on stale load.
- The pick is **anchored power-of-d choices** (``MM_ROUTE_D``): the
  greedy winner (rank 0) is always a candidate, plus d-1 distinct
  uniformly sampled others; the request goes to the sampled candidate
  with the lowest capability-weighted load score, ties broken by greedy
  rank. Consequences that matter:
    * MM_ROUTE_D=1 → always rank 0 → bit-identical to the old
      single-winner cache (the regression-pinned parity mode).
    * No feedback yet (or all decayed) → every score is 0 → rank 0
      wins → identical to the greedy prior. d-choices only *deviates*
      from greedy when live load evidence says the winner is busier.
    * DRAINING candidates keep their rank-behind-healthy semantics
      (reconfig/): the pick key orders (draining, score, rank), so a
      draining copy wins only when every sampled candidate drains.
- **Capability weights** normalize load scores by the instance's
  advertised capacity (InstanceRecord.capacity_units — the PR-7
  record): at equal queue depth a 2× capacity hardware generation
  scores half as loaded, so mixed fleets get proportional traffic.
- **Failed-forward demotion**: a forward failure demotes the failed
  candidate WITHIN the cached set (moved behind the survivors, plus a
  decaying LoadView penalty) instead of dropping the whole entry —
  dropping would make every concurrent retry recompute the same
  greedy ranking and re-herd the thundering retry at one survivor.
  With MM_ROUTE_D=1 the old invalidate behavior is kept (parity).

Concurrency: the hit path stays lock-free — candidate entries and
LoadView slots are whole-tuple dict reads/writes (GIL-atomic), so a
racing store can never expose a half-updated record; the striped locks
only serialize read-modify-write merges of feedback slots, and the
cache-level lock only the rare wholesale reset.

Knobs (utils/envs.py): ``MM_ROUTE_CACHE``, ``MM_ROUTE_CACHE_TTL_MS``,
``MM_ROUTE_D``, ``MM_FEEDBACK_DECAY_MS``.
"""

from __future__ import annotations

import random
import zlib
from typing import Optional, Sequence

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.utils.lockdebug import mm_lock
from modelmesh_tpu.utils import racedebug

DEFAULT_TTL_MS = 1_000
# Distinct model ids cached before a wholesale reset; a cache, not a
# registry mirror — resets only cost the next request per model one
# recompute.
DEFAULT_MAX_MODELS = 8_192

# Load-score units: one in-flight request == 1.0. The demotion penalty
# dwarfs any plausible queue so a freshly-failed candidate loses every
# pick until the penalty decays (or the entry is rebuilt on the epoch
# bump its failure usually causes).
FAIL_PENALTY = 1_000.0
# A peer reporting drain/PARTIAL in its feedback is biased against
# modestly — the candidate set's own draining flag (epoch-fed) is the
# authoritative rank-behind-healthy ordering; this just reacts a watch
# round-trip earlier.
DRAIN_BIAS = 4.0

_N_STRIPES = 8


class LoadFeedback:
    """One piggybacked load report from a peer (Forward response trailer
    on the wire, a direct attribute on the sim/bench transports)."""

    __slots__ = ("instance_id", "in_flight", "queue_depth", "draining")

    def __init__(self, instance_id: str, in_flight: int, queue_depth: int,
                 draining: bool = False):
        self.instance_id = instance_id
        self.in_flight = in_flight
        self.queue_depth = queue_depth
        self.draining = draining

    def encode(self) -> str:
        """Wire form for the mm-load response trailer."""
        return (
            f"{self.in_flight},{self.queue_depth},"
            f"{1 if self.draining else 0}"
        )

    @classmethod
    def decode(cls, instance_id: str, raw: str) -> Optional["LoadFeedback"]:
        try:
            inflight_s, depth_s, drain_s = raw.split(",")
            return cls(
                instance_id, int(inflight_s), int(depth_s),
                drain_s.strip() == "1",
            )
        except (ValueError, AttributeError):
            return None  # malformed trailer: feedback is advisory


class LoadView:
    """Per-instance load scores: piggybacked feedback + own outstanding.

    Two signals compose the score:

    - **Piggybacked feedback** (the responder's in-flight/queue-depth
      report): authoritative but stale by one round trip, so it decays
      linearly to 0 over ``decay_ms`` — silence means "no evidence",
      not "still as loaded as last reported".
    - **Own outstanding forwards** (``begin``/``end`` around every
      Forward dispatch): the sender's zero-staleness view of the load
      IT is creating. Without it, every thread that just read the same
      feedback herds at the same 'least loaded' peer until the next
      response returns (the classic stale-feedback oscillation the
      power-of-d literature warns about); with it, concurrent picks
      from one sender spread immediately.

    The hot-path read is two dict probes (an immutable slot tuple
    ``(ts_ms, load, fail_ts_ms)`` plus the pending counter) — no lock.
    Writers merge under a striped lock (note() must not clobber a
    concurrent demote's fail stamp and vice versa) and publish by
    rebinding whole values.
    """

    __slots__ = (
        "decay_ms", "_slots", "_pending", "_locks", "notes", "demotions",
    )

    def __init__(self, decay_ms: Optional[int] = None):
        if decay_ms is None:
            from modelmesh_tpu.utils import envs

            decay_ms = envs.get_int("MM_FEEDBACK_DECAY_MS")
        self.decay_ms = max(int(decay_ms), 1)
        # iid -> (ts_ms, load, fail_ts_ms); whole-tuple rebinds only.
        # [rebind]: slot reads/installs are deliberately lock-free
        # (GIL-atomic dict ops on immutable tuples); the striped locks
        # below serialize only the read-modify-write merges.
        #: guarded-by: _locks [rebind]
        self._slots: dict[str, tuple[int, float, int]] = {}
        # iid -> count of OUR forwards currently in flight to the peer.
        # [rebind]: same convention — int rebinds under the stripe lock,
        # lock-free reads.
        #: guarded-by: _locks [rebind]
        self._pending: dict[str, int] = {}
        self._locks = [
            mm_lock("LoadView._locks") for _ in range(_N_STRIPES)
        ]
        # Racy plain-int stats (diagnostics, not accounting).
        self.notes = 0
        self.demotions = 0

    def _lock_for(self, iid: str):
        return self._locks[zlib.crc32(iid.encode()) & (_N_STRIPES - 1)]

    def begin(self, iid: str) -> None:
        """A forward to ``iid`` is being dispatched."""
        with self._lock_for(iid):
            self._pending[iid] = self._pending.get(iid, 0) + 1

    def end(self, iid: str) -> None:
        """The forward completed (any outcome)."""
        with self._lock_for(iid):
            cur = self._pending.get(iid, 0)
            if cur > 1:
                self._pending[iid] = cur - 1
            else:
                self._pending.pop(iid, None)

    def note(self, fb: LoadFeedback, now: Optional[int] = None) -> None:
        """Record one piggybacked report (the Forward return path)."""
        now = now if now is not None else now_ms()
        load = float(fb.in_flight + fb.queue_depth)
        if fb.draining:
            load += DRAIN_BIAS
        with self._lock_for(fb.instance_id):
            prev = self._slots.get(fb.instance_id)
            fail_ts = prev[2] if prev is not None else 0
            self._slots[fb.instance_id] = (now, load, fail_ts)
        self.notes += 1

    def demote(self, iid: str, now: Optional[int] = None) -> None:
        """Stamp a forward failure: a decaying penalty that makes the
        candidate lose every d-choices pick while fresh."""
        now = now if now is not None else now_ms()
        with self._lock_for(iid):
            prev = self._slots.get(iid)
            ts, load = (prev[0], prev[1]) if prev is not None else (0, 0.0)
            self._slots[iid] = (ts, load, now)
        self.demotions += 1

    def score(self, iid: str, now: Optional[int] = None) -> float:
        """Decayed load score; 0.0 = no (fresh) evidence — the greedy
        prior. Single dict probe on the hot path."""
        score = float(self._pending.get(iid, 0))
        slot = self._slots.get(iid)
        if slot is None:
            return score
        now = now if now is not None else now_ms()
        ts, load, fail_ts = slot
        if load > 0.0:
            age = now - ts
            if age < self.decay_ms:
                score += load * (1.0 - age / self.decay_ms)
        if fail_ts:
            fail_age = now - fail_ts
            if fail_age < self.decay_ms:
                score += FAIL_PENALTY * (1.0 - fail_age / self.decay_ms)
        return score

    def staleness_ms(self, now: Optional[int] = None) -> Optional[int]:
        """Age of the OLDEST tracked feedback slot (diagnostics/gauge);
        None when nothing has been heard at all."""
        now = now if now is not None else now_ms()
        ages = [now - ts for ts, _load, _f in self._slots.values() if ts]
        return max(ages) if ages else None

    # Fully-decayed slots linger this many decay windows before pruning
    # (kept briefly for diagnostics; pruned so churned/replaced peers —
    # fresh uuid ids every rolling restart — can't grow the map and the
    # per-instance gauge series without bound).
    PRUNE_AFTER_DECAYS = 3

    def prune(self, now: Optional[int] = None) -> list[str]:
        """Drop slots whose every signal has fully decayed and that have
        no outstanding forwards — called on the publisher cadence, never
        from the request path. Returns the pruned instance ids so the
        caller can retire their per-instance gauge series too."""
        now = now if now is not None else now_ms()
        horizon = self.decay_ms * self.PRUNE_AFTER_DECAYS
        dead = [
            iid for iid, (ts, _load, fail_ts) in list(self._slots.items())
            if now - ts >= horizon and now - fail_ts >= horizon
        ]
        pruned: list[str] = []
        for iid in dead:
            with self._lock_for(iid):
                slot = self._slots.get(iid)
                if (
                    slot is not None
                    and now - slot[0] >= horizon
                    and now - slot[2] >= horizon
                    and iid not in self._pending
                ):
                    del self._slots[iid]
                    pruned.append(iid)
        return pruned

    def clear(self) -> None:
        for lock in self._locks:
            lock.acquire()
        try:
            self._clear_locked()
        finally:
            for lock in self._locks:
                lock.release()

    def _clear_locked(self) -> None:
        """Caller holds every stripe lock."""
        self._slots = {}
        self._pending = {}

    def __len__(self) -> int:
        return len(self._slots)


class ServeCandidate:
    """One ranked serve candidate exported by the placement strategy.

    ``weight`` is the capability weight (normalized advertised capacity;
    1.0 = fleet-typical) and ``loading`` marks the ride-a-loading-copy
    fallback pick, which never participates in d-choices (there is
    nothing to balance — the ranked set is that single copy)."""

    __slots__ = ("iid", "draining", "weight", "loading")

    def __init__(self, iid: str, draining: bool = False,
                 weight: float = 1.0, loading: bool = False):
        self.iid = iid
        self.draining = draining
        self.weight = weight if weight > 0 else 1.0
        self.loading = loading

    def __repr__(self) -> str:  # tests/diagnostics
        flags = "".join(
            f for f, on in (("d", self.draining), ("l", self.loading)) if on
        )
        return f"<{self.iid}{':' + flags if flags else ''} w={self.weight:g}>"


@racedebug.tracked("_by_model")
class RouteCache:
    """Candidate-set memo + anchored power-of-d pick.

    Lock-free on the hit path: entry reads/installs are single dict
    operations on immutable tuples (GIL-atomic); the lock only guards
    the rare size-cap reset. Validity is carried in the entry and
    checked against caller-supplied inputs, so a racing store can never
    make a lookup return candidates whose inputs don't match."""

    __slots__ = (
        "enabled", "ttl_ms", "max_models", "route_d", "load_view", "_rng",
        "_by_model", "_lock", "hits", "misses", "invalidations",
    )

    def __init__(
        self,
        enabled: Optional[bool] = None,
        ttl_ms: Optional[int] = None,
        max_models: int = DEFAULT_MAX_MODELS,
        route_d: Optional[int] = None,
        feedback_decay_ms: Optional[int] = None,
        seed: Optional[int] = None,
    ):
        if enabled is None or ttl_ms is None or route_d is None:
            from modelmesh_tpu.utils import envs

            if enabled is None:
                enabled = envs.get_bool("MM_ROUTE_CACHE")
            if ttl_ms is None:
                ttl_ms = envs.get_int("MM_ROUTE_CACHE_TTL_MS")
            if route_d is None:
                route_d = envs.get_int("MM_ROUTE_D")
        self.enabled = enabled
        self.ttl_ms = max(int(ttl_ms), 1)
        self.max_models = max_models
        self.route_d = max(int(route_d), 1)
        self.load_view = LoadView(decay_ms=feedback_decay_ms)
        # Seeded sampler (det-entropy rule): the d-choices draw is load
        # balancing, not security — a fixed default seed keeps
        # single-threaded tests reproducible; owners wanting per-process
        # spread pass a seed derived from the instance id.
        self._rng = random.Random(seed if seed is not None else 0xD0)
        # model_id -> {exclusion_sig: (candidates, record_version,
        #                              view_epoch, clock_bucket)}
        # [rebind]: inner-map writes are deliberately lock-free (GIL-
        # atomic dict ops; validity is carried in the entry) — only the
        # wholesale resets rebind the dict, and those are guarded.
        #: guarded-by: _lock [rebind]
        self._by_model: dict[str, dict[frozenset, tuple]] = {}
        self._lock = mm_lock("RouteCache._lock")
        # Plain-int stats (racy under contention, monotone enough for
        # bench/diagnostics — not billing).
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    def _bucket(self, now: Optional[int]) -> int:
        return (now if now is not None else now_ms()) // self.ttl_ms

    # -- candidate-set entries ------------------------------------------- #

    def lookup(
        self,
        model_id: str,
        sig: frozenset,
        record_version: int,
        view_epoch: int,
        now: Optional[int] = None,
    ) -> Optional[tuple[ServeCandidate, ...]]:
        """Cached candidate set, or None when absent/any validity input
        moved. The caller picks with :meth:`pick`."""
        sigs = self._by_model.get(model_id)
        entry = sigs.get(sig) if sigs is not None else None
        if (
            entry is not None
            and entry[1] == record_version
            and entry[2] == view_epoch
            and entry[3] == self._bucket(now)
        ):
            self.hits += 1
            return entry[0]
        self.misses += 1
        return None

    def store(
        self,
        model_id: str,
        sig: frozenset,
        record_version: int,
        view_epoch: int,
        candidates: Sequence[ServeCandidate],
        now: Optional[int] = None,
    ) -> None:
        if len(self._by_model) >= self.max_models:
            with self._lock:
                if len(self._by_model) >= self.max_models:
                    self._by_model = {}
        entry = (
            tuple(candidates), record_version, view_epoch, self._bucket(now),
        )
        sigs = self._by_model.setdefault(model_id, {})
        # Signatures per model stay tiny (the trivial external signature
        # plus a handful of multi-hop variants); cap defensively so a
        # pathological exclusion churn can't grow one model's map.
        if len(sigs) >= 16:
            sigs.clear()
        sigs[sig] = entry

    # -- the pick --------------------------------------------------------- #

    def pick(
        self,
        candidates: Sequence[ServeCandidate],
        now: Optional[int] = None,
    ) -> Optional[str]:
        """Anchored power-of-d choice over a ranked candidate set.

        Rank 0 (the greedy winner) is always sampled; d-1 distinct
        others join uniformly. The winner minimizes (draining,
        weighted-load-score, rank): zero/decayed scores reduce to the
        greedy prior, MM_ROUTE_D=1 reduces to exactly the old
        single-winner behavior, and a DRAINING candidate only wins when
        the whole sample drains."""
        n = len(candidates)
        if n == 0:
            return None
        first = candidates[0]
        if n == 1 or self.route_d == 1 or first.loading:
            return first.iid
        lv = self.load_view
        if not lv._slots and not lv._pending:
            # No load evidence anywhere: every sample would tie at 0 and
            # the anchor would win by rank — skip the draw entirely. The
            # uncontended hit path costs what the single-winner cache
            # cost.
            return first.iid
        d = min(self.route_d, n)
        if d == 2:
            # The common case, kept cheap: anchor + ONE uniform draw
            # (random.sample's set machinery costs more than the whole
            # ranking walk it replaces).
            r = self._rng.randrange(1, n)
            sample = ((0, first), (r, candidates[r]))
        elif d >= n:
            sample = tuple(enumerate(candidates))
        else:
            sample = ((0, first),) + tuple(
                (i, candidates[i])
                for i in self._rng.sample(range(1, n), d - 1)
            )
        now = now if now is not None else now_ms()
        best = None
        best_key = None
        for rank, cand in sample:
            key = (cand.draining, lv.score(cand.iid, now) / cand.weight, rank)
            if best_key is None or key < best_key:
                best_key, best = key, cand
        return best.iid

    # -- invalidation / demotion ------------------------------------------ #

    def invalidate(self, model_id: str) -> None:
        if self._by_model.pop(model_id, None) is not None:
            self.invalidations += 1

    def demote(self, model_id: str, iid: str) -> None:
        """A forward to ``iid`` just failed. Demote it WITHIN every
        cached candidate set for the model — surviving candidates keep
        their relative ranking, so concurrent retries spread over them
        instead of re-herding at one recomputed winner — and stamp the
        decaying LoadView penalty so d-choices avoids it everywhere.
        With MM_ROUTE_D=1 the pick always takes rank 0, so parity with
        the old cache requires the old behavior: drop the entry."""
        self.load_view.demote(iid)
        if self.route_d == 1:
            self.invalidate(model_id)
            return
        sigs = self._by_model.get(model_id)
        if not sigs:
            return
        for sig, entry in list(sigs.items()):
            cands = entry[0]
            if not any(c.iid == iid for c in cands):
                continue
            keep = [c for c in cands if c.iid != iid]
            failed = [c for c in cands if c.iid == iid]
            sigs[sig] = (tuple(keep + failed),) + entry[1:]

    def clear(self) -> None:
        with self._lock:
            self._by_model = {}
        self.load_view.clear()

    def __len__(self) -> int:
        return len(self._by_model)
