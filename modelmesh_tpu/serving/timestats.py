"""Per-model-type load-time statistics: streaming mean + 3σ.

Re-derivation of the reference's TimeStats (MM/TimeStats.java:17-45, used
in routing at ModelMesh.java:4351): every successful load records its
duration under the model's type; consumers ask for ``expect_ms`` —
mean + 3σ, the "a healthy load of this type should be done by now" bound.

Uses:
- wait-vs-go-elsewhere on loading copies (serving/instance.py,
  placement/greedy.py): a copy that has been loading LONGER than
  expect_ms is probably stuck — route a fresh load elsewhere; one still
  within the bound is worth forwarding to and waiting on (a second cold
  load elsewhere would take the full load time again).
- serve-side warming penalty (placement/greedy.py): replaces the flat
  10 s floor — a slow-type copy is deprioritized for longer after load.

Welford's algorithm per key; bounded key count (types are few, but ids
are caller-controlled).
"""

from __future__ import annotations

import math
import threading

DEFAULT_EXPECT_MS = 10_000.0  # until min_samples: the old flat floor
MIN_SAMPLES = 3


class TimeStats:
    def __init__(
        self,
        default_ms: float = DEFAULT_EXPECT_MS,
        min_samples: int = MIN_SAMPLES,
        max_keys: int = 4096,
    ):
        self.default_ms = default_ms
        self.min_samples = max(1, min_samples)
        self.max_keys = max_keys
        self._lock = threading.Lock()
        # key -> [n, mean, M2]
        self._stats: dict[str, list[float]] = {}

    def record(self, key: str, duration_ms: float) -> None:
        if duration_ms < 0:
            return
        with self._lock:
            s = self._stats.get(key)
            if s is None:
                if len(self._stats) >= self.max_keys:
                    # Safety valve for caller-controlled keyspaces: drop an
                    # arbitrary half. Types are few in practice.
                    for k in list(self._stats)[: self.max_keys // 2]:
                        del self._stats[k]
                s = self._stats[key] = [0.0, 0.0, 0.0]
            s[0] += 1
            delta = duration_ms - s[1]
            s[1] += delta / s[0]
            s[2] += delta * (duration_ms - s[1])

    def mean_ms(self, key: str) -> float:
        with self._lock:
            s = self._stats.get(key)
            return s[1] if s and s[0] >= self.min_samples else self.default_ms

    def expect_ms(self, key: str) -> float:
        """mean + 3σ; ``default_ms`` until enough samples exist."""
        with self._lock:
            s = self._stats.get(key)
            if s is None or s[0] < self.min_samples:
                return self.default_ms
            n, mean, m2 = s
            std = math.sqrt(m2 / (n - 1)) if n > 1 else 0.0
            return mean + 3.0 * std

    def samples(self, key: str) -> int:
        with self._lock:
            s = self._stats.get(key)
            return int(s[0]) if s else 0
