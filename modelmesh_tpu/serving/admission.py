"""SLO-burn-rate admission control at the external-API edge.

Routing (route_cache.py d-choices) spreads load the fleet CAN absorb;
this module is the overload story for load it can't: per-model-class
token buckets at the edge whose refill is modulated by the PR-8 SLO
burn-rate gauges, shedding (fail-fast with a typed ``OverloadShedError``)
or briefly queueing lower-priority classes when a class trends toward
breach — the explicit-overload-penalty model ("Load Balanced Demand
Distribution under Overload Penalties", PAPERS.md): a deliberate shed at
the edge costs one request; letting queues build collapses tails
fleet-wide.

Mechanics:

- **Priority** is the class's position in ``MM_SLO_SPEC`` (first clause
  = highest priority). The spec is already the operator's statement of
  which traffic matters; no second priority vocabulary.
- **Pressure**: every ``BURN_REFRESH_MS`` (amortized onto the admit
  path, never per-request) the controller reads each active class's
  windowed burn rate from the instance's SloTracker. The
  highest-priority class burning at or above ``BURN_SHED_THRESHOLD``
  sets the pressure level: every class of equal or lower priority is
  throttled — EXCEPT the highest-priority class, which is never
  admission-shed (it is exactly the traffic the shedding protects).
  Throttling a burning low-priority class is deliberate fail-fast:
  shedding its own excess beats queueing it into collapse.
- **Buckets**: a throttled class gets a token bucket seeded from its
  observed admit rate cut by ``BACKOFF``; sustained pressure keeps
  multiplying the refill down (floored), calm multiplies it back up
  until the bucket uncaps entirely. An empty bucket briefly queues the
  request (``MM_ADMISSION_QUEUE_MS``, through the injectable clock so
  the sim exercises it under virtual time) before shedding.
- Shed decisions are recorded in the flight recorder
  (``admission-shed``) and counted (``mm_admission_shed_count``); the
  caller must NOT feed a shed into the SLO window — the control loop
  judges the health of *served* traffic, and counting its own sheds as
  breach would latch the throttle on forever.

``MM_ADMISSION`` (default off) gates the whole controller: off, the
``admit`` call is a single attribute check — the regression-pinned
"behaviorally identical to today" mode.
"""

from __future__ import annotations

import threading
from typing import Optional

from modelmesh_tpu.serving.errors import OverloadShedError
from modelmesh_tpu.utils.clock import get_clock
from modelmesh_tpu.utils.lockdebug import mm_lock

BURN_REFRESH_MS = 250
BURN_SHED_THRESHOLD = 1.0
# Burn evidence below this many windowed completions is cold-start
# noise, not pressure.
MIN_BURN_SAMPLES = 8
RATE_FLOOR_PER_S = 0.5
BACKOFF = 0.5
RECOVER = 1.5
# A recovered bucket whose refill clears its observed demand by this
# factor uncaps (no bucket at all — the common healthy fast path).
UNCAP_HEADROOM = 4.0
# Token burst ceiling as seconds of refill: bounds how big a backlog an
# idle throttled class can dump at once.
BURST_S = 1.0
_QUEUE_POLL_S = 0.005


class _Bucket:
    __slots__ = ("lock", "rate_per_s", "tokens", "last_ms")

    def __init__(self, rate_per_s: float, now_ms: int):
        self.lock = mm_lock("_Bucket.lock")
        self.rate_per_s = rate_per_s  #: guarded-by: lock
        self.tokens = max(rate_per_s * BURST_S, 1.0)  #: guarded-by: lock
        self.last_ms = now_ms  #: guarded-by: lock

    def try_take(self, now_ms: int) -> bool:
        with self.lock:
            elapsed = max(now_ms - self.last_ms, 0)
            self.last_ms = now_ms
            burst = max(self.rate_per_s * BURST_S, 1.0)
            self.tokens = min(
                self.tokens + elapsed * self.rate_per_s / 1000.0, burst
            )
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                return True
            return False


class AdmissionController:
    """Per-model-class admission gate for ONE serving instance.

    ``slo`` is the instance's SloTracker (burn-rate source and the class
    vocabulary — priority is spec order). ``admit`` either returns (the
    request proceeds) or raises ``OverloadShedError``.
    """

    def __init__(
        self,
        slo,
        enabled: Optional[bool] = None,
        queue_ms: Optional[int] = None,
        metrics=None,
        flightrec=None,
    ):
        from modelmesh_tpu.utils import envs

        if enabled is None:
            enabled = envs.get_bool("MM_ADMISSION")
        if queue_ms is None:
            queue_ms = envs.get_int("MM_ADMISSION_QUEUE_MS")
        self.enabled = bool(enabled)
        self.queue_ms = max(int(queue_ms), 0)
        self.slo = slo
        self.metrics = metrics
        self.flightrec = flightrec
        # Spec order IS the priority order (dict preserves insertion).
        self._priority: dict[str, int] = {
            cls: i for i, cls in enumerate(slo.objectives)
        }
        # class -> _Bucket; present only while throttled ([rebind]:
        # installs/removals are GIL-atomic dict ops — readers see either
        # no bucket (uncapped) or a fully-built one).
        #: guarded-by: _refresh_lock [rebind]
        self._buckets: dict[str, _Bucket] = {}
        self._refresh_lock = mm_lock("AdmissionController._refresh_lock")
        self._last_refresh_ms = 0  #: guarded-by: _refresh_lock
        # Per-class admit counts since the last refresh — the observed-
        # rate estimate a fresh bucket seeds from. Plain-int increments
        # (racy, a load estimate not accounting).
        self._admits: dict[str, int] = {}
        # Diagnostics / test handles.
        self.shed_count = 0
        self.queued_count = 0

    # -- hot path ---------------------------------------------------------- #

    def admit(self, model_class: str, cancel_event=None) -> None:
        """Admit or shed one external request of ``model_class``.
        Raises OverloadShedError on shed; returns on admit."""
        if not self.enabled:
            return
        cls = self.slo.resolve_class(model_class)
        clock = get_clock()
        now = clock.now_ms()
        if now - self._last_refresh_ms >= BURN_REFRESH_MS:
            self._refresh(now)
        self._admits[cls] = self._admits.get(cls, 0) + 1
        bucket = self._buckets.get(cls)
        if bucket is None or bucket.try_take(now):
            return
        # Empty bucket: brief bounded queue before the shed — absorbs a
        # burst without letting a sustained overload build a real queue.
        if self.queue_ms > 0:
            deadline = now + self.queue_ms
            while True:
                if cancel_event is not None and cancel_event.is_set():
                    # A disconnect while queued is a CANCELLATION, not a
                    # shed: it must not inflate the shed metrics
                    # operators alert on, and must map to CANCELLED at
                    # the edge like every other cancellation path.
                    from modelmesh_tpu.serving.errors import (
                        RequestCancelledError,
                    )

                    raise RequestCancelledError(
                        f"client cancelled while queued for admission "
                        f"({cls})"
                    )
                clock.sleep(_QUEUE_POLL_S)
                now = clock.now_ms()
                if now >= deadline:
                    break
                bucket = self._buckets.get(cls)
                if bucket is None or bucket.try_take(now):
                    self.queued_count += 1
                    return
        self.shed_count += 1
        if self.metrics is not None:
            from modelmesh_tpu.observability.metrics import Metric as MX

            self.metrics.inc(MX.ADMISSION_SHED_COUNT, model_id=cls)
        if self.flightrec is not None:
            self.flightrec.record("admission-shed", slo_class=cls)
        raise OverloadShedError(cls)

    # -- burn-driven bucket management ------------------------------------- #

    def _refresh(self, now: int) -> None:
        """Re-read burn rates and adjust buckets. One caller per cycle;
        latecomers skip (the gate is advisory on a 250 ms cadence)."""
        if not self._refresh_lock.acquire(blocking=False):
            return
        try:
            self._refresh_locked(now)
        finally:
            self._refresh_lock.release()

    def _refresh_locked(self, now: int) -> None:
        """Caller holds _refresh_lock."""
        if now - self._last_refresh_ms < BURN_REFRESH_MS:
            return
        elapsed_ms = max(now - self._last_refresh_ms, 1)
        self._last_refresh_ms = now
        admits, self._admits = self._admits, {}
        pressure_idx: Optional[int] = None
        for cls in self.slo.classes():
            snap = self.slo.attainment(cls)
            if (
                snap.requests >= MIN_BURN_SAMPLES
                and snap.burn_rate >= BURN_SHED_THRESHOLD
            ):
                idx = self._priority.get(cls, len(self._priority))
                if pressure_idx is None or idx < pressure_idx:
                    pressure_idx = idx
        for cls, idx in self._priority.items():
            throttle = (
                pressure_idx is not None
                and idx >= pressure_idx
                and idx != 0
            )
            bucket = self._buckets.get(cls)
            observed_per_s = admits.get(cls, 0) * 1000.0 / elapsed_ms
            if throttle:
                if bucket is None:
                    seed = max(observed_per_s * BACKOFF, RATE_FLOOR_PER_S)
                    self._buckets[cls] = _Bucket(seed, now)
                    self._record_throttle(cls, seed)
                else:
                    with bucket.lock:
                        bucket.rate_per_s = max(
                            bucket.rate_per_s * BACKOFF, RATE_FLOOR_PER_S
                        )
            elif bucket is not None:
                with bucket.lock:
                    bucket.rate_per_s *= RECOVER
                    rate = bucket.rate_per_s
                if rate >= max(observed_per_s, 1.0) * UNCAP_HEADROOM:
                    self._buckets.pop(cls, None)
                    self._record_throttle(cls, None)

    def _record_throttle(self, cls: str, rate: Optional[float]) -> None:
        if self.flightrec is not None:
            if rate is None:
                self.flightrec.record("admission-uncap", slo_class=cls)
            else:
                self.flightrec.record(
                    "admission-throttle", slo_class=cls,
                    rate_per_s=round(rate, 3),
                )

    # -- introspection ----------------------------------------------------- #

    def throttled_classes(self) -> list[str]:
        return list(self._buckets)
