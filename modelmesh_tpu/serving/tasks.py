"""Background tasks: publisher, rate-tracking scaler, janitor, leader reaper.

The autonomous layer of every instance (reference scheduled tasks,
ModelMesh.java:1151-1172; behaviors in SURVEY.md section 3.5):

- publisher: refresh our InstanceRecord advertisement periodically (40 s in
  the reference; configurable here).
- rate task (10 s): per-model scale-up — the 1->2 "used again" pattern and
  the RPM-threshold N>2 rule (rateTrackingTask :5619-5806, default
  threshold 2000 RPM per copy, :240).
- janitor (6 min): local cache <-> registry reconciliation in both
  directions, failure-record expiry, lazy lastUsed persistence, and
  cluster-full scale-down of surplus copies (:5876-6379).
- reaper (7 min, leader only): prune registrations pointing at instances
  gone >10 min (:6524-6608), drop stale loading claims, and proactive
  loading of recently-used-but-unloaded models into free space
  (:6616-6747). The reaper consults the PlacementStrategy, so the JAX
  global plan slots in here.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Optional

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.kv.store import CasFailed
from modelmesh_tpu.records import ModelRecord
from modelmesh_tpu.serving.entry import EntryState
from modelmesh_tpu.serving.instance import ModelMeshInstance
from modelmesh_tpu.utils.clock import get_clock

log = logging.getLogger(__name__)

DEFAULT_SCALE_UP_RPM = 2000          # per copy (reference :240)
SECOND_COPY_MIN_AGE_MS = 7 * 60_000  # "used again" window (reference :249)
SECOND_COPY_MAX_AGE_MS = 40 * 60_000
ASSUME_INSTANCE_GONE_MS = 10 * 60_000   # reaper prune grace (reference :270)
STALE_LOADING_CLAIM_MS = 20 * 60_000    # loading claim with no progress
CLUSTER_FULL_FRACTION = 0.95            # scale-down trigger (reference :6197)
# Surplus-copy lifetime bounds (reference :249-257): never shed a copy
# younger than the min (anti-thrash with the scale-up window); a low-traffic
# surplus copy older than the 10 h cap sheds even when the cluster isn't
# full.
SURPLUS_COPY_MIN_AGE_MS = 7 * 60_000
SURPLUS_COPY_MAX_AGE_MS = 10 * 3600_000
PROACTIVE_RESERVE_FRACTION = 0.125      # keep 12.5% free (reference :6616)


class TaskConfig:
    def __init__(
        self,
        publish_interval_s: float = 40.0,
        rate_interval_s: float = 10.0,
        janitor_interval_s: float = 360.0,
        reaper_interval_s: float = 420.0,
        scale_up_rpm: int = DEFAULT_SCALE_UP_RPM,
        second_copy_min_age_ms: int = SECOND_COPY_MIN_AGE_MS,
        second_copy_max_age_ms: int = SECOND_COPY_MAX_AGE_MS,
        assume_gone_ms: int = ASSUME_INSTANCE_GONE_MS,
        max_copies: int = 8,
        jitter_frac: float = 0.1,
    ):
        self.publish_interval_s = publish_interval_s
        self.rate_interval_s = rate_interval_s
        self.janitor_interval_s = janitor_interval_s
        self.reaper_interval_s = reaper_interval_s
        self.scale_up_rpm = scale_up_rpm
        self.second_copy_min_age_ms = second_copy_min_age_ms
        self.second_copy_max_age_ms = second_copy_max_age_ms
        self.assume_gone_ms = assume_gone_ms
        self.max_copies = max_copies
        # Cadence jitter (fraction of the interval, 0 disables): each tick
        # waits interval*(1 ± U[0,jitter]), and the FIRST tick is phase-
        # shifted by U[0,1)*interval — both drawn from a per-(instance,
        # task) seeded RNG, so a mass-restarted fleet spreads its
        # publisher/janitor KV load instead of thundering in lockstep.
        self.jitter_frac = jitter_frac


class BackgroundTasks:
    def __init__(
        self, instance: ModelMeshInstance, config: Optional[TaskConfig] = None
    ):
        self.instance = instance
        self.config = config or TaskConfig()
        self._clock = get_clock()
        self._stop = self._clock.new_event()
        self._threads: list[threading.Thread] = []
        # Observability: per-task tick timestamps (clock ms; the FIRST
        # _TICK_LOG_CAP per task). The sim's jitter scenario reads these
        # to assert a mass-restarted fleet doesn't fire in lockstep; each
        # list is appended only by its own task thread.
        self.tick_times: dict[str, list[int]] = {}
        # model_id -> previous-use timestamp at last rate tick (drives the
        # 1->2 "used, idle, used again" heuristic).
        self._prev_use: dict[str, int] = {}
        self._last_rate_tick = now_ms()
        # leader state: instance_id -> first time we noticed it missing.
        self._missing_since: dict[str, int] = {}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        specs = [
            ("publisher", self.config.publish_interval_s, self._publish_tick),
            ("rate", self.config.rate_interval_s, self._rate_tick),
            ("janitor", self.config.janitor_interval_s, self._janitor_tick),
            ("reaper", self.config.reaper_interval_s, self._reaper_tick),
        ]
        for name, interval, fn in specs:
            t = threading.Thread(
                target=self._loop, args=(name, interval, fn),
                name=f"task-{name}-{self.instance.instance_id}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()

    _TICK_LOG_CAP = 64

    # Tasks that mutate the registry skip their cycle when the KV store is
    # unreachable (reference janitor/reaper guard, ModelMesh.java:5886,
    # 6449) — half-applied reconciliation against a flapping store does
    # more harm than a skipped cycle.
    _NEEDS_KV = frozenset({"janitor", "reaper"})

    def _kv_reachable(self) -> bool:
        try:
            self.instance.store.get(
                f"{self.instance.config.kv_prefix}/__health__"
            )
            return True
        except Exception:  # noqa: BLE001 — any store error counts
            return False

    def _loop(self, name: str, interval: float, fn) -> None:
        # Deterministic per-(instance, task) jitter stream: the seed is the
        # identity, not entropy, so a sim replay sees identical cadences.
        rng = random.Random(f"{self.instance.instance_id}:{name}")
        jitter = max(0.0, self.config.jitter_frac)
        # Initial phase offset — the anti-thundering-herd half: a fleet
        # restarted at the same instant must not fire its first publisher/
        # janitor cycle at the same instant too.
        wait_s = interval * rng.random() if jitter > 0 else interval
        ticks = self.tick_times.setdefault(name, [])
        while not self._clock.wait_event(self._stop, wait_s):
            wait_s = interval * (
                1.0 + jitter * (2.0 * rng.random() - 1.0)
            ) if jitter > 0 else interval
            if len(ticks) < self._TICK_LOG_CAP:
                # Bounded from the FRONT: consumers (the sim's jitter
                # spread check) read the first ticks; later ones are
                # droppable, the earliest never silently evicted.
                ticks.append(now_ms())
            if self.instance.shutting_down:
                return
            if name in self._NEEDS_KV and not self._kv_reachable():
                log.warning("task %s: kv unreachable; skipping cycle", name)
                continue
            try:
                fn()
            except Exception:  # noqa: BLE001 — tasks must not die
                log.exception("task %s failed (cycle aborted)", name)

    # -- publisher ---------------------------------------------------------

    def _publish_tick(self) -> None:
        self.instance.publish_instance_record()

    # -- rate task: scale UP ----------------------------------------------

    def _rate_tick(self) -> None:
        inst = self.instance
        cfg = self.config
        tick_start = now_ms()
        cutoff = self._last_rate_tick
        self._last_rate_tick = tick_start
        # Prune usage history for models no longer cached here (stale
        # entries both leak and can trigger spurious 1->2 scale-ups when a
        # model id is re-registered later).
        cached = set(inst.cache.keys())
        for gone in [k for k in self._prev_use if k not in cached]:
            del self._prev_use[gone]
        for model_id, ce, last_used in inst.cache.items_used_since(cutoff):
            if ce.state is not EntryState.ACTIVE:
                continue
            mr = inst.registry_view.get(model_id)
            if mr is None:
                continue
            copies = mr.copy_count
            prev = self._prev_use.get(model_id, 0)
            self._prev_use[model_id] = last_used
            if copies >= cfg.max_copies:
                continue
            if copies <= 1:
                # 1 -> 2: the model was used a while ago AND is in use now —
                # recurring traffic deserves redundancy.
                age = last_used - prev
                if prev and cfg.second_copy_min_age_ms <= age <= cfg.second_copy_max_age_ms:
                    self._add_copy(model_id, mr)
                    continue
            # Local per-copy rate vs the per-copy threshold (applies at any
            # copy count — a saturated single copy must scale too): each
            # instance sees only its own copy's traffic, so if the copy it
            # serves is at threshold, the model needs another copy
            # (reference rateTrackingTask, ModelMesh.java:5762). In latency
            # mode (runtime declared a per-model concurrency limit) the
            # threshold is dynamic: 90% of this copy's measured bandwidth
            # (reference :719-732).
            rpm = inst.model_rpm(model_id)
            threshold = cfg.scale_up_rpm
            bandwidth = ce.bandwidth_rpm()
            if bandwidth > 0:
                threshold = max(1, int(bandwidth * 0.9))
            if rpm >= threshold:
                self._add_copy(model_id, mr)

    def _add_copy(self, model_id: str, mr: ModelRecord) -> None:
        try:
            self.instance.ensure_loaded(
                model_id, sync=False, exclude=set(mr.all_placements)
            )
            log.info("scale-up: requested extra copy of %s", model_id)
        except Exception as e:  # noqa: BLE001 — advisory
            log.debug("scale-up of %s skipped: %s", model_id, e)

    # -- janitor: reconcile + scale DOWN ----------------------------------

    def _janitor_tick(self) -> None:
        inst = self.instance
        now = now_ms()
        # (a) registry -> local: drop local copies of unregistered models;
        #     repair records that lost our placement entry.
        for model_id in inst.cache.keys():
            ce = inst.cache.get_quietly(model_id)
            if ce is None:
                continue
            mr = inst.registry.get(model_id)
            if mr is None:
                log.info("janitor: %s unregistered; removing local copy", model_id)
                inst._remove_local(model_id)
                continue
            changed = False
            if (
                ce.state is EntryState.ACTIVE
                and inst.instance_id not in mr.instance_ids
            ):
                mr.promote_loaded(inst.instance_id, ce.load_completed_ms or now)
                changed = True
            if mr.expire_load_failures(now):
                changed = True
            # Lazy lastUsed persistence (reference ModelRecord.java:96-105).
            local_last_used = inst.cache.last_used(model_id) or 0
            if mr.should_persist_last_used(local_last_used):
                mr.last_used = local_last_used
                changed = True
            if changed:
                try:
                    inst.registry.conditional_set(model_id, mr)
                except CasFailed:
                    pass
        # (b) local -> registry: records claiming we hold a copy we don't.
        for model_id, mr in inst.registry.items():
            if mr.placed_on(inst.instance_id) and model_id not in inst.cache:
                def fix(cur):
                    if cur is None:
                        return None
                    cur.remove_instance(inst.instance_id)
                    return cur
                try:
                    inst.registry.update_or_create(model_id, fix)
                except CasFailed:
                    pass
        # (c) scale-down when the cluster is nearly full.
        self._maybe_scale_down()

    def _cluster_fullness(self, model_type: Optional[str] = None) -> float:
        """Fullness over the candidate subset for ``model_type`` (per-label
        subset stats, InstanceSetStatsTracker.java:17-40) — global fullness
        is wrong in heterogeneous clusters: a full GPU-labeled pool must
        trigger scale-down of GPU models even while CPU pools sit empty,
        and vice versa."""
        views = list(self.instance.instances_view.items())
        constraints = self.instance.constraints
        if model_type is not None and constraints is not None:
            subset = [
                (i, r) for i, r in views
                if constraints.is_candidate(model_type, r.labels)
            ]
            views = subset or views
        cap = sum(r.capacity_units for _, r in views) or 1
        used = sum(r.used_units for _, r in views)
        return used / cap

    def _maybe_scale_down(self) -> None:
        inst = self.instance
        cfg = self.config
        # Memoize per-type subset fullness for this pass.
        fullness: dict[Optional[str], float] = {}

        def subset_full(model_type: Optional[str]) -> bool:
            if inst.constraints is None:
                model_type = None
            f = fullness.get(model_type)
            if f is None:
                f = fullness[model_type] = self._cluster_fullness(model_type)
            return f >= CLUSTER_FULL_FRACTION

        now = now_ms()
        for model_id in inst.cache.keys():
            mr = inst.registry_view.get(model_id)
            # Count only READY copies: a copy still loading elsewhere must
            # not license dropping the sole active one.
            if mr is None or len(mr.instance_ids) < 2:
                continue
            our_ts = mr.instance_ids.get(inst.instance_id)
            if our_ts is None:
                continue
            age = now - our_ts
            if age < SURPLUS_COPY_MIN_AGE_MS:
                continue  # anti-thrash: too young to shed
            rpm = inst.model_rpm(model_id)
            # Our copy is surplus if OUR traffic is well under the per-copy
            # threshold (reference: < 2/3 of it, :6197-6379) — local rate vs
            # per-copy threshold, symmetric with scale-up.
            if rpm >= cfg.scale_up_rpm * 2 // 3:
                continue
            # Fullness gates ordinary scale-down; a surplus copy past the
            # 10 h cap sheds regardless (reference :257).
            if not subset_full(mr.model_type) and age < SURPLUS_COPY_MAX_AGE_MS:
                continue
            # Shedder: the NEWEST copy's holder (tie-break id) — keeps the
            # established copy and rotates fairly as newest changes, unlike
            # highest-id-always-sheds which skews one instance forever.
            shedder = max(
                mr.instance_ids.items(), key=lambda kv: (kv[1], kv[0])
            )[0]
            if shedder == inst.instance_id:
                log.info("scale-down: dropping surplus copy of %s", model_id)
                inst._remove_local(model_id)

    # -- reaper (leader only) ---------------------------------------------

    def _reaper_tick(self) -> None:
        inst = self.instance
        if not inst.is_leader:
            self._missing_since.clear()
            return
        # One registry scan + one view snapshot feed the plan refresh, the
        # gauges, the prune pass, and proactive loading below — items() is
        # a full KV range read, unaffordable to repeat per concern at 100k
        # models.
        views = list(inst.instances_view.items())
        records = list(inst.registry.items())
        live = {iid for iid, _ in views}
        # When the instance runs the JAX global strategy, the reaper is its
        # refresh cadence: solve one global plan from current state; the
        # routing layer serves decisions from it until the next pass.
        refresh = getattr(inst.strategy, "refresh", None)
        if refresh is not None:
            try:
                plan = refresh(records, views, inst.model_rpm)
                # Publish so EVERY instance's PlanFollower (instance.py)
                # serves this solve, not just the leader's own strategy.
                from modelmesh_tpu.placement.plan_sync import publish_plan

                publish_plan(inst.store, inst.config.kv_prefix, plan)
            except Exception:  # noqa: BLE001 — plan is advisory
                log.exception("global plan refresh/publish failed")
        now = now_ms()
        # Leader-published fleet gauges (reference cluster-scope metrics).
        from modelmesh_tpu.observability.metrics import Metric as _MX

        inst.metrics.set_gauge(_MX.CLUSTER_INSTANCES, len(views))
        inst.metrics.set_gauge(_MX.CLUSTER_MODELS, len(records))
        inst.metrics.set_gauge(
            _MX.CLUSTER_COPIES,
            sum(len(mr.instance_ids) for _, mr in records),
        )
        inst.metrics.set_gauge(
            _MX.CLUSTER_CAPACITY_UNITS,
            sum(r.capacity_units for _, r in views),
        )
        inst.metrics.set_gauge(
            _MX.CLUSTER_USED_UNITS, sum(r.used_units for _, r in views)
        )
        # Track how long each referenced instance has been missing.
        referenced: set[str] = set()
        for _, mr in records:
            referenced |= mr.all_placements
            # Host-tier claims (transfer/ demotions) are peer-fetch
            # sources, not servable placements — but a dead holder's
            # claim must be pruned the same way or receivers keep
            # dialing a ghost before falling back.
            referenced |= set(mr.host_instances)
        for iid in referenced - live:
            self._missing_since.setdefault(iid, now)
        for iid in list(self._missing_since):
            if iid in live:
                del self._missing_since[iid]
        gone = {
            iid for iid, since in self._missing_since.items()
            if now - since >= self.config.assume_gone_ms
        }
        # (a) prune placements on gone instances + stale loading claims.
        # SUPPRESSED in KV-migration read-only mode: holders registered in
        # the OTHER kv store are invisible here and would all look "gone"
        # (reference skips pruning under readOnlyMode, ModelMesh.java:6543).
        if inst.config.read_only:
            self._proactive_load(records, visible_only=live)
            return
        for model_id, mr in records:
            stale_claims = [
                iid for iid, ts in mr.loading_instances.items()
                if iid in gone or (
                    iid not in live and now - ts > STALE_LOADING_CLAIM_MS
                )
            ]
            dead = [iid for iid in mr.instance_ids if iid in gone]
            dead_hosts = [iid for iid in mr.host_instances if iid in gone]
            if not stale_claims and not dead and not dead_hosts:
                continue

            def prune(cur):
                if cur is None:
                    return None
                for iid in stale_claims + dead + dead_hosts:
                    cur.remove_instance(iid)
                return cur

            try:
                inst.registry.update_or_create(model_id, prune)
                log.info(
                    "reaper: pruned %s from %s",
                    stale_claims + dead + dead_hosts, model_id,
                )
            except CasFailed:
                pass
        # (b) proactive loading: restore the most-recently-used unloaded
        #     models into free cluster space, above a reserve.
        self._proactive_load(records)

    def _proactive_load(self, records, visible_only=None) -> None:
        """``visible_only``: in KV-migration read-only mode, placements on
        instances outside OUR instance registry belong to the other store's
        fleet — for load decisions they count as not loaded here (reference
        filters insts to instanceInfo under readOnlyMode,
        ModelMesh.java:6547-6551)."""
        inst = self.instance
        views = inst.instances_view.items()
        cap = sum(r.capacity_units for _, r in views) or 1
        used = sum(r.used_units for _, r in views)
        budget_units = int((cap - used) - cap * PROACTIVE_RESERVE_FRACTION) // 2
        if budget_units <= 0:
            return

        def visible(ids):
            if visible_only is None:
                return ids
            return [i for i in ids if i in visible_only]

        # loading_instances gets the same filter: an other-store (or stale)
        # claim must not block the local load for the whole migration —
        # read-only mode suppresses the pruning that would clear it.
        unloaded = [
            (mr.last_used, model_id, mr)
            for model_id, mr in records
            if not visible(mr.instance_ids) and not visible(mr.loading_instances)
            and not mr.load_exhausted()
        ]
        unloaded.sort(reverse=True, key=lambda t: t[0])
        default_units = 128
        loads = 0
        for last_used, model_id, mr in unloaded:
            if loads >= 8:  # bounded per pass
                break
            cost = mr.size_units or default_units
            if cost > budget_units:
                continue  # next candidate might be smaller
            try:
                inst.ensure_loaded(model_id, last_used_ms=last_used, sync=False)
                budget_units -= cost
                loads += 1
                log.info("reaper: proactive load of %s (%du)", model_id, cost)
            except Exception as e:  # noqa: BLE001 — advisory
                log.debug("proactive load of %s skipped: %s", model_id, e)
                break
