"""Background tasks: publisher, rate-tracking scaler, janitor, leader reaper.

The autonomous layer of every instance (reference scheduled tasks,
ModelMesh.java:1151-1172; behaviors in SURVEY.md section 3.5):

- publisher: refresh our InstanceRecord advertisement periodically (40 s in
  the reference; configurable here).
- rate task (10 s): per-model scale-up — the 1->2 "used again" pattern and
  the RPM-threshold N>2 rule (rateTrackingTask :5619-5806, default
  threshold 2000 RPM per copy, :240).
- janitor (6 min): local cache <-> registry reconciliation in both
  directions, failure-record expiry, lazy lastUsed persistence, and
  cluster-full scale-down of surplus copies (:5876-6379).
- reaper (7 min, leader only): prune registrations pointing at instances
  gone >10 min (:6524-6608), drop stale loading claims, and proactive
  loading of recently-used-but-unloaded models into free space
  (:6616-6747). The reaper consults the PlacementStrategy, so the JAX
  global plan slots in here.
"""

from __future__ import annotations

import logging
import random
import threading
import weakref
from typing import Optional

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.kv.store import CasFailed
from modelmesh_tpu.records import ModelRecord
from modelmesh_tpu.serving.entry import EntryState
from modelmesh_tpu.serving.instance import ModelMeshInstance
from modelmesh_tpu.utils.clock import get_clock

log = logging.getLogger(__name__)

DEFAULT_SCALE_UP_RPM = 2000          # per copy (reference :240)
SECOND_COPY_MIN_AGE_MS = 7 * 60_000  # "used again" window (reference :249)
SECOND_COPY_MAX_AGE_MS = 40 * 60_000
ASSUME_INSTANCE_GONE_MS = 10 * 60_000   # reaper prune grace (reference :270)
STALE_LOADING_CLAIM_MS = 20 * 60_000    # loading claim with no progress
CLUSTER_FULL_FRACTION = 0.95            # scale-down trigger (reference :6197)
# Surplus-copy lifetime bounds (reference :249-257): never shed a copy
# younger than the min (anti-thrash with the scale-up window); a low-traffic
# surplus copy older than the 10 h cap sheds even when the cluster isn't
# full.
SURPLUS_COPY_MIN_AGE_MS = 7 * 60_000
SURPLUS_COPY_MAX_AGE_MS = 10 * 3600_000
PROACTIVE_RESERVE_FRACTION = 0.125      # keep 12.5% free (reference :6616)


def cluster_fullness(inst, model_type: Optional[str] = None) -> float:
    """Fullness over the candidate subset for ``model_type`` (per-label
    subset stats, InstanceSetStatsTracker.java:17-40) — global fullness
    is wrong in heterogeneous clusters: a full GPU-labeled pool must
    trigger scale-down of GPU models even while CPU pools sit empty,
    and vice versa. Shared by the legacy janitor and the autoscale
    controller's capacity valve."""
    views = list(inst.instances_view.items())
    constraints = inst.constraints
    if model_type is not None and constraints is not None:
        subset = [
            (i, r) for i, r in views
            if constraints.is_candidate(model_type, r.labels)
        ]
        views = subset or views
    cap = sum(r.capacity_units for _, r in views) or 1
    used = sum(r.used_units for _, r in views)
    return used / cap


def surplus_shed_eligible(
    inst, model_id: str, mr: ModelRecord, now: int, min_age_ms: int,
    scale_up_rpm: int,
) -> bool:
    """The surplus-copy predicate BOTH scaling authorities share (the
    legacy janitor's cluster-full scale-down and the autoscale
    controller's calm-class demotion — one definition so their
    eligibility rules cannot fork): this instance holds one of >= 2
    READY copies (a copy still loading elsewhere must not license
    dropping the sole active one), the copy is past the anti-thrash
    minimum age, and OUR local traffic is well under the per-copy
    threshold (< 2/3 of it, reference :6197-6379 — symmetric with
    scale-up)."""
    if mr is None or len(mr.instance_ids) < 2:
        return False
    our_ts = mr.instance_ids.get(inst.instance_id)
    if our_ts is None:
        return False
    if now - our_ts < min_age_ms:
        return False
    return inst.model_rpm(model_id) < scale_up_rpm * 2 // 3


def elected_shedder(mr: ModelRecord) -> str:
    """Shedder election shared by both scaling authorities: the NEWEST
    copy's holder (tie-break id) sheds — keeps the established copy and
    rotates fairly as newest changes, unlike highest-id-always-sheds
    which skews one instance forever."""
    return max(mr.instance_ids.items(), key=lambda kv: (kv[1], kv[0]))[0]


class TaskConfig:
    def __init__(
        self,
        publish_interval_s: float = 40.0,
        rate_interval_s: float = 10.0,
        janitor_interval_s: float = 360.0,
        reaper_interval_s: float = 420.0,
        scale_up_rpm: int = DEFAULT_SCALE_UP_RPM,
        second_copy_min_age_ms: int = SECOND_COPY_MIN_AGE_MS,
        second_copy_max_age_ms: int = SECOND_COPY_MAX_AGE_MS,
        assume_gone_ms: int = ASSUME_INSTANCE_GONE_MS,
        max_copies: int = 8,
        jitter_frac: float = 0.1,
        autoscale_mode: Optional[str] = None,
        autoscale_interval_s: float = 10.0,
        autoscale=None,
    ):
        self.publish_interval_s = publish_interval_s
        self.rate_interval_s = rate_interval_s
        self.janitor_interval_s = janitor_interval_s
        self.reaper_interval_s = reaper_interval_s
        self.scale_up_rpm = scale_up_rpm
        self.second_copy_min_age_ms = second_copy_min_age_ms
        self.second_copy_max_age_ms = second_copy_max_age_ms
        self.assume_gone_ms = assume_gone_ms
        self.max_copies = max_copies
        # Cadence jitter (fraction of the interval, 0 disables): each tick
        # waits interval*(1 ± U[0,jitter]), and the FIRST tick is phase-
        # shifted by U[0,1)*interval — both drawn from a per-(instance,
        # task) seeded RNG, so a mass-restarted fleet spreads its
        # publisher/janitor KV load instead of thundering in lockstep.
        self.jitter_frac = jitter_frac
        # The ONE copy-scaling authority (MM_AUTOSCALE): "legacy" keeps
        # the rate-task scale-up + janitor cluster-full scale-down
        # exactly as before; "burn" replaces BOTH with the autoscale/
        # controller (its tick rides the same task machinery); "off"
        # disables scaling entirely. Exactly one authority ever runs.
        if autoscale_mode is None:
            from modelmesh_tpu.utils import envs

            autoscale_mode = envs.get("MM_AUTOSCALE") or "legacy"
        from modelmesh_tpu.autoscale.controller import MODES

        if autoscale_mode not in MODES:
            raise ValueError(
                f"MM_AUTOSCALE={autoscale_mode!r} — expected one of {MODES}"
            )
        self.autoscale_mode = autoscale_mode
        self.autoscale_interval_s = autoscale_interval_s
        # Optional AutoscaleConfig override (tests/benches/scenarios);
        # None builds the env-resolved defaults, sharing this config's
        # max_copies and per-copy rate threshold.
        self.autoscale = autoscale


class BackgroundTasks:
    def __init__(
        self, instance: ModelMeshInstance, config: Optional[TaskConfig] = None
    ):
        self.instance = instance
        self.config = config or TaskConfig()
        self._clock = get_clock()
        self._stop = self._clock.new_event()
        self._threads: list[threading.Thread] = []
        # Observability: per-task tick timestamps (clock ms; the FIRST
        # _TICK_LOG_CAP per task). The sim's jitter scenario reads these
        # to assert a mass-restarted fleet doesn't fire in lockstep; each
        # list is appended only by its own task thread.
        self.tick_times: dict[str, list[int]] = {}
        # model_id -> (previous-use timestamp at last rate tick, a
        # WEAK ref to the CacheEntry it was observed on). The entry
        # identity pins the prev-use sample to one model INCARNATION: a
        # delete→re-register inside a rate interval mints a fresh entry,
        # and comparing identities makes the stale timestamp read as "no
        # previous use" instead of fabricating a used-again age that
        # trips a spurious 1->2 scale-up (the serving/tasks.py:184
        # leak). Weak, not strong: a strong ref would pin the dead
        # incarnation's entry (and its loaded-weights handle) until the
        # model is next used; a dead ref simply reads as a fresh
        # incarnation, which is the correct answer anyway.
        self._prev_use: dict[str, tuple[int, object]] = {}
        self._last_rate_tick = now_ms()
        # leader state: instance_id -> first time we noticed it missing.
        self._missing_since: dict[str, int] = {}
        # Autoscale controller (autoscale/controller.py), present only in
        # burn mode — the single non-legacy scaling authority.
        self.autoscaler = None
        if self.config.autoscale_mode == "burn":
            from modelmesh_tpu.autoscale.controller import (
                AutoscaleConfig,
                AutoscaleController,
            )

            import copy as _copy

            asc = self.config.autoscale
            if asc is None:
                asc = AutoscaleConfig()
            # Unpinned controller bounds inherit THIS task config's, so
            # the ceiling the controller enforces and the one the sim's
            # copy_bounds invariant checks are the same number even for
            # scenarios passing an explicit AutoscaleConfig. Resolved on
            # a COPY: the caller's config object may be shared across
            # fleets, and writing through it would make two clusters'
            # controllers last-writer-wins on each other's ceilings.
            asc = _copy.copy(asc)
            if not asc._max_copies_pinned:
                asc.max_copies = self.config.max_copies
            if not asc._scale_up_rpm_pinned:
                asc.scale_up_rpm = self.config.scale_up_rpm
            self.autoscaler = AutoscaleController(instance, asc)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        specs = [
            ("publisher", self.config.publish_interval_s, self._publish_tick),
            ("janitor", self.config.janitor_interval_s, self._janitor_tick),
            ("reaper", self.config.reaper_interval_s, self._reaper_tick),
        ]
        # Exactly one scaling authority: the legacy rate-task scaler OR
        # the burn-rate autoscale controller (or neither, mode "off").
        # The janitor's cluster-full scale-down is gated the same way in
        # _janitor_tick — reconciliation always runs, scale-down only
        # under the legacy authority.
        mode = self.config.autoscale_mode
        if mode == "legacy":
            specs.insert(
                1, ("rate", self.config.rate_interval_s, self._rate_tick)
            )
        elif mode == "burn":
            specs.insert(
                1,
                ("autoscale", self.config.autoscale_interval_s,
                 self._autoscale_tick),
            )
        for name, interval, fn in specs:
            t = threading.Thread(
                target=self._loop, args=(name, interval, fn),
                name=f"task-{name}-{self.instance.instance_id}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()

    _TICK_LOG_CAP = 64

    # Tasks that mutate the registry skip their cycle when the KV store is
    # unreachable (reference janitor/reaper guard, ModelMesh.java:5886,
    # 6449) — half-applied reconciliation against a flapping store does
    # more harm than a skipped cycle. The autoscale tick qualifies: its
    # decisions CAS the registry (copy adds/demotions) and read/write the
    # pre-warm plan key.
    _NEEDS_KV = frozenset({"janitor", "reaper", "autoscale"})

    def _kv_reachable(self) -> bool:
        try:
            self.instance.store.get(
                f"{self.instance.config.kv_prefix}/__health__"
            )
            return True
        except Exception:  # noqa: BLE001 — any store error counts
            return False

    def _loop(self, name: str, interval: float, fn) -> None:
        # Deterministic per-(instance, task) jitter stream: the seed is the
        # identity, not entropy, so a sim replay sees identical cadences.
        rng = random.Random(f"{self.instance.instance_id}:{name}")
        jitter = max(0.0, self.config.jitter_frac)
        # Initial phase offset — the anti-thundering-herd half: a fleet
        # restarted at the same instant must not fire its first publisher/
        # janitor cycle at the same instant too.
        wait_s = interval * rng.random() if jitter > 0 else interval
        ticks = self.tick_times.setdefault(name, [])
        while not self._clock.wait_event(self._stop, wait_s):
            wait_s = interval * (
                1.0 + jitter * (2.0 * rng.random() - 1.0)
            ) if jitter > 0 else interval
            if len(ticks) < self._TICK_LOG_CAP:
                # Bounded from the FRONT: consumers (the sim's jitter
                # spread check) read the first ticks; later ones are
                # droppable, the earliest never silently evicted.
                ticks.append(now_ms())
            if self.instance.shutting_down:
                return
            if name in self._NEEDS_KV and not self._kv_reachable():
                log.warning("task %s: kv unreachable; skipping cycle", name)
                continue
            try:
                fn()
            except Exception:  # noqa: BLE001 — tasks must not die
                log.exception("task %s failed (cycle aborted)", name)

    # -- publisher ---------------------------------------------------------

    def _publish_tick(self) -> None:
        self.instance.publish_instance_record()

    # -- autoscale controller (burn mode) ----------------------------------

    def _autoscale_tick(self) -> None:
        self.autoscaler.tick()

    # -- rate task: scale UP ----------------------------------------------

    def _rate_tick(self) -> None:
        inst = self.instance
        cfg = self.config
        tick_start = now_ms()
        cutoff = self._last_rate_tick
        self._last_rate_tick = tick_start
        # Prune usage history for models no longer cached here (stale
        # entries leak). Pruning alone cannot catch a model deleted AND
        # re-registered between two ticks (the id is back in the cache by
        # the time we look), so each sample below also carries the
        # CacheEntry it was observed on — a fresh incarnation never
        # inherits the dead one's timestamp.
        cached = set(inst.cache.keys())
        for gone in [k for k in self._prev_use if k not in cached]:
            del self._prev_use[gone]
        for model_id, ce, last_used in inst.cache.items_used_since(cutoff):
            if ce.state is not EntryState.ACTIVE:
                continue
            mr = inst.registry_view.get(model_id)
            if mr is None:
                continue
            copies = mr.copy_count
            prev_sample = self._prev_use.get(model_id)
            prev = (
                prev_sample[0]
                if prev_sample is not None and prev_sample[1]() is ce
                else 0
            )
            self._prev_use[model_id] = (last_used, weakref.ref(ce))
            if copies >= cfg.max_copies:
                continue
            if copies <= 1:
                # 1 -> 2: the model was used a while ago AND is in use now —
                # recurring traffic deserves redundancy.
                age = last_used - prev
                if prev and cfg.second_copy_min_age_ms <= age <= cfg.second_copy_max_age_ms:
                    self._add_copy(model_id, mr)
                    continue
            # Local per-copy rate vs the per-copy threshold (applies at any
            # copy count — a saturated single copy must scale too): each
            # instance sees only its own copy's traffic, so if the copy it
            # serves is at threshold, the model needs another copy
            # (reference rateTrackingTask, ModelMesh.java:5762). In latency
            # mode (runtime declared a per-model concurrency limit) the
            # threshold is dynamic: 90% of this copy's measured bandwidth
            # (reference :719-732).
            rpm = inst.model_rpm(model_id)
            threshold = cfg.scale_up_rpm
            bandwidth = ce.bandwidth_rpm()
            if bandwidth > 0:
                threshold = max(1, int(bandwidth * 0.9))
            if rpm >= threshold:
                self._add_copy(model_id, mr)

    def _add_copy(self, model_id: str, mr: ModelRecord) -> None:
        try:
            self.instance.ensure_loaded(
                model_id, sync=False, exclude=set(mr.all_placements)
            )
            log.info("scale-up: requested extra copy of %s", model_id)
        except Exception as e:  # noqa: BLE001 — advisory
            log.debug("scale-up of %s skipped: %s", model_id, e)

    # -- janitor: reconcile + scale DOWN ----------------------------------

    def _janitor_tick(self) -> None:
        inst = self.instance
        now = now_ms()
        # (a) registry -> local: drop local copies of unregistered models;
        #     repair records that lost our placement entry.
        for model_id in inst.cache.keys():
            ce = inst.cache.get_quietly(model_id)
            if ce is None:
                continue
            mr = inst.registry.get(model_id)
            if mr is None:
                log.info("janitor: %s unregistered; removing local copy", model_id)
                inst._remove_local(model_id)
                continue
            changed = False
            if (
                ce.state is EntryState.ACTIVE
                and inst.instance_id not in mr.instance_ids
            ):
                mr.promote_loaded(inst.instance_id, ce.load_completed_ms or now)
                changed = True
            if mr.expire_load_failures(now):
                changed = True
            # Lazy lastUsed persistence (reference ModelRecord.java:96-105).
            local_last_used = inst.cache.last_used(model_id) or 0
            if mr.should_persist_last_used(local_last_used):
                mr.last_used = local_last_used
                changed = True
            if changed:
                try:
                    inst.registry.conditional_set(model_id, mr)
                except CasFailed:
                    pass
        # (b) local -> registry: records claiming we hold a copy we don't.
        for model_id, mr in inst.registry.items():
            if mr.placed_on(inst.instance_id) and model_id not in inst.cache:
                def fix(cur):
                    if cur is None:
                        return None
                    cur.remove_instance(inst.instance_id)
                    return cur
                try:
                    inst.registry.update_or_create(model_id, fix)
                except CasFailed:
                    pass
        # (c) scale-down when the cluster is nearly full — LEGACY scaling
        # authority only: in burn mode the autoscale controller owns
        # scale-down (demote-to-host); in off mode nothing scales.
        if self.config.autoscale_mode == "legacy":
            self._maybe_scale_down()

    def _cluster_fullness(self, model_type: Optional[str] = None) -> float:
        return cluster_fullness(self.instance, model_type)

    def _maybe_scale_down(self) -> None:
        inst = self.instance
        cfg = self.config
        # Memoize per-type subset fullness for this pass.
        fullness: dict[Optional[str], float] = {}

        def subset_full(model_type: Optional[str]) -> bool:
            if inst.constraints is None:
                model_type = None
            f = fullness.get(model_type)
            if f is None:
                f = fullness[model_type] = self._cluster_fullness(model_type)
            return f >= CLUSTER_FULL_FRACTION

        now = now_ms()
        for model_id in inst.cache.keys():
            mr = inst.registry_view.get(model_id)
            # Shared eligibility (surplus_shed_eligible): >= 2 READY
            # copies, ours past the anti-thrash minimum age, local rate
            # under 2/3 of the per-copy threshold.
            if not surplus_shed_eligible(
                inst, model_id, mr, now,
                SURPLUS_COPY_MIN_AGE_MS, cfg.scale_up_rpm,
            ):
                continue
            age = now - mr.instance_ids[inst.instance_id]
            # Fullness gates ordinary scale-down; a surplus copy past the
            # 10 h cap sheds regardless (reference :257).
            if not subset_full(mr.model_type) and age < SURPLUS_COPY_MAX_AGE_MS:
                continue
            if elected_shedder(mr) == inst.instance_id:
                log.info("scale-down: dropping surplus copy of %s", model_id)
                if inst._remove_local(model_id):
                    # mm_scale_down_count counts surplus copies removed
                    # by WHICHEVER scaling authority is active (the
                    # burn-mode demote path increments it too);
                    # mm_autoscale_down_count is the controller's
                    # decision counter on top.
                    from modelmesh_tpu.observability.metrics import (
                        Metric as _MX,
                    )

                    inst.metrics.inc(_MX.SCALE_DOWN_COUNT,
                                     model_id=model_id)

    # -- reaper (leader only) ---------------------------------------------

    def _reaper_tick(self) -> None:
        inst = self.instance
        if not inst.is_leader:
            self._missing_since.clear()
            return
        # One registry scan + one view snapshot feed the plan refresh, the
        # gauges, the prune pass, and proactive loading below — items() is
        # a full KV range read, unaffordable to repeat per concern at 100k
        # models.
        views = list(inst.instances_view.items())
        records = list(inst.registry.items())
        live = {iid for iid, _ in views}
        # When the instance runs the JAX global strategy, the reaper is its
        # refresh cadence: solve one global plan from current state; the
        # routing layer serves decisions from it until the next pass.
        refresh = getattr(inst.strategy, "refresh", None)
        if refresh is not None:
            try:
                plan = refresh(records, views, inst.model_rpm)
                # Publish so EVERY instance's PlanFollower (instance.py)
                # serves this solve, not just the leader's own strategy.
                from modelmesh_tpu.placement.plan_sync import publish_plan

                publish_plan(inst.store, inst.config.kv_prefix, plan)
            except Exception:  # noqa: BLE001 — plan is advisory
                log.exception("global plan refresh/publish failed")
        now = now_ms()
        # Leader-published fleet gauges (reference cluster-scope metrics).
        from modelmesh_tpu.observability.metrics import Metric as _MX

        inst.metrics.set_gauge(_MX.CLUSTER_INSTANCES, len(views))
        inst.metrics.set_gauge(_MX.CLUSTER_MODELS, len(records))
        inst.metrics.set_gauge(
            _MX.CLUSTER_COPIES,
            sum(len(mr.instance_ids) for _, mr in records),
        )
        inst.metrics.set_gauge(
            _MX.CLUSTER_CAPACITY_UNITS,
            sum(r.capacity_units for _, r in views),
        )
        inst.metrics.set_gauge(
            _MX.CLUSTER_USED_UNITS, sum(r.used_units for _, r in views)
        )
        # Track how long each referenced instance has been missing.
        referenced: set[str] = set()
        for _, mr in records:
            referenced |= mr.all_placements
            # Host-tier claims (transfer/ demotions) are peer-fetch
            # sources, not servable placements — but a dead holder's
            # claim must be pruned the same way or receivers keep
            # dialing a ghost before falling back.
            referenced |= set(mr.host_instances)
        for iid in referenced - live:
            self._missing_since.setdefault(iid, now)
        for iid in list(self._missing_since):
            if iid in live:
                del self._missing_since[iid]
        gone = {
            iid for iid, since in self._missing_since.items()
            if now - since >= self.config.assume_gone_ms
        }
        # (a) prune placements on gone instances + stale loading claims.
        # SUPPRESSED in KV-migration read-only mode: holders registered in
        # the OTHER kv store are invisible here and would all look "gone"
        # (reference skips pruning under readOnlyMode, ModelMesh.java:6543).
        if inst.config.read_only:
            self._proactive_load(records, visible_only=live)
            return
        for model_id, mr in records:
            stale_claims = [
                iid for iid, ts in mr.loading_instances.items()
                if iid in gone or (
                    iid not in live and now - ts > STALE_LOADING_CLAIM_MS
                )
            ]
            dead = [iid for iid in mr.instance_ids if iid in gone]
            dead_hosts = [iid for iid in mr.host_instances if iid in gone]
            if not stale_claims and not dead and not dead_hosts:
                continue

            def prune(cur):
                if cur is None:
                    return None
                for iid in stale_claims + dead + dead_hosts:
                    cur.remove_instance(iid)
                return cur

            try:
                inst.registry.update_or_create(model_id, prune)
                log.info(
                    "reaper: pruned %s from %s",
                    stale_claims + dead + dead_hosts, model_id,
                )
            except CasFailed:
                pass
        # (b) proactive loading: restore the most-recently-used unloaded
        #     models into free cluster space, above a reserve.
        self._proactive_load(records)

    def _proactive_load(self, records, visible_only=None) -> None:
        """``visible_only``: in KV-migration read-only mode, placements on
        instances outside OUR instance registry belong to the other store's
        fleet — for load decisions they count as not loaded here (reference
        filters insts to instanceInfo under readOnlyMode,
        ModelMesh.java:6547-6551)."""
        inst = self.instance
        views = inst.instances_view.items()
        cap = sum(r.capacity_units for _, r in views) or 1
        used = sum(r.used_units for _, r in views)
        budget_units = int((cap - used) - cap * PROACTIVE_RESERVE_FRACTION) // 2
        if budget_units <= 0:
            return

        def visible(ids):
            if visible_only is None:
                return ids
            return [i for i in ids if i in visible_only]

        # loading_instances gets the same filter: an other-store (or stale)
        # claim must not block the local load for the whole migration —
        # read-only mode suppresses the pruning that would clear it.
        unloaded = [
            (mr.last_used, model_id, mr)
            for model_id, mr in records
            if not visible(mr.instance_ids) and not visible(mr.loading_instances)
            and not mr.load_exhausted()
        ]
        unloaded.sort(reverse=True, key=lambda t: t[0])
        default_units = 128
        loads = 0
        for last_used, model_id, mr in unloaded:
            if loads >= 8:  # bounded per pass
                break
            cost = mr.size_units or default_units
            if cost > budget_units:
                continue  # next candidate might be smaller
            try:
                inst.ensure_loaded(model_id, last_used_ms=last_used, sync=False)
                budget_units -= cost
                loads += 1
                log.info("reaper: proactive load of %s (%du)", model_id, cost)
            except Exception as e:  # noqa: BLE001 — advisory
                log.debug("proactive load of %s skipped: %s", model_id, e)
                break
