"""gRPC surfaces of a serving instance.

Three handlers on one server (reference: ModelMeshApi.java single server
with management service + arbitrary-method fallback; internal thrift service
replaced by the MeshInternal gRPC service):

- mmtpu.api.ModelMesh        — management (register/status/vmodels)
- mmtpu.internal.MeshInternal — instance-to-instance forwarding
- raw fallback handler        — ANY other unary method is inference: model id
  from mm-model-id / mm-vmodel-id metadata, payload passed through opaque
  (zero-copy equivalent of ModelMeshApi.startCall :649-819)

Also provides the client side: ``grpc_peer_call`` used as the instance's
peer transport, with mesh errors mapped onto gRPC status + a detail header.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time as _time
from concurrent import futures
from typing import Optional

import grpc

from modelmesh_tpu.utils.grpcopts import message_size_options
from modelmesh_tpu.observability.metrics import Metric as MX
from modelmesh_tpu.observability.payloads import Payload
from modelmesh_tpu.observability.tracing import (
    SPAN_HEADER,
    TRACE_HEADER,
    Tracer,
    incoming_parent_span,
    incoming_trace_id,
)

from modelmesh_tpu.proto import mesh_api_pb2 as apb
from modelmesh_tpu.proto import mesh_internal_pb2 as ipb
from modelmesh_tpu.proto import mesh_transfer_pb2 as tpb
from modelmesh_tpu.runtime import grpc_defs
from modelmesh_tpu.runtime.spi import ModelInfo
from modelmesh_tpu.serving.errors import (
    RequestCancelledError,
    ApplierError,
    ModelLoadException,
    ModelNotFoundError,
    ModelNotHereError,
    NoCapacityError,
    OverloadShedError,
    ReadOnlyModeError,
    ServiceUnavailableError,
)
from modelmesh_tpu.serving.instance import (
    InvokeResult,
    ModelMeshInstance,
    RoutingContext,
)
from modelmesh_tpu.serving.route_cache import LoadFeedback

log = logging.getLogger(__name__)

ERROR_HEADER = "mm-error"
_ERR_NOT_HERE = "model-not-here"
_ERR_NO_CAPACITY = "no-capacity"
_ERR_LOAD_FAILED = "load-failed"
# Piggybacked load feedback on Forward responses (the responder's
# in-flight count, batch-queue depth, drain flag — route_cache.
# LoadFeedback wire form). A trailer, not a message field: zero bytes
# on requests, and older peers simply don't send it.
LOAD_HEADER = "mm-load"
# Typed overload marker on admission sheds, beside RESOURCE_EXHAUSTED:
# lets clients (and tests) tell a deliberate edge shed from a fleet
# genuinely out of placement capacity.
OVERLOAD_HEADER = "mm-overload"

_STATUS_MAP = {
    "NOT_FOUND": apb.NOT_FOUND,
    "NOT_LOADED": apb.NOT_LOADED,
    "LOADING": apb.LOADING,
    "LOADED": apb.LOADED,
    "LOADING_FAILED": apb.LOADING_FAILED,
}


def _ctx_to_proto(ctx: RoutingContext) -> ipb.RoutingContext:
    # Sets are serialized in iteration order: the receiver rebuilds sets
    # (order-insensitive), and sorting three sets per forward hop was pure
    # hot-path overhead.
    return ipb.RoutingContext(
        hop=ctx.hop,
        exclude_serve=list(ctx.exclude_serve),
        exclude_load=list(ctx.exclude_load),
        visited=list(ctx.visited),
        dest_instance=ctx.dest_instance,
        chain_load_count=ctx.chain_load_count,
        known_size_bytes=ctx.known_size_bytes,
        last_used_ms=ctx.last_used_ms,
    )


def _ctx_from_proto(p: ipb.RoutingContext) -> RoutingContext:
    return RoutingContext(
        hop=p.hop,
        exclude_serve=set(p.exclude_serve),
        exclude_load=set(p.exclude_load),
        visited=set(p.visited),
        dest_instance=p.dest_instance,
        chain_load_count=p.chain_load_count,
        known_size_bytes=p.known_size_bytes,
        last_used_ms=p.last_used_ms,
    )


class MeshApiServicer:
    """mmtpu.api.ModelMesh implementation."""

    def __init__(self, instance: ModelMeshInstance, vmodels=None):
        self.instance = instance
        self.vmodels = vmodels  # VModelManager, optional

    def _status_info(self, model_id: str) -> apb.ModelStatusInfo:
        status, mr = self.instance.get_status(model_id)
        errors = []
        if mr is not None:
            errors = [msg for _, msg in mr.load_failures.values()]
        return apb.ModelStatusInfo(
            status=_STATUS_MAP.get(status, apb.UNKNOWN),
            errors=errors,
            model_id=model_id,
            copy_count=mr.copy_count if mr else 0,
        )

    @staticmethod
    def _require_id(id_: str, context, what: str = "model_id") -> None:
        if not id_ or "/" in id_:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"{what} must be non-empty and must not contain '/'",
            )

    def RegisterModel(self, request, context):
        self._require_id(request.model_id, context)
        info = ModelInfo(
            model_type=request.info.model_type,
            model_path=request.info.model_path,
            model_key=request.info.model_key,
        )
        try:
            self.instance.register_model(
                request.model_id, info,
                load_now=request.load_now, sync=request.sync,
            )
        except ReadOnlyModeError as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        except Exception as e:  # noqa: BLE001 — map to status
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        return self._status_info(request.model_id)

    def UnregisterModel(self, request, context):
        self._require_id(request.model_id, context)
        try:
            self.instance.unregister_model(request.model_id)
        except ReadOnlyModeError as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        return apb.UnregisterModelResponse()

    def GetModelStatus(self, request, context):
        # Reserved diagnostic id: dump full cache + cluster state (the
        # reference's ***LOGCACHE***/***GETSTATE*** facility).
        from modelmesh_tpu.serving.bootstrap import (
            STATE_DUMP_ALIASES,
            STATE_DUMP_ID,
            debug_dump,
        )

        if request.model_id in STATE_DUMP_ALIASES:
            import json as _json

            return apb.ModelStatusInfo(
                status=apb.UNKNOWN,
                model_id=STATE_DUMP_ID,
                errors=[_json.dumps(debug_dump(self.instance))],
            )
        from modelmesh_tpu.observability.flightrec import FLIGHTREC_DUMP_ID
        from modelmesh_tpu.observability.tracing import TRACE_DUMP_ID

        if request.model_id == TRACE_DUMP_ID:
            import json as _json

            tracer = self.instance.tracer
            return apb.ModelStatusInfo(
                status=apb.UNKNOWN,
                model_id=TRACE_DUMP_ID,
                errors=[_json.dumps(tracer.recent(tracer.capacity))],
            )
        if request.model_id == FLIGHTREC_DUMP_ID:
            import json as _json

            return apb.ModelStatusInfo(
                status=apb.UNKNOWN,
                model_id=FLIGHTREC_DUMP_ID,
                errors=[_json.dumps(self.instance.flightrec.dump())],
            )
        self._require_id(request.model_id, context)
        return self._status_info(request.model_id)

    def EnsureLoaded(self, request, context):
        self._require_id(request.model_id, context)
        # Internal ensure ops ride invocation metadata for trace context
        # (the request proto carries no headers): an upstream-traced
        # ensure keeps its tree; untraced ones sample like any root.
        md = list(context.invocation_metadata())
        try:
            with self.instance.tracer.trace(
                incoming_trace_id(md), request.model_id, "EnsureLoaded",
                parent_span=incoming_parent_span(md),
            ):
                self.instance.ensure_loaded(
                    request.model_id,
                    last_used_ms=request.last_used_ms,
                    sync=request.sync,
                )
        except ModelNotFoundError:
            return apb.ModelStatusInfo(
                status=apb.NOT_FOUND, model_id=request.model_id
            )
        except (ModelLoadException, NoCapacityError) as e:
            return apb.ModelStatusInfo(
                status=apb.LOADING_FAILED, model_id=request.model_id,
                errors=[str(e)],
            )
        return self._status_info(request.model_id)

    # -- vmodels (delegated; UNIMPLEMENTED until manager attached) ---------

    def SetVModel(self, request, context):
        if self.vmodels is None:
            context.abort(grpc.StatusCode.UNIMPLEMENTED, "vmodels not enabled")
        return self.vmodels.set_vmodel(request, context, self._status_info)

    def DeleteVModel(self, request, context):
        if self.vmodels is None:
            context.abort(grpc.StatusCode.UNIMPLEMENTED, "vmodels not enabled")
        return self.vmodels.delete_vmodel(request, context)

    def GetVModelStatus(self, request, context):
        if self.vmodels is None:
            context.abort(grpc.StatusCode.UNIMPLEMENTED, "vmodels not enabled")
        return self.vmodels.get_vmodel_status(request, context, self._status_info)


class MeshInternalServicer:
    """mmtpu.internal.MeshInternal implementation."""

    def __init__(self, instance: ModelMeshInstance):
        self.instance = instance

    def Forward(self, request, context):
        ctx = _ctx_from_proto(request.ctx)
        # Transitive cancellation: when the previous hop cancels its
        # Forward RPC (because ITS client disconnected), this context
        # terminates and the event interrupts local work here too.
        ctx.cancel_event = threading.Event()
        context.add_callback(ctx.cancel_event.set)
        headers = list(request.headers.items())
        incoming_tid = incoming_trace_id(headers)
        incoming_parent = incoming_parent_span(headers)
        if incoming_tid:
            # Trace context never rides the opaque header list downstream:
            # outgoing_headers re-attaches it fresh (with THIS hop's span
            # as the parent) on every outbound hop — a second forward of
            # this request must not inherit hop-1's parent link.
            headers = [
                (k, v) for k, v in headers
                if k != TRACE_HEADER and k != SPAN_HEADER
            ]
        try:
            with self.instance.tracer.trace(
                incoming_tid, request.model_id, request.method_name,
                parent_span=incoming_parent,
            ):
                result = self.instance.invoke_model(
                    request.model_id,
                    request.method_name or None,
                    request.payload,
                    headers,
                    ctx,
                )
        except ModelNotHereError:
            context.set_trailing_metadata(((ERROR_HEADER, _ERR_NOT_HERE),))
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"model {request.model_id} not here",
            )
        except NoCapacityError as e:
            context.set_trailing_metadata(((ERROR_HEADER, _ERR_NO_CAPACITY),))
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except ModelNotFoundError:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"model {request.model_id}"
            )
        except ServiceUnavailableError as e:
            # Propagates as UNAVAILABLE so the previous hop excludes this
            # instance and retries elsewhere (same mapping as the external
            # fallback surface).
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        except ModelLoadException as e:
            # Typed trailer so the forwarding hop can catch
            # ModelLoadException and re-route (it forwarded to a LOADING
            # copy whose load died) instead of failing the request.
            context.set_trailing_metadata(((ERROR_HEADER, _ERR_LOAD_FAILED),))
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        except ApplierError as e:
            context.abort(grpc.StatusCode.UNKNOWN, str(e))
        except RequestCancelledError:
            context.abort(grpc.StatusCode.CANCELLED, "upstream cancelled")
        # Piggybacked load feedback: OUR current load rides every
        # successful Forward response as a trailer, feeding the
        # sender's LoadView (d-choices routing). Best-effort — a
        # context that can't take trailers must not fail the response.
        try:
            context.set_trailing_metadata(
                ((LOAD_HEADER, self.instance.load_feedback().encode()),)
            )
        except Exception:  # noqa: BLE001 — advisory signal only
            pass
        return ipb.ForwardResponse(
            payload=result.payload,
            served_by=result.served_by,
            model_status=_STATUS_MAP.get(result.status, apb.UNKNOWN),
        )

    def FetchWeights(self, request, context):
        """Weight-transfer fetch (live scale-up): one chunk of this
        instance's snapshot of the model. Stateless per call; failures
        the receiver should treat as 'try another source' come back as a
        NOT_AVAILABLE status rather than an RPC error. Trace context
        rides invocation metadata (the fetch client attaches it), so a
        traced receiver's stream shows the sender's chunk serving in the
        same tree — recorded ONCE per transfer (chunk 0): a
        record-per-chunk would evict the sender's whole trace ring on a
        single multi-GB stream."""
        md = list(context.invocation_metadata())
        tid = incoming_trace_id(md) if request.chunk_index == 0 else ""
        if tid:
            with self.instance.tracer.trace(
                tid, request.model_id, "FetchWeights",
                parent_span=incoming_parent_span(md),
            ), self.instance.tracer.span(
                "serve-chunk", chunk=request.chunk_index,
            ):
                reply = self.instance.handle_weight_fetch(
                    request.model_id, request.chunk_index,
                    request.fingerprint,
                )
        else:
            reply = self.instance.handle_weight_fetch(
                request.model_id, request.chunk_index, request.fingerprint
            )
        return tpb.FetchWeightsResponse(
            status=reply.status,
            payload=reply.payload,
            seq=reply.seq,
            layer=reply.layer,
            last=reply.last,
            total_chunks=reply.total_chunks,
            total_bytes=reply.total_bytes,
            total_layers=reply.total_layers,
            fingerprint=reply.fingerprint,
        )


class InferenceFallback:
    """Arbitrary-method inference entry: metadata id -> invoke_model.

    Also the request-metrics and payload-observation point (reference:
    ModelMeshApi request metrics + PayloadProcessor hooks :778-818).
    """

    # Parallelism of multi-model fan-out (reference MM_MULTI_PARALLELISM=4,
    # applyParallelMultiModel ModelMeshApi.java:947-1058).
    MULTI_PARALLELISM = 4

    def __init__(self, instance: ModelMeshInstance, vmodels=None,
                 payload_processor=None, dataplane=None, log_headers=None):
        from modelmesh_tpu.observability.logctx import HeaderLogContext

        self.instance = instance
        self.vmodels = vmodels
        self.payload_processor = payload_processor
        self.dataplane = dataplane  # DataplaneApiConfig, optional
        # Header -> log-context mapping (LogRequestHeaders.java:17-35);
        # parsed from MM_LOG_REQUEST_HEADERS unless injected.
        self.log_headers = log_headers or HeaderLogContext.from_env()
        self._req_seq = itertools.count(1)
        self._multi_pool = futures.ThreadPoolExecutor(
            max_workers=self.MULTI_PARALLELISM, thread_name_prefix="multi"
        )

    def _observe_payload(self, req_id, model_id, method, kind, data, status):
        proc = self.payload_processor
        if proc is None:
            return
        try:
            proc.process(Payload(
                request_id=req_id, model_id=model_id, method=method,
                kind=kind, data=data, status=status,
            ))
        except Exception:  # noqa: BLE001 — observer must not break serving
            log.exception("payload processor failed")

    def __call__(self, method: str, request: bytes, context) -> bytes:
        metrics = self.instance.metrics
        md = dict(context.invocation_metadata())
        if self.dataplane is not None and not self.dataplane.is_allowed(method):
            context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                f"method {method} not permitted by dataplane config",
            )
        model_id = md.get(grpc_defs.MODEL_ID_HEADER, "")
        vmodel_id = md.get(grpc_defs.VMODEL_ID_HEADER, "")
        if not model_id and not vmodel_id and self.dataplane is not None:
            # In-body id extraction (ProtoSplicer path, reference
            # ModelMeshApi.java:689).
            path = self.dataplane.extraction_path(method)
            if path:
                from modelmesh_tpu.native import proto_splicer

                try:
                    extracted = proto_splicer.extract_id(request, path)
                except ValueError:
                    extracted = None
                if extracted:
                    cfg = self.dataplane.rpc(method)
                    if cfg is not None and cfg.vmodel:
                        vmodel_id = extracted
                    else:
                        model_id = extracted
        if vmodel_id and not model_id:
            if self.vmodels is None:
                context.abort(
                    grpc.StatusCode.UNIMPLEMENTED, "vmodels not enabled"
                )
            model_id = self.vmodels.resolve(vmodel_id, context)
        if not model_id:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"missing {grpc_defs.MODEL_ID_HEADER} metadata",
            )
        # Single pass over the metadata: strip transport/id entries into
        # the forwardable header list and capture the trace id on the way
        # (previously: a filtering comprehension here plus a second
        # identical one in the multi-model path plus separate md lookups).
        headers = []
        trace_id = ""
        parent_span = ""
        for k, v in md.items():
            if k.startswith("grpc-") or not isinstance(v, str):
                continue
            if k == grpc_defs.MODEL_ID_HEADER or k == grpc_defs.VMODEL_ID_HEADER:
                continue
            if k == TRACE_HEADER:
                # Captured, NOT forwarded in the opaque list: every
                # outbound hop re-attaches the live trace context with
                # its own span as the parent (outgoing_headers).
                trace_id = v
                continue
            if k == SPAN_HEADER:
                parent_span = v
                continue
            headers.append((k, v))
        if "," in model_id:
            return self._multi_model(
                method, request, context, model_id, headers, trace_id
            )
        # Payload observation (and the req-id it needs) only exists when a
        # processor is configured — the common unconfigured case skips the
        # id formatting and the observer calls entirely.
        proc = self.payload_processor
        req_id = ""
        if proc is not None:
            req_id = f"{self.instance.instance_id}-{next(self._req_seq)}"
            self._observe_payload(
                req_id, model_id, method, "request", request, "OK"
            )
        metrics.inc(MX.API_REQUEST_COUNT, model_id=model_id)
        # Client-disconnect propagation (ModelMeshApi.java:709-729): gRPC
        # fires rpc-termination callbacks on cancel; the event interrupts
        # slot waits, runtime calls, and peer forwards downstream. (It also
        # fires on normal completion — harmless, the request is done.)
        cancel_event = threading.Event()
        context.add_callback(cancel_event.set)
        t0 = _time.perf_counter()  #: wall-clock: perf_counter latency metric (API request time)
        metrics.observe(MX.REQUEST_BYTES, len(request), model_id)
        try:
            with self.log_headers.bind(md.items()), self.instance.tracer.trace(
                trace_id, model_id, method, parent_span=parent_span,
            ):
                result = self.instance.invoke_model(
                    model_id, method, request, headers,
                    RoutingContext(cancel_event=cancel_event),
                )
            metrics.observe(MX.RESPONSE_BYTES, len(result.payload), model_id)
            metrics.observe(
                MX.API_REQUEST_TIME, (_time.perf_counter() - t0) * 1e3,  #: wall-clock: perf_counter latency metric
                model_id=model_id,
            )
            if proc is not None:
                self._observe_payload(
                    req_id, model_id, method, "response", result.payload, "OK"
                )
            # Serving-identity trailers: which worker the connection
            # entered (front-door balancing debug) and which instance
            # actually served — operators and tests read these to see
            # kernel connection spread vs internal forwards.
            try:
                context.set_trailing_metadata((
                    ("mm-entry-instance", self.instance.instance_id),
                    ("mm-served-by", result.served_by or ""),
                ))
            except Exception:  # noqa: BLE001 — debug info, never fatal
                pass
            return result.payload
        except RequestCancelledError:
            # The client is gone; nothing to send. Abort with CANCELLED so
            # the server-side bookkeeping closes out cleanly.
            metrics.inc(MX.API_REQUEST_FAILED, model_id=model_id)
            metrics.inc(MX.CANCEL_COUNT, model_id=model_id)
            context.abort(grpc.StatusCode.CANCELLED, "client cancelled")
        except ModelNotFoundError:
            metrics.inc(MX.API_REQUEST_FAILED, model_id=model_id)
            self._observe_payload(
                req_id, model_id, method, "response", b"", "NOT_FOUND"
            )
            context.abort(grpc.StatusCode.NOT_FOUND, f"model {model_id}")
        except OverloadShedError as e:
            # Deliberate edge shed (serving/admission.py): typed via the
            # mm-overload trailer so clients back off instead of
            # retrying into the same overload.
            metrics.inc(MX.API_REQUEST_FAILED, model_id=model_id)
            try:
                context.set_trailing_metadata(
                    ((OVERLOAD_HEADER, e.model_class),)
                )
            except Exception:  # noqa: BLE001 — marker only
                pass
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except NoCapacityError as e:
            metrics.inc(MX.API_REQUEST_FAILED, model_id=model_id)
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        except (ModelLoadException, ModelNotHereError) as e:
            metrics.inc(MX.API_REQUEST_FAILED, model_id=model_id)
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        except ApplierError as e:
            metrics.inc(MX.API_REQUEST_FAILED, model_id=model_id)
            code = getattr(grpc.StatusCode, e.grpc_code, grpc.StatusCode.UNKNOWN)
            context.abort(code, str(e))
        except ServiceUnavailableError as e:
            metrics.inc(MX.API_REQUEST_FAILED, model_id=model_id)
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))

    def _multi_model(
        self, method, request, context, model_ids, headers, trace_id
    ) -> bytes:
        """Fan the same request out to several models in parallel; responses
        are concatenated as length-prefixed frames (4-byte big-endian per
        response, in the order the ids were given). First failure aborts the
        whole call, mirroring the reference's all-or-nothing semantics.

        ``headers`` already has the routing ids stripped (the caller's
        single metadata pass): each per-model call gets its own id header
        from the runtime client; the original comma-list must not leak
        through (duplicate metadata keys would shadow it)."""
        metrics = self.instance.metrics
        ids = [m.strip() for m in model_ids.split(",") if m.strip()]
        req_id = f"{self.instance.instance_id}-{next(self._req_seq)}"
        metrics.inc(MX.API_REQUEST_COUNT, model_id=model_ids)
        metrics.inc(MX.MULTI_MODEL_COUNT, model_id=model_ids)
        self._observe_payload(req_id, model_ids, method, "request", request, "OK")
        cancel_event = threading.Event()
        context.add_callback(cancel_event.set)
        t0 = _time.perf_counter()  #: wall-clock: perf_counter latency metric (API request time)
        # Adopted ids always trace; a fan-out without one is sampled like
        # any minted root (maybe_mint, not uuid4: no per-request entropy
        # I/O, and sampled-out fan-outs skip tracing entirely instead of
        # letting each member mint a fragment).
        trace_id = trace_id or self.instance.tracer.maybe_mint()

        def run_member(mid):
            # Pool threads don't inherit the handler's trace contextvar:
            # each member records under the SHARED trace id so the fan-out
            # appears as one trace across instances.
            if not trace_id:
                return self.instance.invoke_model(
                    mid, method, request, headers,
                    RoutingContext(cancel_event=cancel_event),
                )
            with self.instance.tracer.trace(trace_id, mid, method):
                return self.instance.invoke_model(
                    mid, method, request, headers,
                    RoutingContext(cancel_event=cancel_event),
                )

        futs = [self._multi_pool.submit(run_member, mid) for mid in ids]
        out = bytearray()
        # Per-model budget tied to the LOAD timeout (a fan-out member may
        # legitimately cold-load), not a flat wall unrelated to it — the
        # round-1 verdict's 60 s INTERNAL failure mode.
        per_model_s = max(60.0, self.instance.load_timeout_s * 1.5 + 30.0)
        try:
            for mid, fut in zip(ids, futs):
                payload = fut.result(timeout=per_model_s).payload
                out += len(payload).to_bytes(4, "big") + payload
        except Exception as e:  # noqa: BLE001 — first failure aborts the call
            for f in futs:
                f.cancel()
            cancel_event.set()  # release in-flight members' slots
            metrics.inc(MX.API_REQUEST_FAILED, model_id=model_ids)
            code, label = {
                ModelNotFoundError: (grpc.StatusCode.NOT_FOUND, "NOT_FOUND"),
                NoCapacityError: (
                    grpc.StatusCode.RESOURCE_EXHAUSTED, "NO_CAPACITY"
                ),
                OverloadShedError: (
                    grpc.StatusCode.RESOURCE_EXHAUSTED, "OVERLOAD"
                ),
                ServiceUnavailableError: (
                    grpc.StatusCode.UNAVAILABLE, "UNAVAILABLE"
                ),
                RequestCancelledError: (
                    grpc.StatusCode.CANCELLED, "CANCELLED"
                ),
            }.get(type(e), (grpc.StatusCode.INTERNAL, "INTERNAL"))
            self._observe_payload(
                req_id, model_ids, method, "response", b"", label
            )
            context.abort(code, f"multi-model {mid}: {e}")
        metrics.observe(
            MX.API_REQUEST_TIME, (_time.perf_counter() - t0) * 1e3,  #: wall-clock: perf_counter latency metric
            model_id=model_ids,
        )
        self._observe_payload(
            req_id, model_ids, method, "response", bytes(out), "OK"
        )
        return bytes(out)


class MeshServer:
    """One gRPC server exposing all three surfaces for an instance."""

    def __init__(
        self,
        instance: ModelMeshInstance,
        port: int = 0,
        vmodels=None,
        max_workers: int = 24,
        bind_host: str = "0.0.0.0",
        advertise_host: str = "127.0.0.1",
        payload_processor=None,
        dataplane=None,
        tls=None,
        frontdoor_port: Optional[int] = None,
    ):
        """``bind_host`` is the listen address (0.0.0.0 for cross-host
        deployments); ``advertise_host`` is what peers dial — production
        config passes the pod IP / hostname. ``tls`` (serving.tls.TlsConfig)
        secures all three surfaces; with require_client_auth peers must
        present certs signed by the configured CA. ``frontdoor_port``
        additionally binds the external surfaces on a SHARED
        SO_REUSEPORT listener so several worker processes on one host
        can serve one public port (multi-core scaling; must be a fixed
        port, not 0)."""
        if frontdoor_port is not None and frontdoor_port <= 0:
            # Ephemeral would give every worker a DIFFERENT port,
            # silently defeating the shared-listener design. Checked
            # before any server exists so failure leaks nothing.
            raise ValueError("frontdoor_port must be a fixed positive port")
        self.instance = instance
        self._advertise_host = advertise_host
        self.tls = tls
        # SO_REUSEPORT explicitly OFF here: the per-instance port must be
        # unique (peer forwards are addressed to exactly this process) —
        # gRPC's Linux default of reuseport=1 would let a copy-pasted
        # duplicate --port bind silently and split forwards between
        # workers. The shared front door below opts back in.
        self.server = grpc.server(
            futures.ThreadPoolExecutor(max_workers),
            options=message_size_options() + [("grpc.so_reuseport", 0)],
        )
        api_servicer = MeshApiServicer(instance, vmodels)
        fallback = InferenceFallback(
            instance, vmodels, payload_processor, dataplane
        )
        grpc_defs.add_servicer(
            self.server, api_servicer,
            grpc_defs.API_SERVICE, grpc_defs.API_METHODS,
        )
        grpc_defs.add_servicer(
            self.server, MeshInternalServicer(instance),
            grpc_defs.INTERNAL_SERVICE, grpc_defs.INTERNAL_METHODS,
        )
        self.server.add_generic_rpc_handlers(
            (grpc_defs.RawFallbackHandler(fallback),)
        )
        addr = f"{bind_host}:{port}"
        if tls is not None:
            self.port = self.server.add_secure_port(
                addr, tls.server_credentials()
            )
        else:
            self.port = self.server.add_insecure_port(addr)
        self.server.start()

        # Optional SHARED front door (multi-core hosts): N worker
        # processes on one host bind the SAME public port via
        # SO_REUSEPORT; the kernel balances incoming connections across
        # them and cache misses ride the normal internal Forward hop to
        # the owning worker. Only the EXTERNAL surfaces live here — the
        # per-instance port above stays unique so peer forwards reach
        # exactly this process. This is the framework's answer to the
        # Python GIL: scale the data plane with processes, not threads
        # (the reference scales one JVM with threads,
        # ModelMeshApi.java:649-819; a CPython port of that design would
        # serialize on the interpreter lock).
        self.frontdoor = None
        self.frontdoor_port = None
        if frontdoor_port is not None:
            # Same servicer/fallback OBJECTS as the internal listener:
            # one multi-model pool, one request-id sequence — two copies
            # would emit payload records with colliding req_ids.
            self.frontdoor = grpc.server(
                futures.ThreadPoolExecutor(max_workers),
                options=message_size_options() + [("grpc.so_reuseport", 1)],
            )
            grpc_defs.add_servicer(
                self.frontdoor, api_servicer,
                grpc_defs.API_SERVICE, grpc_defs.API_METHODS,
            )
            self.frontdoor.add_generic_rpc_handlers(
                (grpc_defs.RawFallbackHandler(fallback),)
            )
            fd_addr = f"{bind_host}:{frontdoor_port}"
            if tls is not None:
                self.frontdoor_port = self.frontdoor.add_secure_port(
                    fd_addr, tls.server_credentials()
                )
            else:
                self.frontdoor_port = self.frontdoor.add_insecure_port(fd_addr)
            if not self.frontdoor_port:
                # The internal server is already live — release it before
                # surfacing the failure or the caller has no handle left.
                self.server.stop(0)
                raise RuntimeError(
                    f"could not bind shared front door on {fd_addr}"
                )
            self.frontdoor.start()

    @property
    def endpoint(self) -> str:
        return f"{self._advertise_host}:{self.port}"

    def stop(self, grace: float = 0.5) -> None:
        if self.frontdoor is not None:
            self.frontdoor.stop(grace)
        self.server.stop(grace)


# -- client side --------------------------------------------------------------

class PeerChannels:
    """Channel cache for instance-to-instance calls (TLS-aware)."""

    def __init__(self, tls=None):
        self._channels: dict[str, grpc.Channel] = {}
        self._lock = threading.Lock()
        self._tls = tls

    def get(self, endpoint: str) -> grpc.Channel:
        with self._lock:
            ch = self._channels.get(endpoint)
            if ch is None:
                if self._tls is not None:
                    from modelmesh_tpu.serving.tls import secure_channel

                    ch = secure_channel(endpoint, self._tls)
                else:
                    ch = grpc.insecure_channel(
                        endpoint, options=message_size_options()
                    )
                self._channels[endpoint] = ch
            return ch

    def close(self) -> None:
        with self._lock:
            for ch in self._channels.values():
                ch.close()
            self._channels.clear()


def make_grpc_peer_call(channels: Optional[PeerChannels] = None,
                        timeout_s: float = 30.0, tls=None):
    """Build the instance's peer transport over gRPC."""
    if channels is not None and tls is not None:
        raise ValueError(
            "pass tls to the PeerChannels cache, not alongside it — a "
            "caller-supplied cache keeps its own transport security"
        )
    channels = channels or PeerChannels(tls)

    def peer_call(
        endpoint: str, model_id: str, method: Optional[str], payload: bytes,
        headers: list[tuple[str, str]], ctx: RoutingContext,
    ) -> InvokeResult:
        stub = grpc_defs.make_stub(
            channels.get(endpoint), grpc_defs.INTERNAL_SERVICE,
            grpc_defs.INTERNAL_METHODS,
        )
        req = ipb.ForwardRequest(
            model_id=model_id,
            method_name=method or "",
            payload=payload,
            headers=dict(headers),
            ctx=_ctx_to_proto(ctx),
        )
        try:
            resp, trailers = grpc_defs.call_cancellable(
                stub.Forward, req, timeout=timeout_s,
                cancel_event=ctx.cancel_event, with_trailers=True,
            )
        except grpc.RpcError as e:
            detail = ""
            for k, v in (e.trailing_metadata() or ()):
                if k == ERROR_HEADER:
                    detail = v
            if detail == _ERR_NOT_HERE:
                raise ModelNotHereError(ctx.dest_instance, model_id) from e
            if detail == _ERR_NO_CAPACITY:
                raise NoCapacityError(e.details() or "") from e
            if detail == _ERR_LOAD_FAILED:
                raise ModelLoadException(e.details() or "load failed") from e
            if e.code() == grpc.StatusCode.NOT_FOUND:
                raise ModelNotFoundError(model_id) from e
            if e.code() in (
                grpc.StatusCode.UNAVAILABLE, grpc.StatusCode.DEADLINE_EXCEEDED
            ):
                raise ServiceUnavailableError(endpoint) from e
            raise ApplierError(e.code().name, e.details() or "") from e
        status_name = {v: k for k, v in _STATUS_MAP.items()}.get(
            resp.model_status, "UNKNOWN"
        )
        # The mm-load trailer is the IMMEDIATE peer's report, so it is
        # attributed to the dialed instance (served_by may be a further
        # hop — not who our next pick would queue behind).
        feedback = None
        for k, v in trailers:
            if k == LOAD_HEADER:
                feedback = LoadFeedback.decode(ctx.dest_instance, v)
                break
        return InvokeResult(
            resp.payload, resp.served_by, status_name, feedback=feedback
        )

    peer_call.channels = channels  # for cleanup
    return peer_call


def make_grpc_peer_fetch(channels: Optional[PeerChannels] = None,
                         timeout_s: float = 30.0, tls=None):
    """Build the instance's weight-fetch transport over gRPC (the
    FetchWeights method beside Forward). Share the ``channels`` cache
    with ``make_grpc_peer_call`` so both internal surfaces multiplex one
    connection per peer."""
    from modelmesh_tpu.transfer.protocol import FetchReply

    if channels is not None and tls is not None:
        raise ValueError(
            "pass tls to the PeerChannels cache, not alongside it — a "
            "caller-supplied cache keeps its own transport security"
        )
    channels = channels or PeerChannels(tls)

    def peer_fetch(endpoint: str, model_id: str, chunk_index: int,
                   fingerprint: str) -> FetchReply:
        stub = grpc_defs.make_stub(
            channels.get(endpoint), grpc_defs.INTERNAL_SERVICE,
            grpc_defs.INTERNAL_METHODS,
        )
        req = tpb.FetchWeightsRequest(
            model_id=model_id, chunk_index=chunk_index,
            fingerprint=fingerprint,
        )
        # Propagate the fetching load's trace context so the sender's
        # chunk-serving records join the receiver's trace tree.
        tid = Tracer.current_trace_id()
        md = (
            ((TRACE_HEADER, tid), (SPAN_HEADER, Tracer.current_span_id()))
            if tid else None
        )
        try:
            resp = stub.FetchWeights(req, timeout=timeout_s, metadata=md)
        except grpc.RpcError as e:
            # Transport-level failure (peer death, deadline): surfaced as
            # the mesh's unavailable error so the transfer manager's
            # mid-stream fallback takes over.
            raise ServiceUnavailableError(endpoint) from e
        return FetchReply(
            status=resp.status,
            payload=resp.payload,
            seq=resp.seq,
            layer=resp.layer,
            last=resp.last,
            total_chunks=resp.total_chunks,
            total_bytes=resp.total_bytes,
            total_layers=resp.total_layers,
            fingerprint=resp.fingerprint,
        )

    peer_fetch.channels = channels
    return peer_fetch
