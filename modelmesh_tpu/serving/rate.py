"""Request-rate tracking: per-instance and per-model RPM over a ring buffer.

Equivalent of the reference's RateTracker (RateTracker.java:26-115): 30
one-minute buckets; busyness = extrapolated requests/min over the recent
window. Also used per-model by the scale-up logic (rateTrackingTask,
ModelMesh.java:5619-5806).
"""

from __future__ import annotations

import threading

from modelmesh_tpu.utils import clock as _clock

BUCKETS = 30
BUCKET_MS = 60_000


class RateTracker:
    def __init__(self, clock_ms=None):
        self._clock = clock_ms or _clock.now_ms
        self._counts = [0] * BUCKETS
        self._bucket_start = self._clock()
        self._bucket_idx = 0
        self._lock = threading.Lock()

    def _advance(self, now: int) -> None:
        elapsed = now - self._bucket_start
        steps = int(elapsed // BUCKET_MS)
        if steps <= 0:
            return
        for _ in range(min(steps, BUCKETS)):
            self._bucket_idx = (self._bucket_idx + 1) % BUCKETS
            self._counts[self._bucket_idx] = 0
        self._bucket_start += steps * BUCKET_MS

    def record(self, n: int = 1) -> None:
        with self._lock:
            self._advance(self._clock())
            self._counts[self._bucket_idx] += n

    def rpm(self, window_minutes: int = 5) -> int:
        """Requests/min over the last ``window_minutes`` full+current buckets,
        extrapolating the in-progress bucket."""
        with self._lock:
            now = self._clock()
            self._advance(now)
            w = max(1, min(window_minutes, BUCKETS - 1))
            total = 0
            for k in range(w):
                total += self._counts[(self._bucket_idx - k) % BUCKETS]
            frac = (now - self._bucket_start) / BUCKET_MS
            minutes = (w - 1) + max(frac, 1.0 / 60)
            return int(total / minutes)
