"""Type constraints + upgrade tracking: placement candidate filtering.

Two placement filters the reference applies before its LB walk:

- TypeConstraintManager (TypeConstraintManager.java, SURVEY.md section 2.1):
  heterogeneous clusters where model types may only load on instances with
  certain labels (``required``) and prefer others (``preferred``); config is
  JSON from an env var or a live-watched file (the ConfigMap pattern,
  ConfigMapKeyFileWatcher.java).
- UpgradeTracker (UpgradeTracker.java:17-32): during rolling updates, infer
  which replica sets are being replaced from instance-id structure
  (``<deployment>-<rs-hash>-<pod>``) and arrival order, and avoid placing
  new copies on pods of the outgoing set.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Optional, Sequence

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.records import InstanceRecord

log = logging.getLogger(__name__)


class TypeConstraints:
    """model_type -> required/preferred instance labels.

    Config JSON:
    {"types": {
        "my-type": {"required": ["gpu"], "preferred": ["zone-a"]},
        "_default": {"required": []}
    }}
    """

    def __init__(self, config: Optional[dict] = None):
        self._lock = threading.Lock()
        self._types: dict[str, dict] = {}
        if config:
            self.update(config)

    @classmethod
    def from_json(cls, text: str) -> "TypeConstraints":
        return cls(json.loads(text) if text.strip() else None)

    def update(self, config: dict) -> None:
        types = config.get("types", config)
        with self._lock:
            self._types = {
                t: {
                    "required": set(spec.get("required", ())),
                    "preferred": set(spec.get("preferred", ())),
                }
                for t, spec in types.items()
            }

    def _spec(self, model_type: str) -> dict:
        with self._lock:
            return (
                self._types.get(model_type)
                or self._types.get("_default")
                or {"required": set(), "preferred": set()}
            )

    def is_candidate(self, model_type: str, labels: Sequence[str]) -> bool:
        spec = self._spec(model_type)
        return spec["required"] <= set(labels)

    def is_preferred(self, model_type: str, labels: Sequence[str]) -> bool:
        spec = self._spec(model_type)
        pref = spec["preferred"]
        return not pref or bool(pref & set(labels))

    def non_candidates(
        self, model_type: str,
        instances: Sequence[tuple[str, InstanceRecord]],
    ) -> set[str]:
        """Instance ids that must NOT host this model type."""
        return {
            iid for iid, rec in instances
            if not self.is_candidate(model_type, rec.labels)
        }


class ConstraintsFileWatcher:
    """Poll a JSON constraints file for live reload.

    The reference watches the ConfigMap ``..data`` symlink with inotify;
    mtime+content polling is the portable equivalent with the same observable
    behavior (sub-second pickup of atomic file replacement).
    """

    def __init__(
        self, path: str, constraints: TypeConstraints,
        poll_interval_s: float = 1.0,
    ):
        self.path = path
        self.constraints = constraints
        self._interval = poll_interval_s
        self._stop = threading.Event()
        self._last: Optional[bytes] = None
        self._load()
        self._thread = threading.Thread(
            target=self._loop, name="constraints-watch", daemon=True
        )
        self._thread.start()

    def _load(self) -> None:
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return
        if data == self._last:
            return
        self._last = data
        try:
            self.constraints.update(json.loads(data.decode() or "{}"))
            log.info("type constraints reloaded from %s", self.path)
        except Exception as e:  # noqa: BLE001 — a bad file must never kill
            # the watcher thread; keep serving the previous constraints.
            log.error("bad constraints file %s: %s", self.path, e)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self._load()

    def close(self) -> None:
        self._stop.set()


def parse_instance_id(instance_id: str) -> tuple[str, str]:
    """``<deployment>-<rs-hash>-<pod-suffix>`` -> (deployment, replicaset).

    Ids that don't match the k8s naming shape map to themselves (no
    grouping, so the tracker never penalizes them).
    """
    parts = instance_id.rsplit("-", 2)
    if len(parts) == 3 and parts[1] and parts[2]:
        return parts[0], f"{parts[0]}-{parts[1]}"
    return instance_id, instance_id


class UpgradeTracker:
    """Infer replica sets being replaced during rolling updates.

    When instances from two replica sets of the same deployment coexist and
    the newer set's first arrival is recent, the older set is "likely being
    replaced": placement should avoid it (its pods will shut down soon).
    """

    def __init__(self, fresh_window_ms: int = 10 * 60_000):
        self.fresh_window_ms = fresh_window_ms
        # replicaset -> first time an instance of it was observed.
        self._first_seen: dict[str, int] = {}
        self._lock = threading.Lock()

    def observe(self, instances: Sequence[tuple[str, InstanceRecord]]) -> None:
        now = now_ms()
        with self._lock:
            live_rs = set()
            for iid, _rec in instances:
                _, rs = parse_instance_id(iid)
                live_rs.add(rs)
                self._first_seen.setdefault(rs, now)
            for rs in list(self._first_seen):
                if rs not in live_rs:
                    del self._first_seen[rs]

    def likely_replaced(
        self, instances: Sequence[tuple[str, InstanceRecord]]
    ) -> set[str]:
        """Instance ids in replica sets presumed outgoing."""
        self.observe(instances)
        now = now_ms()
        by_deploy: dict[str, list[str]] = {}
        for iid, _rec in instances:
            dep, rs = parse_instance_id(iid)
            by_deploy.setdefault(dep, [])
            if rs not in by_deploy[dep]:
                by_deploy[dep].append(rs)
        doomed_rs: set[str] = set()
        with self._lock:
            for dep, rss in by_deploy.items():
                if len(rss) < 2:
                    continue
                # Newest set = most recent first_seen; if it's fresh, all
                # older sets of this deployment are being replaced.
                rss.sort(key=lambda rs: self._first_seen.get(rs, 0))
                newest = rss[-1]
                if now - self._first_seen.get(newest, 0) <= self.fresh_window_ms:
                    doomed_rs.update(rss[:-1])
        return {
            iid for iid, _ in instances
            if parse_instance_id(iid)[1] in doomed_rs
        }
