"""Virtual models: stable aliases over concrete model versions.

Capability parity with the reference's VModelManager (VModelManager.java,
SURVEY.md section 2.1): a vmodel maps a stable id to an ``active`` concrete
model; updating the vmodel to a new ``target`` starts a managed transition —
the target is loaded up to the active's copy count before promotion, so the
alias never points at a cold model. Concrete models are ref-counted and can
be auto-deleted when the last vmodel reference moves away (:749-767).
Failed transitions are parked (``target_load_failed``) and retried by the
leader's transition sweep (:666-683). Per-request resolution with a
retry-on-concurrent-transition loop mirrors resolveVModelId (:569).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from modelmesh_tpu.utils.clock import get_clock

import grpc

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.kv.store import CasFailed, Compare, KVStore, Op
from modelmesh_tpu.kv.table import KVTable, TableView
from modelmesh_tpu.proto import mesh_api_pb2 as apb
from modelmesh_tpu.records import ModelRecord, VModelRecord
from modelmesh_tpu.runtime.spi import ModelInfo
from modelmesh_tpu.serving.instance import ModelMeshInstance

log = logging.getLogger(__name__)


class VModelManager:
    def __init__(
        self,
        instance: ModelMeshInstance,
        sweep_interval_s: float = 30.0,
    ):
        self.instance = instance
        store: KVStore = instance.store
        prefix = instance.config.kv_prefix
        self.table: KVTable[VModelRecord] = KVTable(
            store, f"{prefix}/vmodels", VModelRecord
        )
        self.view: TableView[VModelRecord] = TableView(self.table)
        self._clock = get_clock()
        self._stop = threading.Event()
        # clock-aware: kicks (and close) wake a virtual-time sweep wait.
        self._kick = self._clock.new_event()
        self._sweeper = threading.Thread(
            target=self._sweep_loop, args=(sweep_interval_s,),
            name=f"vmodel-sweep-{instance.instance_id}", daemon=True,
        )
        self._sweeper.start()

    def close(self) -> None:
        self._stop.set()
        self._kick.set()
        self.view.close()

    # ------------------------------------------------------------------ #
    # management ops                                                     #
    # ------------------------------------------------------------------ #

    def set_vmodel(self, request, context, status_fn) -> apb.VModelStatusInfo:
        vmid = request.vmodel_id
        target = request.target_model_id
        if not vmid or not target:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "vmodel_id and target_model_id are required",
            )
        if self.instance.config.read_only:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "vmodel mutations rejected in KV-migration read-only mode",
            )
        info = ModelInfo(
            model_type=request.info.model_type,
            model_path=request.info.model_path,
            model_key=request.info.model_key,
        )
        # Register the target concrete model; the vmodel reference is added
        # only if the record mutation actually starts referencing it (an
        # idempotent re-set must not leak a ref).
        self.instance.register_model(target, info)

        existing = self.table.get(vmid)
        if existing is None and request.update_only:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"vmodel {vmid} does not exist"
            )
        if (
            existing is not None
            and existing.owner
            and request.owner
            and existing.owner != request.owner
        ):
            context.abort(
                grpc.StatusCode.ALREADY_EXISTS,
                f"vmodel {vmid} is owned by {existing.owner}",
            )

        # Vmodel mutation + ref bumps ride ONE multi-key txn (same
        # no-crash-window property as _promote_atomically): a crash can't
        # leave the record referencing an unbumped target or leak a
        # superseded target's refcount.
        vkey = self.table.raw_key(vmid)
        vr = None
        for _ in range(20):
            cur = self.table.get(vmid)
            superseded = None
            if cur is None:
                if request.update_only:
                    context.abort(
                        grpc.StatusCode.NOT_FOUND,
                        f"vmodel {vmid} does not exist",
                    )
                vr = VModelRecord(
                    owner=request.owner, active_model=target,
                    target_model=target,
                )
                added_ref, expected_version = True, 0
            else:
                vr = cur
                if cur.target_model == target:
                    added_ref = False
                else:
                    if cur.target_model != cur.active_model and not request.force:
                        # A different transition is already running.
                        context.abort(
                            grpc.StatusCode.FAILED_PRECONDITION,
                            f"vmodel {vmid} transition to {cur.target_model} "
                            f"in progress (use force to supersede)",
                        )
                    # Invariant: the vmodel holds ONE ref on active and ONE
                    # on target when they differ. A force-ROLLBACK (target
                    # == current active) must therefore not bump — the
                    # active ref is already held; only the superseded
                    # in-flight target releases.
                    added_ref = target != cur.active_model
                    if cur.target_model != cur.active_model:
                        superseded = cur.target_model
                    cur.target_model = target
                    cur.target_load_failed = False
                expected_version = cur.version
            compares = [Compare(vkey, expected_version)]
            ops = [Op(vkey, vr.to_bytes())]
            auto_deleted = []
            if added_ref:
                c, o, _ = self._ref_mutation(
                    target, +1, auto_delete=request.auto_delete_target
                )
                if c is not None:
                    compares.append(c)
                    ops.append(o)
            if superseded and superseded != target:
                c, o, deleted = self._ref_mutation(superseded, -1)
                if c is not None:
                    compares.append(c)
                    ops.append(o)
                    if deleted:
                        auto_deleted.append(superseded)
            ok, _ = self.instance.store.txn(compares, ops, [])
            if ok:
                vr.version = expected_version + 1
                for mid in auto_deleted:
                    log.info("auto-deleted unreferenced model %s", mid)
                break
        else:
            context.abort(
                grpc.StatusCode.ABORTED,
                f"vmodel {vmid} set kept conflicting; retry",
            )

        if request.load_now or vr.in_transition:
            if request.sync:
                self._advance_transition(vmid)
            else:
                self._kick.set()
        if request.load_now and not vr.in_transition:
            try:
                self.instance.ensure_loaded(target, sync=request.sync)
            except Exception as e:  # noqa: BLE001 — best effort
                log.debug("vmodel %s initial load: %s", vmid, e)
        return self._status(vmid, status_fn)

    def delete_vmodel(self, request, context) -> apb.DeleteVModelResponse:
        if self.instance.config.read_only:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "vmodel mutations rejected in KV-migration read-only mode",
            )
        vmid = request.vmodel_id
        vkey = self.table.raw_key(vmid)
        # Alias delete + refcount releases ride ONE txn: a crash after a
        # bare alias delete would orphan the refcounts forever (no record
        # left for any sweeper to redo the decrements from). CAS-retry: a
        # concurrent promotion bumps versions between read and txn.
        for _ in range(10):
            vr = self.table.get(vmid)
            if vr is None:
                return apb.DeleteVModelResponse()
            if vr.owner and request.owner and vr.owner != request.owner:
                context.abort(
                    grpc.StatusCode.ALREADY_EXISTS,
                    f"vmodel {vmid} is owned by {vr.owner}",
                )
            compares = [Compare(vkey, vr.version)]
            ops = [Op(vkey)]
            auto_deleted = []
            for mid in {vr.active_model, vr.target_model} - {""}:
                c, o, deleted = self._ref_mutation(mid, -1)
                if c is not None:
                    compares.append(c)
                    ops.append(o)
                    if deleted:
                        auto_deleted.append(mid)
            ok, _ = self.instance.store.txn(compares, ops, [])
            if ok:
                for mid in auto_deleted:
                    log.info("auto-deleted unreferenced model %s", mid)
                return apb.DeleteVModelResponse()
        context.abort(
            grpc.StatusCode.ABORTED,
            f"vmodel {vmid} delete kept conflicting; retry",
        )

    def get_vmodel_status(self, request, context, status_fn) -> apb.VModelStatusInfo:
        return self._status(request.vmodel_id, status_fn, abort_ctx=context)

    def _status(
        self, vmid: str, status_fn, abort_ctx=None
    ) -> apb.VModelStatusInfo:
        # Authoritative read: the watch-fed view may lag a just-completed
        # synchronous transition; status RPCs are rare enough to pay the
        # direct KV read.
        vr = self.table.get(vmid) or self.view.get(vmid)
        if vr is None:
            if abort_ctx is not None:
                abort_ctx.abort(
                    grpc.StatusCode.NOT_FOUND, f"vmodel {vmid} not found"
                )
            return apb.VModelStatusInfo()
        if not vr.in_transition:
            transition = apb.VModelStatusInfo.NONE
        elif vr.target_load_failed:
            transition = apb.VModelStatusInfo.FAILED
        else:
            transition = apb.VModelStatusInfo.IN_PROGRESS
        return apb.VModelStatusInfo(
            active_model_id=vr.active_model,
            target_model_id=vr.target_model,
            transition=transition,
            active_status=status_fn(vr.active_model),
            owner=vr.owner,
        )

    # ------------------------------------------------------------------ #
    # per-request resolution                                             #
    # ------------------------------------------------------------------ #

    def resolve(self, vmodel_id: str, context=None) -> str:
        """vmodel id -> active concrete id, tolerating concurrent
        transitions (retry loop, reference resolveVModelId :569)."""
        for _ in range(3):
            vr = self.view.get(vmodel_id) or self.table.get(vmodel_id)
            if vr is None:
                break
            active = vr.active_model
            if self.instance.registry_view.get(active) is not None or (
                self.instance.registry.get(active) is not None
            ):
                return active
            # Active model vanished mid-promotion; re-read.
        if context is not None:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"vmodel {vmodel_id} not found"
            )
        raise KeyError(vmodel_id)

    # ------------------------------------------------------------------ #
    # transitions                                                        #
    # ------------------------------------------------------------------ #

    def _sweep_loop(self, interval: float) -> None:
        while True:
            kicked = self._clock.wait_event(self._kick, interval)
            self._kick.clear()
            if self._stop.is_set():
                return
            # The leader sweeps ALL transitions (including parked/stuck ones
            # left by dead initiators); a non-leader only advances ones it
            # was just kicked for (its own async SetVModel calls).
            if not (kicked or self.instance.is_leader):
                continue
            try:
                for vmid, vr in self.view.items():
                    if vr.in_transition and not (
                        vr.target_load_failed and not self.instance.is_leader
                    ):
                        self._advance_transition(vmid)
            except Exception:  # noqa: BLE001
                log.exception("vmodel sweep failed")

    def _advance_transition(self, vmid: str) -> None:
        if self.instance.config.read_only:
            # Migration read-only: promotion writes the vmodel record and
            # can auto-delete the old model's registration — both blocked.
            # The transition stays pending and resumes after migration.
            return
        vr = self.table.get(vmid)
        if vr is None or not vr.in_transition:
            return
        target = vr.target_model
        old = vr.active_model
        old_mr = self.instance.registry.get(old)
        want_copies = max(1, old_mr.copy_count if old_mr else 1)
        try:
            # Load the target up to the active's scale before promotion.
            tgt = self.instance.registry.get(target)
            have = len(tgt.instance_ids) if tgt else 0
            while have < want_copies:
                exclude = set(tgt.all_placements) if tgt else set()
                status = self.instance.ensure_loaded(
                    target, sync=True, exclude=exclude
                )
                # A sync load unblocks on the cache entry going ACTIVE;
                # the loader thread's registry promote (a CAS, possibly
                # over a networked KV) can land a beat LATER. When the load
                # reports success, poll briefly for visible progress — but
                # don't stall on a load that plainly didn't happen (that
                # would serialize the leader sweep behind every unplaceable
                # transition). The long poll applies only to the FIRST copy
                # (the promotion-blocking race); "LOADED" during scale-up
                # can mean the request rode an existing copy with no new
                # placement, so extra copies get a short poll and the
                # sweep's next pass picks up any real lag.
                if status not in ("LOADED", "LOADING"):
                    poll_s = 0.0
                elif have == 0:
                    poll_s = 5.0
                else:
                    poll_s = 1.0
                poll_deadline = self._clock.monotonic() + poll_s
                new_tgt, new_have = tgt, have
                while True:
                    new_tgt = self.instance.registry.get(target)
                    new_have = len(new_tgt.instance_ids) if new_tgt else 0
                    if new_have > have or self._clock.monotonic() > poll_deadline:
                        break
                    self._clock.sleep(0.05)
                if new_have <= have:
                    break  # no progress (cluster can't fit more copies)
                tgt, have = new_tgt, new_have
            if have < 1:
                raise RuntimeError(f"no copies of target {target} loaded")
        except Exception as e:  # noqa: BLE001 — park the transition
            log.warning("vmodel %s transition failed: %s", vmid, e)

            def park(cur):
                if cur is None or cur.target_model != target:
                    return cur
                cur.target_load_failed = True
                return cur

            try:
                self.table.update_or_create(vmid, park)
            except CasFailed:
                pass
            return

        flipped_from = self._promote_atomically(vmid, target)
        if flipped_from is not None:
            log.info("vmodel %s promoted %s -> %s", vmid, flipped_from, target)

    def _promote_atomically(self, vmid: str, target: str) -> Optional[str]:
        """Flip active -> target AND release the old model's reference in
        ONE multi-key store transaction (the reference promotes and
        decrements in a single KV txn, VModelManager.java:749-767). A crash
        can no longer land between the flip and the decrement and leak a
        refcount that keeps auto-delete from ever firing (round-2 VERDICT
        weak #4). Compare guards on both records' versions give the same
        only-the-winning-racer-decrements property the old two-step CAS
        had — without its non-atomic window.

        Returns the previous active id if THIS call performed the flip,
        None if the transition was superseded or already promoted.
        """
        store: KVStore = self.instance.store
        vkey = self.table.raw_key(vmid)
        for _ in range(20):
            vr = self.table.get(vmid)
            if vr is None or vr.target_model != target:
                return None  # superseded
            if vr.active_model == target:
                return None  # already promoted by a concurrent sweeper
            old = vr.active_model
            vr.active_model = target
            vr.target_load_failed = False
            compares = [Compare(vkey, vr.version)]
            ops = [Op(vkey, vr.to_bytes())]
            auto_deleted = False
            if old and old != target:
                # On refcount 0 + auto_delete the registration delete rides
                # the same txn; holders unload via the deletion watch.
                c, o, auto_deleted = self._ref_mutation(old, -1)
                if c is not None:
                    compares.append(c)
                    ops.append(o)
            ok, _ = store.txn(compares, ops, [])
            if ok:
                if auto_deleted:
                    log.info("auto-deleted unreferenced model %s", old)
                return old
            # Either record moved under us; re-read and retry.
        log.warning("vmodel %s promotion kept conflicting; sweeper retries", vmid)
        return None

    # ------------------------------------------------------------------ #
    # concrete-model ref counting                                        #
    # ------------------------------------------------------------------ #

    def _ref_mutation(
        self, model_id: str, delta: int, auto_delete: bool = False
    ) -> tuple[Optional[Compare], Optional[Op], bool]:
        """Read the model record and express a refcount bump as a
        (Compare, Op) pair composable into multi-key store txns — the
        building block that makes set/promote/delete atomic with their ref
        releases. Returns (None, None, False) if the record is absent; the
        bool is True when the op deletes an unreferenced auto_delete record.
        """
        mr = self.instance.registry.get(model_id)
        if mr is None:
            return None, None, False
        mkey = self.instance.registry.raw_key(model_id)
        compare = Compare(mkey, mr.version)
        mr.ref_count = max(0, mr.ref_count + delta)
        if delta > 0 and auto_delete:
            mr.auto_delete = True
        if mr.ref_count == 0 and mr.auto_delete:
            return compare, Op(mkey), True
        return compare, Op(mkey, mr.to_bytes()), False

    def bump_ref(self, model_id: str, delta: int, auto_delete: bool = False) -> None:
        """Standalone refcount bump as a single-key txn (CAS-retried).

        Production mutation paths compose ``_ref_mutation`` into their OWN
        multi-key txns — do NOT reach for this from a path that also
        mutates a vmodel record, or you reintroduce the crash window the
        txn-ification closed. For out-of-band adjustments (tests, tooling).
        """
        for _ in range(10):
            compare, op, deleted = self._ref_mutation(
                model_id, delta, auto_delete
            )
            if compare is None:
                return
            ok, _ = self.instance.store.txn([compare], [op], [])
            if ok:
                if deleted:
                    log.info("auto-deleted unreferenced model %s", model_id)
                return
        log.warning("ref-count txn gave up for %s", model_id)


