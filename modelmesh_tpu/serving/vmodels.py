"""Virtual models: stable aliases over concrete model versions.

Capability parity with the reference's VModelManager (VModelManager.java,
SURVEY.md section 2.1): a vmodel maps a stable id to an ``active`` concrete
model; updating the vmodel to a new ``target`` starts a managed transition —
the target is loaded up to the active's copy count before promotion, so the
alias never points at a cold model. Concrete models are ref-counted and can
be auto-deleted when the last vmodel reference moves away (:749-767).
Failed transitions are parked (``target_load_failed``) and retried by the
leader's transition sweep (:666-683). Per-request resolution with a
retry-on-concurrent-transition loop mirrors resolveVModelId (:569).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

import grpc

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.kv.store import CasFailed, KVStore
from modelmesh_tpu.kv.table import KVTable, TableView
from modelmesh_tpu.proto import mesh_api_pb2 as apb
from modelmesh_tpu.records import ModelRecord, VModelRecord
from modelmesh_tpu.runtime.spi import ModelInfo
from modelmesh_tpu.serving.instance import ModelMeshInstance

log = logging.getLogger(__name__)


class VModelManager:
    def __init__(
        self,
        instance: ModelMeshInstance,
        sweep_interval_s: float = 30.0,
    ):
        self.instance = instance
        store: KVStore = instance.store
        prefix = instance.config.kv_prefix
        self.table: KVTable[VModelRecord] = KVTable(
            store, f"{prefix}/vmodels", VModelRecord
        )
        self.view: TableView[VModelRecord] = TableView(self.table)
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._sweeper = threading.Thread(
            target=self._sweep_loop, args=(sweep_interval_s,),
            name=f"vmodel-sweep-{instance.instance_id}", daemon=True,
        )
        self._sweeper.start()

    def close(self) -> None:
        self._stop.set()
        self._kick.set()
        self.view.close()

    # ------------------------------------------------------------------ #
    # management ops                                                     #
    # ------------------------------------------------------------------ #

    def set_vmodel(self, request, context, status_fn) -> apb.VModelStatusInfo:
        vmid = request.vmodel_id
        target = request.target_model_id
        if not vmid or not target:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "vmodel_id and target_model_id are required",
            )
        info = ModelInfo(
            model_type=request.info.model_type,
            model_path=request.info.model_path,
            model_key=request.info.model_key,
        )
        # Register the target concrete model; the vmodel reference is added
        # only if the record mutation actually starts referencing it (an
        # idempotent re-set must not leak a ref).
        self.instance.register_model(target, info)

        existing = self.table.get(vmid)
        if existing is None and request.update_only:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"vmodel {vmid} does not exist"
            )
        if (
            existing is not None
            and existing.owner
            and request.owner
            and existing.owner != request.owner
        ):
            context.abort(
                grpc.StatusCode.ALREADY_EXISTS,
                f"vmodel {vmid} is owned by {existing.owner}",
            )

        # Written fresh on every mutate attempt so CAS retries don't
        # accumulate stale outcomes.
        outcome: dict = {}

        def mutate(cur: Optional[VModelRecord]) -> VModelRecord:
            outcome.clear()
            if cur is None:
                outcome["added_ref"] = True
                return VModelRecord(
                    owner=request.owner, active_model=target, target_model=target
                )
            if cur.target_model != target:
                if cur.target_model != cur.active_model and not request.force:
                    # A different transition is already running.
                    raise _TransitionBusy(cur.target_model)
                outcome["added_ref"] = True
                if cur.target_model != cur.active_model:
                    outcome["superseded"] = cur.target_model
                cur.target_model = target
                cur.target_load_failed = False
            return cur

        try:
            vr = self.table.update_or_create(vmid, mutate)
        except _TransitionBusy as e:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"vmodel {vmid} transition to {e.args[0]} in progress "
                f"(use force to supersede)",
            )
        if outcome.get("added_ref"):
            self._bump_ref(target, +1, auto_delete=request.auto_delete_target)
        superseded = outcome.get("superseded")
        if superseded and superseded != target:
            self._bump_ref(superseded, -1)  # superseded mid-transition

        if request.load_now or vr.in_transition:
            if request.sync:
                self._advance_transition(vmid)
            else:
                self._kick.set()
        if request.load_now and not vr.in_transition:
            try:
                self.instance.ensure_loaded(target, sync=request.sync)
            except Exception as e:  # noqa: BLE001 — best effort
                log.debug("vmodel %s initial load: %s", vmid, e)
        return self._status(vmid, status_fn)

    def delete_vmodel(self, request, context) -> apb.DeleteVModelResponse:
        vmid = request.vmodel_id
        # CAS-retry: a concurrent promotion bumps the record version between
        # read and delete; silently not deleting (while returning success)
        # would leak the alias and its refs.
        for _ in range(10):
            vr = self.table.get(vmid)
            if vr is None:
                return apb.DeleteVModelResponse()
            if vr.owner and request.owner and vr.owner != request.owner:
                context.abort(
                    grpc.StatusCode.ALREADY_EXISTS,
                    f"vmodel {vmid} is owned by {vr.owner}",
                )
            if self.table.conditional_delete(vmid, vr.version):
                refs = {vr.active_model, vr.target_model} - {""}
                for mid in refs:
                    self._bump_ref(mid, -1)
                return apb.DeleteVModelResponse()
        context.abort(
            grpc.StatusCode.ABORTED,
            f"vmodel {vmid} delete kept conflicting; retry",
        )

    def get_vmodel_status(self, request, context, status_fn) -> apb.VModelStatusInfo:
        return self._status(request.vmodel_id, status_fn, abort_ctx=context)

    def _status(
        self, vmid: str, status_fn, abort_ctx=None
    ) -> apb.VModelStatusInfo:
        # Authoritative read: the watch-fed view may lag a just-completed
        # synchronous transition; status RPCs are rare enough to pay the
        # direct KV read.
        vr = self.table.get(vmid) or self.view.get(vmid)
        if vr is None:
            if abort_ctx is not None:
                abort_ctx.abort(
                    grpc.StatusCode.NOT_FOUND, f"vmodel {vmid} not found"
                )
            return apb.VModelStatusInfo()
        if not vr.in_transition:
            transition = apb.VModelStatusInfo.NONE
        elif vr.target_load_failed:
            transition = apb.VModelStatusInfo.FAILED
        else:
            transition = apb.VModelStatusInfo.IN_PROGRESS
        return apb.VModelStatusInfo(
            active_model_id=vr.active_model,
            target_model_id=vr.target_model,
            transition=transition,
            active_status=status_fn(vr.active_model),
            owner=vr.owner,
        )

    # ------------------------------------------------------------------ #
    # per-request resolution                                             #
    # ------------------------------------------------------------------ #

    def resolve(self, vmodel_id: str, context=None) -> str:
        """vmodel id -> active concrete id, tolerating concurrent
        transitions (retry loop, reference resolveVModelId :569)."""
        for _ in range(3):
            vr = self.view.get(vmodel_id) or self.table.get(vmodel_id)
            if vr is None:
                break
            active = vr.active_model
            if self.instance.registry_view.get(active) is not None or (
                self.instance.registry.get(active) is not None
            ):
                return active
            # Active model vanished mid-promotion; re-read.
        if context is not None:
            context.abort(
                grpc.StatusCode.NOT_FOUND, f"vmodel {vmodel_id} not found"
            )
        raise KeyError(vmodel_id)

    # ------------------------------------------------------------------ #
    # transitions                                                        #
    # ------------------------------------------------------------------ #

    def _sweep_loop(self, interval: float) -> None:
        while True:
            kicked = self._kick.wait(interval)
            self._kick.clear()
            if self._stop.is_set():
                return
            # The leader sweeps ALL transitions (including parked/stuck ones
            # left by dead initiators); a non-leader only advances ones it
            # was just kicked for (its own async SetVModel calls).
            if not (kicked or self.instance.is_leader):
                continue
            try:
                for vmid, vr in self.view.items():
                    if vr.in_transition and not (
                        vr.target_load_failed and not self.instance.is_leader
                    ):
                        self._advance_transition(vmid)
            except Exception:  # noqa: BLE001
                log.exception("vmodel sweep failed")

    def _advance_transition(self, vmid: str) -> None:
        vr = self.table.get(vmid)
        if vr is None or not vr.in_transition:
            return
        target = vr.target_model
        old = vr.active_model
        old_mr = self.instance.registry.get(old)
        want_copies = max(1, old_mr.copy_count if old_mr else 1)
        try:
            # Load the target up to the active's scale before promotion.
            tgt = self.instance.registry.get(target)
            have = len(tgt.instance_ids) if tgt else 0
            while have < want_copies:
                exclude = set(tgt.all_placements) if tgt else set()
                self.instance.ensure_loaded(target, sync=True, exclude=exclude)
                new_tgt = self.instance.registry.get(target)
                new_have = len(new_tgt.instance_ids) if new_tgt else 0
                if new_have <= have:
                    break  # no progress (cluster can't fit more copies)
                tgt, have = new_tgt, new_have
            if have < 1:
                raise RuntimeError(f"no copies of target {target} loaded")
        except Exception as e:  # noqa: BLE001 — park the transition
            log.warning("vmodel %s transition failed: %s", vmid, e)

            def park(cur):
                if cur is None or cur.target_model != target:
                    return cur
                cur.target_load_failed = True
                return cur

            try:
                self.table.update_or_create(vmid, park)
            except CasFailed:
                pass
            return

        # Only the racer whose CAS actually flips active -> target releases
        # the old model's reference; a concurrent promoter that finds the
        # flip already done must not double-decrement.
        outcome: dict = {}

        def promote(cur: Optional[VModelRecord]) -> Optional[VModelRecord]:
            outcome.clear()
            if cur is None or cur.target_model != target:
                return cur  # superseded
            if cur.active_model == target:
                return cur  # already promoted by a concurrent sweeper
            outcome["flipped_from"] = cur.active_model
            cur.active_model = target
            cur.target_load_failed = False
            return cur

        try:
            self.table.update_or_create(vmid, promote)
        except CasFailed:
            return
        flipped_from = outcome.get("flipped_from")
        if flipped_from and flipped_from != target:
            self._bump_ref(flipped_from, -1)
        if flipped_from is not None:
            log.info("vmodel %s promoted %s -> %s", vmid, flipped_from, target)

    # ------------------------------------------------------------------ #
    # concrete-model ref counting                                        #
    # ------------------------------------------------------------------ #

    def _bump_ref(self, model_id: str, delta: int, auto_delete: bool = False) -> None:
        deleted = []

        def mutate(cur: Optional[ModelRecord]) -> Optional[ModelRecord]:
            if cur is None:
                return None
            cur.ref_count = max(0, cur.ref_count + delta)
            if delta > 0 and auto_delete:
                cur.auto_delete = True
            if cur.ref_count == 0 and cur.auto_delete:
                deleted.append(model_id)
                return None  # delete the registration
            return cur

        try:
            self.instance.registry.update_or_create(model_id, mutate)
        except CasFailed:
            log.warning("ref-count CAS gave up for %s", model_id)
        if deleted:
            log.info("auto-deleted unreferenced model %s", model_id)


class _TransitionBusy(Exception):
    pass
