"""Model-copy lifecycle: cache entries, the loading pool, unload accounting.

Parity targets in the reference core:
- CacheEntry state machine NEW -> QUEUED -> WAITING -> LOADING -> SIZING ->
  ACTIVE | FAILED | REMOVED (ModelMesh.java:1838-1848, CacheEntry :1632)
- priority loading queue with limited concurrency (loadingPool :504,
  CacheEntry.run :2145)
- load timeout with diagnostic capture (scheduleTimeoutForLoad :2308-2336)
- unload-buffer accounting: space freed by eviction is unusable until the
  runtime confirms the unload, and loads block (bounded) waiting for it
  (ModelCacheUnloadBufManager.java; waitForSpaceToLoad :2271-2305)
- per-entry invocation gating for latency-based autoscaling
  (MaxConcCacheEntry :2641-2797)
"""

from __future__ import annotations

import enum
import heapq
import logging
import threading
import traceback
from typing import Callable, Optional

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.utils.clock import get_clock
from modelmesh_tpu.runtime.spi import (
    CACHE_UNIT_BYTES,
    LoadedModel,
    ModelInfo,
    ModelLoader,
    ModelLoadException,
)
from modelmesh_tpu.utils.lockdebug import mm_condition, mm_lock
from modelmesh_tpu.utils import racedebug

log = logging.getLogger(__name__)

# Initial nominal weight before prediction/sizing (units).
INSERTION_WEIGHT_UNITS = 8
# Max time a queued load waits for unloads to free space (reference: 3 min).
DEFAULT_SPACE_WAIT_S = 180.0


class EntryState(enum.Enum):
    NEW = "new"
    QUEUED = "queued"
    WAITING = "waiting"      # waiting for unload space
    LOADING = "loading"
    SIZING = "sizing"
    # Serve-before-fully-loaded (layer-streamable families only): enough
    # layers of a streamed transfer have landed to admit requests, while
    # the tail of the stream is still arriving. Servable AND still
    # loading; finalizes to ACTIVE when the stream completes (or FAILED/
    # REMOVED like any in-flight load).
    PARTIAL = "partial"
    # One shard of a multi-instance placement GROUP (sharded execution):
    # this copy holds 1/shard_count of the model's weights and is
    # servable — but only as a member of a COMPLETE group, a condition
    # the ROUTING layer enforces from the registry record (the entry
    # cannot see its peers). Terminal like ACTIVE: the shard is fully
    # materialized; group membership changes arrive as registry events
    # that REMOVE the entry, never as state regressions.
    SHARDED = "sharded"
    ACTIVE = "active"
    FAILED = "failed"
    REMOVED = "removed"

    @property
    def is_terminal(self) -> bool:
        return self in (
            EntryState.ACTIVE, EntryState.SHARDED,
            EntryState.FAILED, EntryState.REMOVED,
        )

    @property
    def is_loading(self) -> bool:
        return self in (
            EntryState.QUEUED, EntryState.WAITING,
            EntryState.LOADING, EntryState.SIZING, EntryState.PARTIAL,
        )

    @property
    def is_servable(self) -> bool:
        """Requests may execute against this copy (fully loaded, a
        partial streamed copy past its serve threshold, or a shard of a
        complete group — group completeness is the router's check)."""
        return self in (
            EntryState.ACTIVE, EntryState.PARTIAL, EntryState.SHARDED,
        )


@racedebug.tracked("state")
class CacheEntry:
    """One local copy of a model. Thread-safe via its own lock; completion
    is observed through ``wait_active``. Under MM_RACE_DEBUG=1 every
    ``state`` write is epoch-checked against the happens-before clocks —
    a transition that bypasses ``_lock`` raises ``DataRaceViolation``."""

    def __init__(
        self,
        model_id: str,
        info: ModelInfo,
        weight_units: int = INSERTION_WEIGHT_UNITS,
        last_used: Optional[int] = None,
    ):
        self.model_id = model_id
        self.info = info
        self.weight_units = weight_units
        self.last_used = last_used if last_used is not None else now_ms()
        # Every transition goes through the ONE funnel (PR-8): bare
        # writes would skip the terminal-state check, the cv broadcast,
        # and the flight-recorder event — the state-funnel rule flags
        # them.
        #: state-funnel: _transition_locked
        self.state = EntryState.NEW  #: guarded-by: _lock [rebind]
        self.error: Optional[str] = None  #: guarded-by: _lock
        self.loaded: Optional[LoadedModel] = None
        self.queued_ms: Optional[int] = None
        self.load_started_ms: Optional[int] = None
        self.load_completed_ms: Optional[int] = None
        self._lock = mm_lock("CacheEntry._lock")
        self._done = threading.Event()
        # Broadcast on EVERY state transition (not just terminal ones):
        # load waiters sleep on this instead of polling, waking exactly
        # when the entry moves — activation, failure, removal, or an
        # intermediate phase change that re-bases their timeout budget
        # (QUEUED -> LOADING starts the per-type load clock).
        self._state_cv = mm_condition("CacheEntry._state_cv", self._lock)
        self._sem: Optional[threading.Semaphore] = None  #: guarded-by: _lock
        # True once begin_partial installed a provisional runtime copy
        # (sticky — survives later state transitions; see _load_failed).
        self.partial_started = False
        # Sharded-execution shard metadata (set at insert time by
        # _load_local when the registry assigns this instance a shard;
        # immutable for the entry's lifetime — a re-plan REPLACES the
        # entry rather than mutating it). shard_index < 0 = unsharded.
        self.shard_index = -1
        self.shard_count = 0
        self.group_epoch = 0
        # Observability linkage, attached by the owning instance at
        # insert time: every state transition is recorded into the
        # flight recorder, and a load inherits the initiating request's
        # trace context (observability/tracing.py).
        self.recorder = None  # FlightRecorder | None
        self.trace_id = ""
        self.trace_parent = ""
        self.max_concurrency = 0
        self.inflight = 0  #: guarded-by: _lock
        self.total_invocations = 0  #: guarded-by: _lock
        # EWMA of invocation latency (ms); drives the latency-based
        # autoscaling threshold (reference MaxConcCacheEntry bandwidth
        # estimate, ModelMesh.java:2641-2797).
        self.avg_latency_ms = 0.0
        self._latency_samples = 0

    @property
    def is_shard(self) -> bool:
        return self.shard_index >= 0

    # bandwidth_rpm() stays 0 until this many samples — the first call often
    # includes cold-start/compile time and must not collapse the threshold.
    MIN_LATENCY_SAMPLES = 20

    def record_latency(self, ms: float, alpha: float = 0.1) -> None:
        self._latency_samples += 1
        if self._latency_samples == 1:
            # Discard the very first sample entirely (cold start/compile).
            return
        prev = self.avg_latency_ms
        self.avg_latency_ms = ms if prev == 0 else (1 - alpha) * prev + alpha * ms

    def bandwidth_rpm(self) -> int:
        """Estimated sustainable requests/min of this copy: concurrency
        slots / average service time. 0 = unknown (insufficient latency
        data or no concurrency limit)."""
        if (
            self.avg_latency_ms <= 0
            or self.max_concurrency <= 0
            or self._latency_samples < self.MIN_LATENCY_SAMPLES
        ):
            return 0
        return int(60_000.0 / self.avg_latency_ms * self.max_concurrency)

    # -- state ------------------------------------------------------------

    def _transition_locked(self, new: EntryState) -> None:
        prev = self.state
        self.state = new
        if new.is_terminal:
            self._done.set()
        self._state_cv.notify_all()
        # Flight-recorder hook (the single funnel every transition takes):
        # the stripe lock nests INSIDE CacheEntry._lock and the recorder
        # never takes entry locks, so the edge is acyclic.
        rec = self.recorder
        if rec is not None:
            rec.record("state", model=self.model_id, frm=prev.value,
                       to=new.value)

    def try_transition(self, new: EntryState) -> bool:
        """Advance to a non-terminal loading state unless already terminal
        (e.g. REMOVED by a concurrent eviction). Loader threads must use this
        so eviction-during-load is never clobbered."""
        with self._lock:
            if self.state.is_terminal:
                return False
            self._transition_locked(new)
            return True

    def claim_chain_fire(self) -> bool:
        """Atomically claim the one-shot chained-fan-out trigger: True for
        exactly ONE caller across every path that can fire the chain
        (claim-time, ride-a-loading-entry, servable hit, completion) —
        a plain check-then-set raced when two async requests rode the
        same in-flight load."""
        with self._lock:
            if getattr(self, "_chain_fired", False):
                return False
            self._chain_fired = True
            return True

    def begin_partial(self, loaded: LoadedModel) -> bool:
        """Admit requests on a partially-streamed copy: install the
        (already-servable) provisional handle and move to PARTIAL. Returns
        False when the entry is already terminal (evicted/failed mid-
        stream) — the caller abandons the early-serve and lets the stream
        outcome decide. Idempotent-ish: a second call just refreshes the
        handle."""
        with self._lock:
            if self.state.is_terminal:
                return False
            self.loaded = loaded
            # Sticky: a provisional runtime copy is resident from here on,
            # even if a later eviction moves the STATE off PARTIAL — the
            # failure path keys its unload on this, not on the state.
            self.partial_started = True
            if loaded.max_concurrency and self._sem is None:
                self.max_concurrency = loaded.max_concurrency
                self._sem = threading.Semaphore(loaded.max_concurrency)
            self._transition_locked(EntryState.PARTIAL)
            return True

    def complete_load(self, loaded: LoadedModel) -> bool:
        """Finalize to ACTIVE unless removed meanwhile. Returns False if the
        entry was removed — caller must release the runtime copy."""
        return self._complete(loaded, EntryState.ACTIVE)

    def complete_shard(self, loaded: LoadedModel) -> bool:
        """Finalize a shard load to SHARDED (the sharded-execution analog
        of ``complete_load``). Returns False if the entry was removed —
        caller must release the runtime shard."""
        return self._complete(loaded, EntryState.SHARDED)

    def _complete(self, loaded: LoadedModel, final: EntryState) -> bool:
        with self._lock:
            if self.state.is_terminal:
                return False
            self.loaded = loaded
            self.load_completed_ms = now_ms()
            if loaded.max_concurrency and self._sem is None:
                # Keep a semaphore installed at PARTIAL time: requests may
                # already hold slots on it — swapping would leak permits.
                self.max_concurrency = loaded.max_concurrency
                self._sem = threading.Semaphore(loaded.max_concurrency)
            self._transition_locked(final)
            return True

    def fail(self, message: str) -> None:
        with self._lock:
            if self.state.is_terminal:
                return
            self.error = message
            self._transition_locked(EntryState.FAILED)

    def remove(self) -> None:
        with self._lock:
            self._transition_locked(EntryState.REMOVED)

    def wait_active(self, timeout_s: float) -> bool:
        """True if ACTIVE (or SHARDED) within the timeout; False on
        timeout. Raises ModelLoadException if the entry FAILED."""
        if not self._done.wait(timeout_s):
            return False
        if self.state is EntryState.FAILED:
            raise ModelLoadException(self.error or "load failed")
        return self.state in (EntryState.ACTIVE, EntryState.SHARDED)

    def await_transition(
        self, known: EntryState, timeout_s: float
    ) -> EntryState:
        """Event-driven wait: block until the state is no longer ``known``
        (any transition wakes us — the condition broadcasts on every
        advance) or the timeout elapses; returns the state seen on wake.
        Load waiters use this instead of a fixed-cadence poll, so wakeup
        latency is notification latency, not poll-interval slack."""
        with self._state_cv:
            if self.state is known and timeout_s > 0:
                get_clock().cond_wait(self._state_cv, timeout_s)
            return self.state

    # -- invocation gating ---------------------------------------------------

    def before_invoke(
        self, timeout_s: Optional[float] = None, cancel_event=None,
    ) -> bool:
        with self._lock:
            sem = self._sem
        if sem is not None:
            if cancel_event is None:
                if not sem.acquire(timeout=timeout_s or 30.0):
                    return False
            else:
                # Interruptible acquire: a cancelled client must stop
                # queueing for the slot immediately.
                import time as _t

                deadline = _t.monotonic() + (timeout_s or 30.0)  #: wall-clock: slices a REAL semaphore acquire at cancel-check cadence; the waker is a real thread's release, not virtual time
                acquired = False
                while not acquired:
                    if cancel_event.is_set():
                        return False
                    remaining = deadline - _t.monotonic()  #: wall-clock: same wall bound as above
                    if remaining <= 0:
                        return False
                    acquired = sem.acquire(timeout=min(0.05, remaining))
        with self._lock:
            self.inflight += 1
            self.total_invocations += 1
        return True

    def after_invoke(self) -> None:
        with self._lock:
            self.inflight -= 1
            sem = self._sem
        if sem is not None:
            sem.release()


class PrioritizedLoadingPool:
    """Fixed-thread pool draining a priority queue of load tasks.

    Priority: loads with a waiting request run before preemptive/chained
    loads; ties broken by most-recently-used (reference priority queue at
    ModelMesh.java:504, 2108-2116).
    """

    def __init__(self, concurrency: int = 8, name: str = "loader"):
        #: guarded-by: _cv
        self._heap: list[tuple[tuple, int, Callable[[], None]]] = []
        self._cv = mm_condition("PrioritizedLoadingPool._cv")
        self._seq = 0  #: guarded-by: _cv
        self._shutdown = False  #: guarded-by: _cv
        self._threads = [
            threading.Thread(target=self._run, name=f"{name}-{i}", daemon=True)
            for i in range(concurrency)
        ]
        for t in self._threads:
            t.start()

    def submit(
        self, task: Callable[[], None], *, urgent: bool, last_used: int
    ) -> None:
        key = (0 if urgent else 1, -last_used)
        with self._cv:
            if self._shutdown:
                raise RuntimeError("loading pool is shut down")
            self._seq += 1
            heapq.heappush(self._heap, (key, self._seq, task))
            self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._heap and not self._shutdown:
                    self._cv.wait()
                if self._shutdown and not self._heap:
                    return
                _, _, task = heapq.heappop(self._heap)
            try:
                task()
            except Exception:
                log.error("load task crashed:\n%s", traceback.format_exc())

    def shutdown(self, drain: bool = False) -> None:
        with self._cv:
            self._shutdown = True
            if not drain:
                self._heap.clear()
            self._cv.notify_all()


class UnloadTracker:
    """Accounting for in-flight unloads: evicted space isn't reusable until
    the runtime confirms release. Loads block on ``wait_for_space``.

    The reference implements this as a buffer entry inside the cache sharing
    the eviction lock; here the cache reports its own weight and we track
    the pending-unload units beside it — same invariant:
        cache_weight + pending_unload_units <= capacity_units.

    The reference's borrow/repay weight adjustment
    (ModelCacheUnloadBufManager.adjustNewEntrySpaceRequest:152 — revising a
    loading entry's space claim when sizing changes the estimate) has no
    separate mechanism here because the decomposition covers it: a mid-load
    grow goes through WeightedLRUCache.update_weight, which evicts others to
    keep cache_weight <= capacity; those evictions enter pending-unload
    accounting; and every later load re-checks ``wait_for_space`` at its own
    WAITING stage, so new work blocks until the displaced space is actually
    released. The transient accounting catch-up after an
    underestimated-then-loaded model is unavoidable in ANY design — the
    runtime has already physically allocated the real size by the time it is
    known — and the runtime's own capacity enforcement backstops it.
    """

    def __init__(self, capacity_units: int):
        self.capacity_units = capacity_units
        self._pending_units = 0  #: guarded-by: _cv
        self._cv = mm_condition("UnloadTracker._cv")

    @property
    def pending_units(self) -> int:
        return self._pending_units

    def unload_started(self, units: int) -> None:
        with self._cv:
            self._pending_units += units

    def unload_finished(self, units: int) -> None:
        with self._cv:
            self._pending_units = max(0, self._pending_units - units)
            self._cv.notify_all()

    def wait_for_space(
        self, cache_weight_fn: Callable[[], int], need_units: int,
        timeout_s: float = DEFAULT_SPACE_WAIT_S,
    ) -> bool:
        """Block until need_units fit beside cache weight + pending unloads."""
        clock = get_clock()
        deadline = clock.monotonic() + timeout_s
        with self._cv:
            while (
                cache_weight_fn() + self._pending_units + need_units
                > self.capacity_units
            ):
                remaining = deadline - clock.monotonic()
                if remaining <= 0:
                    return False
                clock.cond_wait(self._cv, min(remaining, 1.0))
            return True


def bytes_to_units(size_bytes: int) -> int:
    return max(1, (size_bytes + CACHE_UNIT_BYTES - 1) // CACHE_UNIT_BYTES)
