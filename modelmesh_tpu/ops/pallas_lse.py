"""Pallas TPU kernels for the Sinkhorn hot path: fused potential-shifted LSE.

The Sinkhorn loop's entire cost is 2 logsumexp passes over the bf16 cost
matrix per iteration (24 passes at 12 iterations — the dominant HBM traffic
of the whole solve at 100k x 1k). These kernels compute

    row_lse[n] = logsumexp_m (g[m] - C[n, m]) / eps        (row update)
    col_lse[m] = logsumexp_n (f[n] - C[n, m]) / eps        (column update)

as single tiled passes: C streams through VMEM in bf16 blocks, the shift
and scale fuse into the streaming online-LSE (running max + rescaled sum in
f32 scratch), and neither the shifted matrix ``z`` nor any f32 copy of C is
ever materialized in HBM. The XLA path (ops/sinkhorn.py) relies on fusion
heuristics for the same effect; the kernel makes the schedule explicit and
keeps the accumulators pinned in VMEM across the whole reduction.

Numerics match ops.sinkhorn's ``_row_lse``/``_col_lse`` (f32 accumulation
over bf16-read costs); parity is pinned by tests/test_pallas_lse.py in
interpret mode on CPU and holds on real TPUs by construction (same dtypes,
same reduction order up to tile-local reassociation).

Selection: ``sinkhorn(..., lse_impl="auto")`` uses these kernels on TPU
backends and the XLA path elsewhere (the interpreter is far slower than
XLA on CPU — interpret mode is for correctness, not speed).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tile sizes: multiples of the f32 (8, 128) / bf16 (16, 128) register tiles.
_TN = 256   # rows per block
_TM = 512   # cols per block
_NEG_BIG = -1.0e30  # padding shift value: exp() underflows to exactly 0


def _pad_to(x, mult, axis, value):
    n = x.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, rem)
    return jnp.pad(x, widths, constant_values=value)


def pad_cost(C: jax.Array) -> jax.Array:
    """Pad C to kernel tile multiples ONCE (callers loop over LSE passes;
    padding inside the loop would re-materialize the big matrix every
    iteration). _pad_to is a no-op on already-padded input, so the
    per-call pads below vanish for pre-padded matrices."""
    return _pad_to(_pad_to(C, _TN, 0, 0.0), _TM, 1, 0.0)


@functools.partial(
    jax.jit, static_argnames=("eps", "interpret", "valid_rows")
)
def row_lse(C: jax.Array, g: jax.Array, eps: float,
            interpret: bool = False,
            valid_rows: int | None = None) -> jax.Array:
    """logsumexp_m (g[m] - C[n, m]) / eps  -> f32[valid_rows or N].

    ``g`` has the ORIGINAL column count; pass ``valid_rows`` with a
    pre-padded C (pad_cost) to slice the live rows."""
    m, s = row_lse_partial(
        C, g, eps, interpret=interpret, valid_rows=valid_rows
    )
    return jnp.log(jnp.maximum(s, 1e-30)) + m


@functools.partial(
    jax.jit, static_argnames=("eps", "interpret", "valid_cols")
)
def col_lse(C: jax.Array, f: jax.Array, eps: float,
            interpret: bool = False,
            valid_cols: int | None = None) -> jax.Array:
    """logsumexp_n (f[n] - C[n, m]) / eps  -> f32[valid_cols or M]."""
    m, s = col_lse_partial(
        C, f, eps, interpret=interpret, valid_cols=valid_cols
    )
    return jnp.log(jnp.maximum(s, 1e-30)) + m


def _partial_kernel(shift_ref, c_ref, m_out, s_out, m_scr, s_scr, *,
                    inv_eps, axis):
    """THE online-LSE kernel: one (out-block, reduce-tile) step emitting the
    raw (running max, rescaled sum) pair. Single source of the
    accumulation math — the full LSE is ``log(max(s, eps0)) + m``
    (row_lse/col_lse wrappers), and the sharded combine is
    ``M = pmax(m); lse = log(psum(s * exp(m - M))) + M``.

    axis=1: reduce over columns (grid dim 1 iterates column tiles);
    axis=0: reduce over rows (grid dim 1 iterates row tiles). The reduced
    axis is always grid dim 1 so the scratch persists across it."""
    step = pl.program_id(1)

    @pl.when(step == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        s_scr[:] = jnp.zeros_like(s_scr)

    c = c_ref[:].astype(jnp.float32)
    if axis == 1:
        z = (shift_ref[:] - c) * inv_eps
        m_tile = jnp.max(z, axis=1, keepdims=True)
    else:
        z = (shift_ref[:] - c) * inv_eps
        m_tile = jnp.max(z, axis=0, keepdims=True)
    m_old = m_scr[:]
    m_new = jnp.maximum(m_old, m_tile)
    s_scr[:] = s_scr[:] * jnp.exp(m_old - m_new) + jnp.sum(
        jnp.exp(z - m_new), axis=axis, keepdims=True
    )
    m_scr[:] = m_new

    @pl.when(step == pl.num_programs(1) - 1)
    def _finalize():
        m_out[:] = m_scr[:]
        s_out[:] = s_scr[:]


@functools.partial(
    jax.jit, static_argnames=("eps", "interpret", "valid_rows")
)
def row_lse_partial(C: jax.Array, g: jax.Array, eps: float,
                    interpret: bool = False,
                    valid_rows: int | None = None):
    """Per-shard partial row reduction -> (m, s) f32[valid_rows] pair.

    ``logsumexp = log(s) + m`` after combining shards (pmax/psum)."""
    n = valid_rows if valid_rows is not None else C.shape[0]
    Cp = pad_cost(C)
    gp = _pad_to(g.astype(jnp.float32), _TM, 0, _NEG_BIG).reshape(1, -1)
    np_, mp = Cp.shape
    m_out, s_out = pl.pallas_call(
        functools.partial(_partial_kernel, inv_eps=1.0 / eps, axis=1),
        grid=(np_ // _TN, mp // _TM),
        in_specs=[
            pl.BlockSpec((1, _TM), lambda i, j: (0, j)),
            pl.BlockSpec((_TN, _TM), lambda i, j: (i, j)),
        ],
        out_specs=[
            pl.BlockSpec((_TN, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((_TN, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((np_, 1), jnp.float32),
            jax.ShapeDtypeStruct((np_, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_TN, 1), jnp.float32),
            pltpu.VMEM((_TN, 1), jnp.float32),
        ],
        interpret=interpret,
    )(gp, Cp)
    return m_out[:n, 0], s_out[:n, 0]


@functools.partial(
    jax.jit, static_argnames=("eps", "interpret", "valid_cols")
)
def col_lse_partial(C: jax.Array, f: jax.Array, eps: float,
                    interpret: bool = False,
                    valid_cols: int | None = None):
    """Per-shard partial column reduction -> (m, s) f32[valid_cols] pair."""
    m = valid_cols if valid_cols is not None else C.shape[1]
    Cp = pad_cost(C)
    fp = _pad_to(f.astype(jnp.float32), _TN, 0, _NEG_BIG).reshape(-1, 1)
    np_, mp = Cp.shape
    m_out, s_out = pl.pallas_call(
        functools.partial(_partial_kernel, inv_eps=1.0 / eps, axis=0),
        grid=(mp // _TM, np_ // _TN),
        in_specs=[
            pl.BlockSpec((_TN, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((_TN, _TM), lambda j, i: (i, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, _TM), lambda j, i: (0, j)),
            pl.BlockSpec((1, _TM), lambda j, i: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, mp), jnp.float32),
            jax.ShapeDtypeStruct((1, mp), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, _TM), jnp.float32),
            pltpu.VMEM((1, _TM), jnp.float32),
        ],
        interpret=interpret,
    )(fp, Cp)
    return m_out[0, :m], s_out[0, :m]
