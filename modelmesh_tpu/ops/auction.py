"""Integral rounding of the Sinkhorn soft plan: Gumbel-top-k + price repair.

Rounding strategy (all vectorized, one ``lax.scan``, no per-model loops):

1. **Gumbel-top-k sampling.** The Sinkhorn plan logits define, per model, a
   distribution over instances whose *column* marginals already respect
   capacity shares. Adding Gumbel noise and taking the top-``copies`` per row
   draws distinct instances approximately proportional to that distribution —
   so the *expected* instance load already matches capacity. This is the key
   de-herding device: deterministic argmax would send near-identical rows to
   the same instance; sampling spreads them like the soft plan says to.

2. **Price repair.** Residual sampling variance (and anything the soft plan
   got wrong) is cleaned up by a few dozen rounds of congestion pricing:
   instances above capacity raise their price, below-capacity prices decay.
   Synchronous batched dynamics limit-cycle rather than converge (the
   cobweb pattern), so the loop tracks the minimum-overflow price vector
   seen and the final selection uses it — constant step + best-iterate
   beats annealing here. Bertsekas-auction flavor.

The result is *advisory*: per-instance local guards (churn age, unload buffer
accounting — serving layer) remain authoritative, exactly as SURVEY.md
section 7 "hard parts" #4 prescribes.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

# Max copies of a single model the solver will place (reference scales copies
# per request load; the per-round top-k width bounds it).
MAX_COPIES: int = 8
# Candidate shortlist for the price loop: a full-width top-k narrows each
# row to its K_CAND best instances AT CURRENT PRICES, then price iterations
# work on the [N, K_CAND] block — at 100k x 1k this cuts the loop's HBM
# traffic ~30x. Prices move BETWEEN rows' rankings (a priced-out shortlist
# can make rank-33 the true argmax), so the shortlist is recomputed every
# RESHORTLIST_EVERY iterations: spill targets enter as prices rise. The
# returned assignment is the better of (a) a full-width exact top-k at the
# final prices and (b) the best-overflow assignment recorded during the
# narrow rounds.
K_CAND: int = 4 * MAX_COPIES
RESHORTLIST_EVERY: int = 8

_NEG_INF = -1.0e9
_JITTER_KEY = 0x5EED


class AuctionResult(NamedTuple):
    indices: jax.Array   # i32[N, MAX_COPIES] chosen instance per copy slot
    valid: jax.Array     # bool[N, MAX_COPIES] slot is a real, feasible pick
    load: jax.Array      # f32[M] implied memory load of the assignment
    prices: jax.Array    # f32[M] prices the returned assignment was
                         # selected at (the warm-start carry for the next
                         # refresh's price0 — best-iterate, NOT last-
                         # iterate: last prices are mid-cobweb and
                         # re-selecting at them can overflow ~100x worse)
    overflow: jax.Array  # f32[] sum of capacity overflow (diagnostic)
    # i32[] price iterations actually run (== iters when stall_tol=0; an
    # early-exit solve — warm prices converge immediately — reports fewer).
    iters_run: jax.Array = None


def _finalize_topk(vals, idx, copies):
    """Shared epilogue: pad to MAX_COPIES slots + validity mask."""
    k = vals.shape[1]
    if k < MAX_COPIES:
        pad = ((0, 0), (0, MAX_COPIES - k))
        vals = jnp.pad(vals, pad, constant_values=_NEG_INF)
        idx = jnp.pad(idx, pad)
    slot = jnp.arange(MAX_COPIES, dtype=jnp.int32)[None, :]
    valid = (slot < copies[:, None]) & (vals > _NEG_INF / 2)
    return idx, valid


def select_from_candidates(cand_vals, cand_idx, copies, price,
                           sel_k: int = MAX_COPIES):
    """Top-``sel_k`` within a row's candidate shortlist at ``price``,
    padded to the MAX_COPIES output slots (``_finalize_topk``).

    ``cand_vals`` holds RAW scores (no price baked in) so the selection is
    exact for any later price vector. Shared by the dense narrow rounds
    AND the sparse top-K auction — the parity-critical epilogue must not
    fork. ``sel_k`` < MAX_COPIES narrows the per-iteration top-k when the
    problem's real max copy count allows it (the sparse dispatch layer
    derives it from the snapshot; callers must keep ``sel_k >=
    max(copies)`` or high-copy rows silently lose slots)."""
    eff = cand_vals - price[cand_idx]                    # [N, kc]
    k = min(min(sel_k, MAX_COPIES), eff.shape[1])
    vals, pos = jax.lax.top_k(eff, k)
    return _finalize_topk(
        vals, jnp.take_along_axis(cand_idx, pos, axis=1), copies
    )


def shortlist(scores: jax.Array, price: jax.Array, kc: int):
    """Row shortlist at current prices; returns (raw_vals, idx).

    approx_max_k: the shortlist is approximate BY DESIGN (it's refreshed
    every RESHORTLIST_EVERY iterations and the final selection is exact),
    and the approximate variant maps onto far cheaper TPU code than a
    bitonic full sort."""
    _, idx = jax.lax.approx_max_k(scores - price[None, :], kc)
    return jnp.take_along_axis(scores, idx, axis=1), idx


def _select(scores_minus_price: jax.Array, copies: jax.Array):
    """Full-width exact top-MAX_COPIES per row + validity mask.

    Clusters smaller than MAX_COPIES instances still return MAX_COPIES-wide
    results (padded invalid) so output shapes are static.
    """
    k = min(MAX_COPIES, scores_minus_price.shape[1])
    vals, idx = jax.lax.top_k(scores_minus_price, k)  # [N, k]
    return _finalize_topk(vals, idx, copies)


# Flat (idx, weight) entries per scan step of the fused histogram. The
# [_FUSED_CHUNK, M] one-hot comparison is an XLA input fusion into the
# reduction — it never materializes — so the step size only bounds the
# fusion's working set, not HBM traffic.
_FUSED_CHUNK = 8192


def resolve_load_impl(impl: str) -> str:
    """Validate + resolve "auto" for the implied-load implementation.

    "scatter" is the natural formulation and fast on CPU/GPU; on TPU a
    1M-entry scatter-add with duplicate indices lowers to a serialized
    update path that can dominate the whole solve (the same reason
    embedding gradients on TPU are classically expressed as one-hot
    matmuls), so "auto" picks the fused compare-reduce there."""
    if impl not in ("auto", "scatter", "fused"):
        raise ValueError(f"load_impl={impl!r} (expected auto | scatter | fused)")
    if impl != "auto":
        return impl
    return "fused" if jax.default_backend() == "tpu" else "scatter"


def _implied_load_fused(
    idx: jax.Array, valid: jax.Array, sizes: jax.Array, num_instances: int
) -> jax.Array:
    """Scatter-free histogram: chunked one-hot compare-reduce.

    Each scan step reduces a [chunk, M] on-the-fly comparison block; XLA
    fuses the broadcasted equality into the reduction so the block never
    hits HBM. Compute is O(N·K·M) VPU ops — bandwidth-trivial, and immune
    to the duplicate-index serialization that makes TPU scatter-add slow."""
    if idx.size == 0:  # zero-model problem: nothing contributes
        return jnp.zeros((num_instances,), jnp.float32)
    contrib = sizes[:, None] * valid.astype(jnp.float32)  # [N, K]
    flat_idx = idx.reshape(-1).astype(jnp.int32)
    flat_w = contrib.reshape(-1)
    s = flat_idx.shape[0]
    chunk = min(_FUSED_CHUNK, s)
    pad = (-s) % chunk
    if pad:
        # Padded entries point one past the column range: they match no
        # iota column and contribute nothing (weight 0 besides).
        flat_idx = jnp.pad(flat_idx, (0, pad), constant_values=num_instances)
        flat_w = jnp.pad(flat_w, (0, pad))
    cols = jnp.arange(num_instances, dtype=jnp.int32)

    def body(acc, xs):
        ic, wc = xs
        acc = acc + jnp.sum(
            jnp.where(ic[:, None] == cols[None, :], wc[:, None], 0.0), axis=0
        )
        return acc, None

    acc, _ = jax.lax.scan(
        body,
        jnp.zeros((num_instances,), jnp.float32),
        (flat_idx.reshape(-1, chunk), flat_w.reshape(-1, chunk)),
    )
    return acc


def _implied_load(
    idx: jax.Array,
    valid: jax.Array,
    sizes: jax.Array,
    num_instances: int,
    impl: str = "scatter",
) -> jax.Array:
    # "auto" is resolved ONCE at the solver entry points (auction /
    # _sharded_auction); this private helper takes only concrete impls.
    if impl not in ("scatter", "fused"):
        raise ValueError(f"unresolved load impl {impl!r}")
    if impl == "fused":
        return _implied_load_fused(idx, valid, sizes, num_instances)
    contrib = sizes[:, None] * valid.astype(jnp.float32)  # [N, K]
    return (
        jnp.zeros((num_instances,), jnp.float32)
        .at[idx.reshape(-1)]
        .add(contrib.reshape(-1))
    )


def check_rounding_config(noise_impl: str, final_select: str, iters: int):
    """Validate the rounding knobs once, shared by both solvers (the
    single-device and sharded epilogues must behave identically)."""
    if noise_impl not in ("threefry", "hash"):
        raise ValueError(
            f"noise_impl={noise_impl!r} (expected threefry | hash)"
        )
    if final_select not in ("exact", "approx", "none"):
        raise ValueError(
            f"final_select={final_select!r} (expected exact | approx | none)"
        )
    if final_select == "none" and iters < 1:
        # The best-iterate carry would still hold the inf/zeros sentinel.
        raise ValueError("final_select='none' requires iters >= 1")


def final_candidate(scores_minus_price, copies, final_select: str):
    """Epilogue competitor to the best price iterate — shared by both
    solvers so the parity-critical selection cannot drift."""
    if final_select == "approx":
        k = min(MAX_COPIES, scores_minus_price.shape[1])
        vals, idx = jax.lax.approx_max_k(scores_minus_price, k)
        return _finalize_topk(vals, idx, copies)
    return _select(scores_minus_price, copies)


def warm_probe(select_fn, p_init, cap,
               load_fn, eta_eff, stall_tol: float, total_demand):
    """Single-step warm probe shared by ``auction``,
    ``parallel/sharded_solver._sharded_auction`` and the sparse top-k
    solver (parameterized by the selection and load callbacks so the gate
    arithmetic — overflow noise floor, price-stall condition — cannot
    drift between them).

    ``select_fn(price)`` is one epilogue-grade selection at that price:
    full-width ``final_candidate`` for the dense solvers (in the
    configured ``final_select`` mode, so "approx" tiers never pay the
    exact top-k it exists to avoid), candidate-limited for the sparse
    path. One selection at the carried prices, one price step.
    ``probe_ok`` certifies the carry: the step stalled, or the overflow
    is already below the stall noise floor (``stall_tol`` of total
    demand — the same threshold the round loop treats as a
    non-improvement). ``load_fn`` is the plain implied-load histogram on
    a single device and the psum'd one on a mesh — with psum'd
    load/demand every probe scalar is replicated, so all devices take
    the same cond branch. Returns
    (idx_p, valid_p, load_p, of_p, p_probe, probe_ok)."""
    of_tol = stall_tol * jnp.maximum(total_demand, 1e-30)
    idx_p, valid_p = select_fn(p_init)
    load_p = load_fn(idx_p, valid_p)
    of_p = jnp.sum(jnp.maximum(load_p - cap, 0.0))
    p_probe = price_step(load_p, cap, p_init, eta_eff)
    dprice = jnp.max(jnp.abs(p_probe - p_init))
    probe_ok = (dprice <= stall_tol) | (of_p <= of_tol)
    return idx_p, valid_p, load_p, of_p, p_probe, probe_ok


def hash_gumbel_at(
    rows: jax.Array, cols: jax.Array, seed: jax.Array
) -> jax.Array:
    """Gumbel(0, 1) at EXPLICIT (row, col) counter positions.

    The value is a pure function of (row, col, seed), so a gathered
    evaluation at scattered column ids — the sparse top-k path, the
    incremental dirty-row re-solve — reproduces ``hash_gumbel(shape)[i, j]``
    bit-for-bit at every (i, j) it touches. That identity is what lets the
    sparse/incremental solvers keep the dense path's frozen noise epoch:
    re-selecting a row under the same seed sees the same draw regardless
    of which solver evaluates it."""
    rows = rows.astype(jnp.uint32)
    cols = cols.astype(jnp.uint32)

    def fmix32(v):
        # murmur3 finalizer: full avalanche, pure VPU integer ops.
        v ^= v >> 16
        v *= jnp.uint32(0x85EBCA6B)
        v ^= v >> 13
        v *= jnp.uint32(0xC2B2AE35)
        v ^= v >> 16
        return v

    # Mix rows before cols touch the counter: a single linear combination
    # rows*c1 + cols*c2 + seed*c3 repeats along any lattice direction with
    # dr*c1 + dc*c2 == 0 (mod 2^32), putting identical noise on whole
    # diagonals at large tiers. The intermediate fmix32 breaks additivity,
    # and the value still depends only on (global row, col, seed) so the
    # sharded-equals-single-device property is preserved.
    x = fmix32(rows ^ (jnp.asarray(seed, jnp.uint32) * jnp.uint32(0xC2B2AE35)))
    x = fmix32(x ^ (cols * jnp.uint32(0x85EBCA6B)))
    # Top 24 bits -> uniform in [eps, 1) (0 would blow up the outer log).
    u = (x >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
    u = jnp.maximum(u, 1e-7)
    return -jnp.log(-jnp.log(u))


def hash_gumbel(
    shape: tuple[int, int],
    seed: jax.Array,
    row_offset: jax.Array | int = 0,
) -> jax.Array:
    """Counter-based Gumbel(0, 1) noise: murmur3-finalizer mixing of the
    (global row, col, seed) counter, bitcast to uniform, double-log map.

    Statistically ample for de-herding top-k draws (the only consumer),
    and much cheaper than threefry on a 1e8-element matrix. ``row_offset``
    makes a sharded block's noise equal the corresponding rows of the
    full-matrix draw — single-device and sharded solves see IDENTICAL
    noise for the same seed, which threefry's fold_in cannot offer."""
    rows = jax.lax.broadcasted_iota(jnp.uint32, shape, 0) + jnp.asarray(
        row_offset, jnp.uint32
    )
    cols = jax.lax.broadcasted_iota(jnp.uint32, shape, 1)
    return hash_gumbel_at(rows, cols, seed)


def gumbel_perturb(
    scores: jax.Array,
    tau: float,
    seed: jax.Array,
    impl: str = "threefry",
    row_offset: jax.Array | int = 0,
) -> jax.Array:
    """Add Gumbel(0, tau) noise so top-k draws ~ softmax(scores / tau).

    ``seed`` is a *traced* int32 scalar — callers vary it per solve (janitor
    pass counter) without triggering a recompile. ``impl``: "threefry" uses
    the JAX PRNG; "hash" the cheap counter-based draw (hash_gumbel).
    """
    if impl not in ("threefry", "hash"):
        raise ValueError(f"noise impl {impl!r} (expected threefry | hash)")
    if impl == "hash":
        g = hash_gumbel(scores.shape, seed, row_offset)
    else:
        g = jax.random.gumbel(jax.random.PRNGKey(seed), scores.shape)
    return scores.astype(jnp.float32) + tau * g


def price_step(load, cap, price, eta_t):
    """One synchronous congestion-price update (shared with sharded solver).

    Rise with clipped overload pressure; decay gently when under 90% full.
    """
    pressure = load / cap - 1.0
    step = jnp.where(
        pressure > 0,
        jnp.clip(pressure, 0.0, 2.0),
        0.25 * jnp.minimum(pressure + 0.1, 0.0),
    )
    return jnp.clip(price + eta_t * step, 0.0, None)


def _stall_gated_rounds(narrow_round, carry, iters: int, stall_tol: float,
                        total_demand):
    """Convergence-gated round loop, shared by both solvers.

    Runs rounds of RESHORTLIST_EVERY price iterations under a
    ``lax.while_loop`` (each round body is the same fixed-length scan the
    unrolled path uses, so the compiled program stays stable) and exits
    once a full round stalls on ANY of:

    - price movement <= stall_tol: the selection depends on state only
      through prices, so a round that left them (essentially) in place
      proves further rounds would reproduce themselves. This is the
      warm-start fast exit — carried-in prices are already at equilibrium
      and round one confirms it.
    - best overflow hit zero: the loop minimizes overflow; there is
      nothing left to repair.
    - best-overflow improvement <= stall_tol * total_demand: prices are
      limit-cycling (the cobweb pattern) without finding better
      assignments — the cold-side exit. Guarded against the first round's
      inf sentinel, which would read as zero improvement.

    Returns (carry, iterations_run)."""
    n_rounds = -(-iters // RESHORTLIST_EVERY)
    of_tol = stall_tol * jnp.maximum(total_demand, 1e-30)

    def cond(state):
        rnd, stalled, _carry = state
        return (~stalled) & (rnd < n_rounds)

    def body(state):
        rnd, _stalled, carry = state
        # Positional unpack kept loose: both solvers' carries lead with the
        # price vector and end with the best overflow (what sits in between
        # — best assignment, best prices — is the caller's business).
        price_in, bo_in = carry[0], carry[-1]
        carry = narrow_round(carry, RESHORTLIST_EVERY)
        price_out, bo_out = carry[0], carry[-1]
        dprice = jnp.max(jnp.abs(price_out - price_in))
        improved = jnp.where(jnp.isinf(bo_in), jnp.inf, bo_in - bo_out)
        stalled = (
            (dprice <= stall_tol)
            | (bo_out <= 0.0)
            | (improved <= of_tol)
        )
        return rnd + 1, stalled, carry

    rnd, _stalled, carry = jax.lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), jnp.asarray(False), carry)
    )
    return carry, rnd * RESHORTLIST_EVERY


@partial(
    jax.jit,
    static_argnames=(
        "iters", "eta", "price_scale", "tau", "load_impl", "noise_impl",
        "final_select", "stall_tol",
    ),
)
def auction(
    scores: jax.Array,      # [N, M] plan logits, higher is better (bf16 ok)
    sizes: jax.Array,       # f32[N]
    copies: jax.Array,      # i32[N]
    capacity: jax.Array,    # f32[M]
    feasible: jax.Array,    # bool[N, M]
    seed: jax.Array | int = _JITTER_KEY,  # traced: varying it never retraces
    *,
    iters: int = 40,
    eta: float = 0.5,
    price_scale: float = 1.0,
    tau: float = 1.0,
    load_impl: str = "auto",
    noise_impl: str = "hash",
    final_select: str = "exact",
    stall_tol: float = 0.0,
    price0: jax.Array | None = None,
) -> AuctionResult:
    """Gumbel-top-k sampling + best-iterate congestion-price repair.

    ``price_scale`` converts prices into score units; with Sinkhorn plan
    logits the useful spread is O(1), so the default 1.0 is right — the
    per-iteration step is ``eta * price_scale * clip(overload)``.

    ``noise_impl``: "hash" (default: cheap counter-based draw, identical
    across topologies) or "threefry" (JAX PRNG). ``final_select``: how
    the epilogue competes with the tracked
    best-iterate assignment — "exact" full-width top-k, "approx"
    approx_max_k (cheaper on TPU, recall ~0.95), "none" skips the
    epilogue candidate entirely and returns the best iterate.

    ``price0`` warm-starts the congestion prices from the previous
    refresh's last iterate (steady-state churn barely moves the price
    equilibrium, so warm prices are a round from converged). ``stall_tol``
    > 0 enables early exit: rounds of RESHORTLIST_EVERY price iterations
    run under a ``lax.while_loop`` and the loop stops once a whole round
    neither moved prices more than ``stall_tol`` (price units) nor
    improved the best overflow by more than ``stall_tol`` of total demand
    — further rounds would reproduce the same iterates. A one-step probe
    at the carried prices runs first: when it stalls, or its overflow is
    already below ``stall_tol`` of total demand (the round loop's own
    noise floor), the probe's full-width selection is returned directly
    with ``iters_run == 1`` — the steady-state warm-price fast exit. The
    ``iters`` budget rounds up to probe + whole rounds in this mode.
    ``final_select="none"`` skips the probe (it is itself a full-width
    selection, exactly what "none" avoids) and gates the rounds only.
    """
    check_rounding_config(noise_impl, final_select, iters)
    num_instances = capacity.shape[0]
    seed = jnp.asarray(seed, jnp.uint32)
    scores_f32 = (
        gumbel_perturb(scores, tau, seed, impl=noise_impl)
        if tau > 0 else scores.astype(jnp.float32)
    )
    scores_f32 = jnp.where(feasible, scores_f32, _NEG_INF)
    cap = jnp.maximum(capacity.astype(jnp.float32), 1e-6)
    copies = jnp.minimum(copies, MAX_COPIES)

    # Synchronous price dynamics oscillate (every row reacts to the same
    # prices at once, so an over-full column can empty and refill — the
    # cobweb pattern). Rather than hoping the LAST iterate is good, track
    # the best-overflow ASSIGNMENT seen (the selection itself, not just its
    # price — a narrow-round selection can be feasible at a price whose
    # full-width argmax herds, so re-deriving from the price would lose it).
    kc = min(K_CAND, num_instances)
    n = scores_f32.shape[0]
    load_impl = resolve_load_impl(load_impl)

    def narrow_round(carry, length):
        price, best_price, best_idx, best_valid, best_load, best_of = carry
        cand_vals, cand_idx = shortlist(scores_f32, price, kc)

        def body(carry, _):
            price, bp, bi, bv, bl, bo = carry
            idx, valid = select_from_candidates(
                cand_vals, cand_idx, copies, price
            )
            load = _implied_load(idx, valid, sizes, num_instances, load_impl)
            of = jnp.sum(jnp.maximum(load - cap, 0.0))
            better = of < bo
            # Track the price the best assignment was SELECTED at — the
            # warm-start carry. Last-iterate prices are mid-cobweb (the
            # synchronous dynamics limit-cycle) and re-selecting at them
            # can overflow ~100x worse than the best iterate.
            bp = jnp.where(better, price, bp)
            bi = jnp.where(better, idx, bi)
            bv = jnp.where(better, valid, bv)
            bl = jnp.where(better, load, bl)
            bo = jnp.minimum(of, bo)
            return (
                price_step(load, cap, price, eta * price_scale),
                bp, bi, bv, bl, bo,
            ), None

        carry, _ = jax.lax.scan(body, carry, None, length=length)
        return carry

    p_init = (
        jnp.maximum(price0.astype(jnp.float32), 0.0)  # price >= 0 invariant
        if price0 is not None
        else jnp.zeros((num_instances,), jnp.float32)
    )
    carry = (
        p_init,
        p_init,
        jnp.zeros((n, MAX_COPIES), jnp.int32),
        jnp.zeros((n, MAX_COPIES), bool),
        jnp.zeros((num_instances,), jnp.float32),
        jnp.asarray(jnp.inf, jnp.float32),
    )
    def epilogue(carry, iters_run):
        # One full-width selection at the final prices competes with the
        # best recorded assignment; whichever overflows less wins. The
        # winner's load rides the carry — no histogram recompute here —
        # and the returned prices are the ones the WINNING assignment was
        # selected at (the warm-start carry the next refresh probes).
        price, best_price, best_idx, best_valid, best_load, best_of = carry
        if final_select == "none":
            # With iters >= 1 the first narrow round always improves on
            # the inf sentinel, so the best-iterate carry is a real
            # assignment.
            return AuctionResult(
                indices=best_idx, valid=best_valid, load=best_load,
                prices=best_price, overflow=best_of, iters_run=iters_run,
            )
        idx_l, valid_l = final_candidate(
            scores_f32 - price[None, :], copies, final_select
        )
        load_l = _implied_load(idx_l, valid_l, sizes, num_instances,
                               load_impl)
        of_l = jnp.sum(jnp.maximum(load_l - cap, 0.0))
        use_last = of_l <= best_of
        idx = jnp.where(use_last, idx_l, best_idx)
        valid = jnp.where(use_last, valid_l, best_valid)
        load = jnp.where(use_last, load_l, best_load)
        overflow = jnp.minimum(of_l, best_of)
        return AuctionResult(
            indices=idx, valid=valid, load=load,
            prices=jnp.where(use_last, price, best_price),
            overflow=overflow, iters_run=iters_run,
        )

    if stall_tol <= 0.0:
        # Honor `iters` exactly: full rounds of RESHORTLIST_EVERY plus one
        # partial round for the remainder.
        for length in [RESHORTLIST_EVERY] * (iters // RESHORTLIST_EVERY) + (
            [iters % RESHORTLIST_EVERY] if iters % RESHORTLIST_EVERY else []
        ):
            carry = narrow_round(carry, length)
        return epilogue(carry, jnp.asarray(iters, jnp.int32))

    total_demand = jnp.sum(sizes * copies.astype(jnp.float32))
    if final_select == "none":
        # "none" exists to keep full-width selections out of huge tiers,
        # and the warm probe below IS one — so this mode goes straight to
        # the stall-gated rounds and keeps its best-iterate-only contract
        # (the round loop still early-exits on the price/overflow gates).
        carry2, iters_run = _stall_gated_rounds(
            narrow_round, carry, iters, stall_tol, total_demand,
        )
        return epilogue(carry2, iters_run)

    # Stall-gated path: a single-step warm probe first (warm_probe — the
    # selection is exactly what the epilogue would compute). When it
    # certifies the carry, the probe's assignment IS the answer and the
    # solve exits after ONE price iteration — no shortlist, no narrow
    # rounds, no duplicate epilogue selection. Cold zero prices herd the
    # full-width argmax, fail the probe, and fall into the round loop
    # with the probe's assignment seeding the best-iterate carry
    # (replacing the inf sentinel — the first round's improvement test
    # becomes real).
    idx_p, valid_p, load_p, of_p, p_probe, probe_ok = warm_probe(
        lambda p: final_candidate(
            scores_f32 - p[None, :], copies, final_select
        ),
        p_init, cap,
        lambda i, v: _implied_load(i, v, sizes, num_instances, load_impl),
        eta * price_scale, stall_tol, total_demand,
    )

    def _probe_exit(_):
        # Return the STEPPED prices, not p_init: steady-state drift then
        # keeps nudging the carry toward the current load pattern instead
        # of freezing it, and with of_p under the noise floor the step is
        # tiny anyway.
        return AuctionResult(
            indices=idx_p, valid=valid_p, load=load_p, prices=p_probe,
            overflow=of_p, iters_run=jnp.asarray(1, jnp.int32),
        )

    def _rounds(_):
        seeded = (p_probe, p_init, idx_p, valid_p, load_p, of_p)
        carry2, iters_run = _stall_gated_rounds(
            narrow_round, seeded, iters, stall_tol, total_demand,
        )
        return epilogue(carry2, iters_run + 1)

    return jax.lax.cond(probe_ok, _probe_exit, _rounds, None)
