"""Log-domain Sinkhorn iterations for the placement transport prior.

Solves the entropically-regularized optimal transport between model copy-mass
(rows: ``copies * sizes``) and instance capacity (columns) over the placement
cost matrix from ops.costs. The output potentials define a soft assignment
``P = exp((f + g - C) / eps)`` used as the score prior for integral rounding
(ops.auction).

TPU notes: the cost matrix stays bf16 in HBM (bandwidth is the bottleneck at
100k x 1k and above); all potentials and log-sum-exp accumulation are f32.
The loop is a ``lax.scan`` so the whole solve is one XLA program; no
data-dependent Python control flow (fixed iteration count — this is a prior,
not an exact solve, so tight convergence is unnecessary).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class SinkhornResult(NamedTuple):
    f: jax.Array        # f32[N] row potentials
    g: jax.Array        # f32[M] column potentials
    row_err: jax.Array  # f32[] final L1 row-marginal error (diagnostic)


def _row_lse(C: jax.Array, g: jax.Array, eps: float) -> jax.Array:
    """logsumexp_i (g[i] - C[m, i]) / eps  -> f32[N]."""
    z = (g[None, :] - C.astype(jnp.float32)) / eps
    return jax.nn.logsumexp(z, axis=1)


def _col_lse(C: jax.Array, f: jax.Array, eps: float) -> jax.Array:
    """logsumexp_m (f[m] - C[m, i]) / eps  -> f32[M]."""
    z = (f[:, None] - C.astype(jnp.float32)) / eps
    return jax.nn.logsumexp(z, axis=0)


def resolve_lse_impl(lse_impl: str) -> str:
    """Validate + resolve "auto": pallas only when BOTH the default
    backend and any explicit default-device override point at TPU (a CPU
    default_device on a TPU host compiles the program for CPU, where a
    Mosaic kernel cannot lower). Shared with the sharded solver."""
    if lse_impl not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"lse_impl={lse_impl!r} (expected auto | xla | pallas)"
        )
    if lse_impl != "auto":
        return lse_impl
    dd = jax.config.jax_default_device
    on_tpu = jax.default_backend() == "tpu" and (
        dd is None or getattr(dd, "platform", "tpu") == "tpu"
    )
    return "pallas" if on_tpu else "xla"


@partial(jax.jit, static_argnames=("eps", "iters", "lse_impl"))
def sinkhorn(
    C: jax.Array,
    row_mass: jax.Array,
    col_mass: jax.Array,
    *,
    eps: float = 0.05,
    iters: int = 12,
    lse_impl: str = "auto",
    g0: jax.Array | None = None,
) -> SinkhornResult:
    """Semi-unbalanced log-domain Sinkhorn: rows are equalities (every
    model's copy-mass must place), columns are CAPS.

    The column update clamps ``g <= 0``: a column whose demand at g=0 is
    below its capacity keeps g = 0 (no subsidy to fill slack), one whose
    demand exceeds capacity gets the usual negative potential pushing mass
    away. Capacity-as-quota (the balanced form) would force every column to
    absorb its proportional share even when the whole fleet prefers a
    subset — nullifying cost-pool preferences (the `preferred` label term)
    whenever there is slack, which is most of the time.

    ``g0`` warm-starts the column potentials (SURVEY.md section 7 hard
    part #4: incremental solves as state churns). Between consecutive
    refreshes the problem barely moves, so the last solve's g is a few
    iterations from the new fixed point — the same iteration budget
    converges tighter. Only g needs carrying: the first iteration derives
    f entirely from g, so a row-potential input would be dead code.
    """
    row_mass = row_mass.astype(jnp.float32)
    col_mass = col_mass.astype(jnp.float32)
    log_a = jnp.log(jnp.maximum(row_mass, 1e-30))
    log_b = jnp.log(jnp.maximum(col_mass, 1e-30))

    # LSE backend: the Pallas kernels (ops/pallas_lse.py) pin the online
    # reduction in VMEM on TPU; XLA's fused reduction everywhere else.
    # Explicit "pallas" off-TPU runs the kernels under the interpreter
    # (slow, for testing the REAL selection path) rather than crashing in
    # Mosaic lowering for a backend that doesn't exist.
    use_pallas = resolve_lse_impl(lse_impl) == "pallas"
    if use_pallas:
        from modelmesh_tpu.ops import pallas_lse

        interp = jax.default_backend() != "tpu"
        n_rows, n_cols = C.shape
        Cp = pallas_lse.pad_cost(C)  # ONCE, outside the scan
        row_fn = lambda _C, g_: pallas_lse.row_lse(   # noqa: E731
            Cp, g_, eps, interpret=interp, valid_rows=n_rows
        )
        col_fn = lambda _C, f_: pallas_lse.col_lse(   # noqa: E731
            Cp, f_, eps, interpret=interp, valid_cols=n_cols
        )
    else:
        row_fn = lambda C_, g_: _row_lse(C_, g_, eps)  # noqa: E731
        col_fn = lambda C_, f_: _col_lse(C_, f_, eps)  # noqa: E731

    def body(carry, _):
        f, g = carry
        f = eps * (log_a - row_fn(C, g))
        g = jnp.minimum(0.0, eps * (log_b - col_fn(C, f)))
        return (f, g), None

    f_init = jnp.zeros_like(log_a)
    g_init = (
        jnp.minimum(0.0, g0.astype(jnp.float32))  # g <= 0 invariant
        if g0 is not None else jnp.zeros_like(log_b)
    )
    (f, g), _ = jax.lax.scan(body, (f_init, g_init), None, length=iters)

    # Diagnostic: row-marginal violation of the implied plan.
    row_sum = jnp.exp((f + eps * row_fn(C, g)) / eps)
    row_err = jnp.mean(jnp.abs(row_sum - row_mass)) / jnp.maximum(
        jnp.mean(row_mass), 1e-30
    )
    return SinkhornResult(f=f, g=g, row_err=row_err)


def plan_logits(
    C: jax.Array, f: jax.Array, g: jax.Array, eps: float
) -> jax.Array:
    """Soft-assignment logits log P[m, i] = (f[m] + g[i] - C[m, i]) / eps.

    Returned in the cost matrix's dtype to keep the big buffer narrow.
    """
    z = (f[:, None] + g[None, :] - C.astype(jnp.float32)) / eps
    return z.astype(C.dtype)
