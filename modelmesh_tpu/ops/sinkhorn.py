"""Log-domain Sinkhorn iterations for the placement transport prior.

Solves the entropically-regularized optimal transport between model copy-mass
(rows: ``copies * sizes``) and instance capacity (columns) over the placement
cost matrix from ops.costs. The output potentials define a soft assignment
``P = exp((f + g - C) / eps)`` used as the score prior for integral rounding
(ops.auction).

TPU notes: the cost matrix stays bf16 in HBM (bandwidth is the bottleneck at
100k x 1k and above); all potentials and log-sum-exp accumulation are f32.
With ``tol=0`` (default) the loop is a fixed-length ``lax.scan`` so the whole
solve is one XLA program with no data-dependent control flow. With ``tol>0``
the loop becomes a ``lax.while_loop`` over K-iteration *chunks* (the chunk
body is still a fixed-length scan, so the compiled program is one stable
XLA computation regardless of where the exit lands) that stops as soon as
the row-marginal error drops below ``tol`` — the steady-state refresh path:
a warm-started solve is already a chunk or two from its fixed point, and
iterating to the full budget anyway throws that convergence away.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class SinkhornResult(NamedTuple):
    f: jax.Array        # f32[N] row potentials
    g: jax.Array        # f32[M] column potentials
    row_err: jax.Array  # f32[] final L1 row-marginal error (diagnostic)
    # i32[] iterations actually run (== iters when tol=0; a warm-started
    # early-exit solve reports fewer — the steady-state win, observable).
    iters_run: jax.Array = None


def _row_lse(C: jax.Array, g: jax.Array, eps: float) -> jax.Array:
    """logsumexp_i (g[i] - C[m, i]) / eps  -> f32[N]."""
    z = (g[None, :] - C.astype(jnp.float32)) / eps
    return jax.nn.logsumexp(z, axis=1)


def _col_lse(C: jax.Array, f: jax.Array, eps: float) -> jax.Array:
    """logsumexp_m (f[m] - C[m, i]) / eps  -> f32[M]."""
    z = (f[:, None] - C.astype(jnp.float32)) / eps
    return jax.nn.logsumexp(z, axis=0)


def resolve_lse_impl(lse_impl: str) -> str:
    """Validate + resolve "auto": pallas only when BOTH the default
    backend and any explicit default-device override point at TPU (a CPU
    default_device on a TPU host compiles the program for CPU, where a
    Mosaic kernel cannot lower). Shared with the sharded solver."""
    if lse_impl not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"lse_impl={lse_impl!r} (expected auto | xla | pallas)"
        )
    if lse_impl != "auto":
        return lse_impl
    dd = jax.config.jax_default_device
    on_tpu = jax.default_backend() == "tpu" and (
        dd is None or getattr(dd, "platform", "tpu") == "tpu"
    )
    return "pallas" if on_tpu else "xla"


@partial(jax.jit, static_argnames=("eps", "iters", "lse_impl", "tol", "chunk"))
def sinkhorn(
    C: jax.Array,
    row_mass: jax.Array,
    col_mass: jax.Array,
    *,
    eps: float = 0.05,
    iters: int = 12,
    lse_impl: str = "auto",
    g0: jax.Array | None = None,
    tol: float = 0.0,
    chunk: int = 4,
) -> SinkhornResult:
    """Semi-unbalanced log-domain Sinkhorn: rows are equalities (every
    model's copy-mass must place), columns are CAPS.

    The column update clamps ``g <= 0``: a column whose demand at g=0 is
    below its capacity keeps g = 0 (no subsidy to fill slack), one whose
    demand exceeds capacity gets the usual negative potential pushing mass
    away. Capacity-as-quota (the balanced form) would force every column to
    absorb its proportional share even when the whole fleet prefers a
    subset — nullifying cost-pool preferences (the `preferred` label term)
    whenever there is slack, which is most of the time.

    ``g0`` warm-starts the column potentials (SURVEY.md section 7 hard
    part #4: incremental solves as state churns). Between consecutive
    refreshes the problem barely moves, so the last solve's g is a few
    iterations from the new fixed point — the same iteration budget
    converges tighter. Only g needs carrying: the first iteration derives
    f entirely from g, so a row-potential input would be dead code.

    ``tol`` > 0 enables convergence-gated early exit: one probe iteration
    runs first, and if it moved g by no more than ``tol * eps`` (bounding
    the relative row-marginal error by ~tol) the solve returns immediately
    with ``iters_run == 1`` — the steady-state warm-start fast exit.
    Otherwise iterations continue in ``chunk``-sized blocks and the loop
    stops once the relative L1 row-marginal error is <= tol (or the
    ``iters`` budget, rounded up to probe + whole chunks, is spent). The
    error check costs one extra row-LSE per chunk, amortized by the chunk
    width.
    """
    row_mass = row_mass.astype(jnp.float32)
    col_mass = col_mass.astype(jnp.float32)
    log_a = jnp.log(jnp.maximum(row_mass, 1e-30))
    log_b = jnp.log(jnp.maximum(col_mass, 1e-30))

    # LSE backend: the Pallas kernels (ops/pallas_lse.py) pin the online
    # reduction in VMEM on TPU; XLA's fused reduction everywhere else.
    # Explicit "pallas" off-TPU runs the kernels under the interpreter
    # (slow, for testing the REAL selection path) rather than crashing in
    # Mosaic lowering for a backend that doesn't exist.
    use_pallas = resolve_lse_impl(lse_impl) == "pallas"
    if use_pallas:
        from modelmesh_tpu.ops import pallas_lse

        interp = jax.default_backend() != "tpu"
        n_rows, n_cols = C.shape
        Cp = pallas_lse.pad_cost(C)  # ONCE, outside the scan
        row_fn = lambda _C, g_: pallas_lse.row_lse(   # noqa: E731
            Cp, g_, eps, interpret=interp, valid_rows=n_rows
        )
        col_fn = lambda _C, f_: pallas_lse.col_lse(   # noqa: E731
            Cp, f_, eps, interpret=interp, valid_cols=n_cols
        )
    else:
        row_fn = lambda C_, g_: _row_lse(C_, g_, eps)  # noqa: E731
        col_fn = lambda C_, f_: _col_lse(C_, f_, eps)  # noqa: E731

    def body(carry, _):
        f, g = carry
        f = eps * (log_a - row_fn(C, g))
        g = jnp.minimum(0.0, eps * (log_b - col_fn(C, f)))
        return (f, g), None

    def run_iters(f, g, length):
        (f, g), _ = jax.lax.scan(body, (f, g), None, length=length)
        return f, g

    def marginal_err(f, g):
        # Relative L1 row-marginal violation of the implied plan.
        row_sum = jnp.exp((f + eps * row_fn(C, g)) / eps)
        return jnp.mean(jnp.abs(row_sum - row_mass)) / jnp.maximum(
            jnp.mean(row_mass), 1e-30
        )

    f_init = jnp.zeros_like(log_a)
    g_init = (
        jnp.minimum(0.0, g0.astype(jnp.float32))  # g <= 0 invariant
        if g0 is not None else jnp.zeros_like(log_b)
    )
    # iters <= 0 keeps the fixed path: the gate's probe would run one
    # unbudgeted iteration (and a zero chunk clamp would divide by zero).
    if tol <= 0.0 or chunk <= 0 or iters <= 0:
        f, g = run_iters(f_init, g_init, iters)
        return SinkhornResult(
            f=f, g=g, row_err=marginal_err(f, g),
            iters_run=jnp.asarray(iters, jnp.int32),
        )
    f, g, row_err, iters_run = gated_sinkhorn_loop(
        run_iters, marginal_err, f_init, g_init,
        eps=eps, iters=iters, tol=tol, chunk=chunk,
    )
    return SinkhornResult(f=f, g=g, row_err=row_err, iters_run=iters_run)


def gated_sinkhorn_loop(
    run_iters, marginal_err, f_init, g_init, *,
    eps: float, iters: int, tol: float, chunk: int, dg_reduce=None,
):
    """Convergence-gated iteration driver shared by this module and
    ``parallel/sharded_solver._sharded_sinkhorn`` (parameterized by the
    iteration/error callbacks so the gate logic — probe bound, budget
    rounding, iteration accounting — cannot drift between the two; the
    parity tests pin potentials AND iters_run).

    A single-iteration warm probe, then a while_loop over fixed-size
    chunks. The probe runs one full iteration from the (possibly carried)
    potentials and measures how far it moved g: the whole solve state is
    a function of g, so a g-move of dg bounds the relative row-marginal
    error by ~dg/eps — dg <= tol*eps means the carry was already at the
    fixed point and the solve exits after ONE iteration instead of a
    whole chunk (the steady-state fast exit; a cold zeros-g start fails
    the probe and pays one extra iteration). The budget rounds UP to
    probe + whole chunks (iters is a budget, not an exact count) and the
    error carried out of the last chunk doubles as the final diagnostic —
    no extra LSE at the end.

    ``dg_reduce`` replicates the probe scalar across a device mesh (pmax
    over the axis g is sharded on) so every device takes the same cond
    branch; None on a single device. Returns (f, g, row_err, iters_run).
    """
    # The warm probe doesn't depend on chunking, so a small budget must
    # not disable the gate — just clamp the chunk to the budget.
    chunk = min(chunk, iters)
    n_chunks = -(-iters // chunk)

    def cond(carry):
        step, _f, _g, err = carry
        return (err > tol) & (step < n_chunks)

    def wbody(carry):
        step, f, g, _err = carry
        f, g = run_iters(f, g, chunk)
        return step + 1, f, g, marginal_err(f, g)

    f1, g1 = run_iters(f_init, g_init, 1)
    dg = jnp.max(jnp.abs(g1 - g_init))
    if dg_reduce is not None:
        dg = dg_reduce(dg)

    def _probe_exit(_):
        # dg/eps is the error bound the gate certified — reporting it
        # instead of the exact marginal saves a row-LSE on the fast path.
        return jnp.asarray(0, jnp.int32), f1, g1, dg / eps

    def _chunked(_):
        return jax.lax.while_loop(
            cond,
            wbody,
            (jnp.asarray(0, jnp.int32), f1, g1,
             jnp.asarray(jnp.inf, jnp.float32)),
        )

    step, f, g, row_err = jax.lax.cond(
        dg <= tol * eps, _probe_exit, _chunked, None
    )
    return f, g, row_err, step * chunk + 1


def plan_logits(
    C: jax.Array, f: jax.Array, g: jax.Array, eps: float
) -> jax.Array:
    """Soft-assignment logits log P[m, i] = (f[m] + g[i] - C[m, i]) / eps.

    Returned in the cost matrix's dtype to keep the big buffer narrow.
    """
    z = (f[:, None] + g[None, :] - C.astype(jnp.float32)) / eps
    return z.astype(C.dtype)
