"""Log-domain Sinkhorn iterations for the placement transport prior.

Solves the entropically-regularized optimal transport between model copy-mass
(rows: ``copies * sizes``) and instance capacity (columns) over the placement
cost matrix from ops.costs. The output potentials define a soft assignment
``P = exp((f + g - C) / eps)`` used as the score prior for integral rounding
(ops.auction).

TPU notes: the cost matrix stays bf16 in HBM (bandwidth is the bottleneck at
100k x 1k and above); all potentials and log-sum-exp accumulation are f32.
The loop is a ``lax.scan`` so the whole solve is one XLA program; no
data-dependent Python control flow (fixed iteration count — this is a prior,
not an exact solve, so tight convergence is unnecessary).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp


class SinkhornResult(NamedTuple):
    f: jax.Array        # f32[N] row potentials
    g: jax.Array        # f32[M] column potentials
    row_err: jax.Array  # f32[] final L1 row-marginal error (diagnostic)


def _row_lse(C: jax.Array, g: jax.Array, eps: float) -> jax.Array:
    """logsumexp_i (g[i] - C[m, i]) / eps  -> f32[N]."""
    z = (g[None, :] - C.astype(jnp.float32)) / eps
    return jax.nn.logsumexp(z, axis=1)


def _col_lse(C: jax.Array, f: jax.Array, eps: float) -> jax.Array:
    """logsumexp_m (f[m] - C[m, i]) / eps  -> f32[M]."""
    z = (f[:, None] - C.astype(jnp.float32)) / eps
    return jax.nn.logsumexp(z, axis=0)


@partial(jax.jit, static_argnames=("eps", "iters"))
def sinkhorn(
    C: jax.Array,
    row_mass: jax.Array,
    col_mass: jax.Array,
    *,
    eps: float = 0.05,
    iters: int = 12,
) -> SinkhornResult:
    """Semi-unbalanced log-domain Sinkhorn: rows are equalities (every
    model's copy-mass must place), columns are CAPS.

    The column update clamps ``g <= 0``: a column whose demand at g=0 is
    below its capacity keeps g = 0 (no subsidy to fill slack), one whose
    demand exceeds capacity gets the usual negative potential pushing mass
    away. Capacity-as-quota (the balanced form) would force every column to
    absorb its proportional share even when the whole fleet prefers a
    subset — nullifying cost-pool preferences (the `preferred` label term)
    whenever there is slack, which is most of the time.
    """
    row_mass = row_mass.astype(jnp.float32)
    col_mass = col_mass.astype(jnp.float32)
    log_a = jnp.log(jnp.maximum(row_mass, 1e-30))
    log_b = jnp.log(jnp.maximum(col_mass, 1e-30))

    def body(carry, _):
        f, g = carry
        f = eps * (log_a - _row_lse(C, g, eps))
        g = jnp.minimum(0.0, eps * (log_b - _col_lse(C, f, eps)))
        return (f, g), None

    f0 = jnp.zeros_like(log_a)
    g0 = jnp.zeros_like(log_b)
    (f, g), _ = jax.lax.scan(body, (f0, g0), None, length=iters)

    # Diagnostic: row-marginal violation of the implied plan.
    row_sum = jnp.exp((f + eps * _row_lse(C, g, eps)) / eps)
    row_err = jnp.mean(jnp.abs(row_sum - row_mass)) / jnp.maximum(
        jnp.mean(row_mass), 1e-30
    )
    return SinkhornResult(f=f, g=g, row_err=row_err)


def plan_logits(
    C: jax.Array, f: jax.Array, g: jax.Array, eps: float
) -> jax.Array:
    """Soft-assignment logits log P[m, i] = (f[m] + g[i] - C[m, i]) / eps.

    Returned in the cost matrix's dtype to keep the big buffer narrow.
    """
    z = (f[:, None] + g[None, :] - C.astype(jnp.float32)) / eps
    return z.astype(C.dtype)
