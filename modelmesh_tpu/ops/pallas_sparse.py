"""Pallas TPU kernels for the sparse solve's hot loop: fused candidate
mask + scaled-kernel matvec pair.

The XLA sparse path (ops/sparse.py) materializes two [N, M] intermediates
per solve — the bool candidate mask from ``topk_candidates`` and the f32
scaled kernel ``P = exp((rowmin - C) / eps) * mask`` — and then streams P
through two matvecs per Sinkhorn iteration. At 100k x 1k that is ~400 MB
of f32 kernel state read twice per iteration; the cost matrix itself is
bf16 and half that. These kernels keep the bf16 cost matrix as the ONLY
[N, M] operand in HBM:

    rowmin[n] = min_m { C[n, m] : key(n, m) <= thresh[n] }
    r[n]      = sum_m [key <= thresh] * exp((rowmin[n] - C[n, m]) / eps) * v[m]
    c[m]      = sum_n [key <= thresh] * exp((rowmin[n] - C[n, m]) / eps) * u[n]

where ``key(n, m) = f32(C[n, m]) - tau * gumbel(n, m)`` is the noisy
top-K selection key and ``thresh[n]`` the row's K-th key (from the one
XLA ``top_k`` pass that builds the gathered candidate columns — finding
the threshold is a selection problem and stays in XLA's sort/TopK custom
call; everything downstream of it fuses here). The membership test, the
positional Gumbel draw, the row shift and the exp all recompute inside
the tile loop from streamed bf16 C plus three row vectors — neither the
mask nor P ever exists in HBM, and the f32 accumulators live in VMEM
scratch across the whole reduction (the ops/pallas_lse.py streaming
pattern, with the online max replaced by a plain masked sum since the
row shift already bounds the exponent).

The Gumbel draw is bit-identical to ops.auction.hash_gumbel_at: the
row-side murmur state ``fmix32(row ^ seed * C3)`` is precomputed once
per solve (``noise_row_state`` — O(N), and how the kernel avoids needing
the traced seed scalar), and the kernel applies the column-side mix. A
pure function of (row, col, seed) in both backends means the fused mask
equals the XLA mask bit-for-bit; ``rowmin`` is a min over the same set
(exact in f32), and the matvecs match to reduction-order rounding. The
parity suite (tests/test_pallas_sparse.py) pins the mask/rowmin bitwise
and the end-to-end Placement indices/valid bitwise in interpret mode.

Selection: ``SolveConfig.sparse_impl`` ("auto" = Pallas on TPU backends,
XLA elsewhere — the interpreter is for correctness, not speed; explicit
"pallas" off-TPU runs interpreted for the parity gates).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tile sizes: multiples of the f32 (8, 128) / bf16 (16, 128) register
# tiles, matching ops/pallas_lse.py.
_TN = 256   # rows per block
_TM = 512   # cols per block
# Padding cost: far above any real assembled cost (INFEASIBLE included),
# so padded entries can never pass the threshold test, and the shifted
# exponent underflows to exactly 0 (no NaN path).
_POS_BIG = 1.0e30


def resolve_sparse_impl(sparse_impl: str) -> str:
    """Validate + resolve "auto" for the fused sparse-kernel backend.

    Mirrors ops.sinkhorn.resolve_lse_impl: "auto" picks the Pallas
    kernels only on TPU backends — in interpret mode they are far slower
    than the XLA scaled-kernel path, so CPU "auto" stays on XLA and an
    explicit "pallas" off-TPU is the parity-test configuration."""
    if sparse_impl not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"sparse_impl={sparse_impl!r} (expected auto | xla | pallas)"
        )
    if sparse_impl != "auto":
        return sparse_impl
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def _fmix32(v):
    """murmur3 finalizer — MUST stay op-for-op identical to the one in
    ops.auction.hash_gumbel_at (the bitwise mask parity depends on it)."""
    v ^= v >> 16
    v *= jnp.uint32(0x85EBCA6B)
    v ^= v >> 13
    v *= jnp.uint32(0xC2B2AE35)
    v ^= v >> 16
    return v


def noise_row_state(n: int, seed: jax.Array) -> jax.Array:
    """Row-side hash state ``fmix32(row ^ seed * 0xC2B2AE35)`` — the
    (row, seed)-only prefix of hash_gumbel_at's counter mix. Precomputing
    it keeps the traced seed out of the kernels (no scalar-prefetch
    plumbing) without changing a single bit of the draw."""
    rows = jnp.arange(n, dtype=jnp.uint32)
    return _fmix32(rows ^ (jnp.asarray(seed, jnp.uint32) * jnp.uint32(0xC2B2AE35)))


def _tile_key(c, xr, col0, tau, noised):
    """f32 selection key for one (rows, cols) tile: the cost plus the
    column-side completion of the hash-Gumbel draw. ``col0`` is the
    tile's global column origin (traced program_id arithmetic)."""
    if not noised:
        return c
    # col0 is program_id arithmetic (int32); cast BEFORE combining so the
    # counter stays uint32 — a signed intermediate would turn the >> 8
    # into an arithmetic shift and fork the draw from hash_gumbel_at.
    cols = jax.lax.broadcasted_iota(jnp.uint32, c.shape, 1) + jnp.asarray(
        col0, jnp.uint32
    )
    x = _fmix32(xr ^ (cols * jnp.uint32(0x85EBCA6B)))
    u = (x >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
    u = jnp.maximum(u, 1e-7)
    return c - tau * (-jnp.log(-jnp.log(u)))


def _row_min_kernel(xr_ref, th_ref, c_ref, out_ref, acc, *, tau, noised):
    step = pl.program_id(1)

    @pl.when(step == 0)
    def _init():
        acc[:] = jnp.full_like(acc, _POS_BIG)

    c = c_ref[:].astype(jnp.float32)
    key = _tile_key(c, xr_ref[:], step * _TM, tau, noised)
    masked = jnp.where(key <= th_ref[:], c, _POS_BIG)
    acc[:] = jnp.minimum(acc[:], jnp.min(masked, axis=1, keepdims=True))

    @pl.when(step == pl.num_programs(1) - 1)
    def _finalize():
        out_ref[:] = acc[:]


def _row_matvec_kernel(xr_ref, th_ref, rm_ref, v_ref, c_ref, out_ref, acc,
                       *, eps, tau, noised):
    step = pl.program_id(1)

    @pl.when(step == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    c = c_ref[:].astype(jnp.float32)
    key = _tile_key(c, xr_ref[:], step * _TM, tau, noised)
    p = jnp.where(key <= th_ref[:], jnp.exp((rm_ref[:] - c) / eps), 0.0)
    acc[:] += jnp.sum(p * v_ref[:], axis=1, keepdims=True)

    @pl.when(step == pl.num_programs(1) - 1)
    def _finalize():
        out_ref[:] = acc[:]


def _col_matvec_kernel(xr_ref, th_ref, rm_ref, u_ref, c_ref, out_ref, acc,
                       *, eps, tau, noised):
    # Reduced axis (rows) is grid dim 1 so the [1, _TM] accumulator
    # persists across it; the column origin is grid dim 0.
    step = pl.program_id(1)

    @pl.when(step == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    c = c_ref[:].astype(jnp.float32)
    key = _tile_key(c, xr_ref[:], pl.program_id(0) * _TM, tau, noised)
    p = jnp.where(key <= th_ref[:], jnp.exp((rm_ref[:] - c) / eps), 0.0)
    acc[:] += jnp.sum(p * u_ref[:], axis=0, keepdims=True)

    @pl.when(step == pl.num_programs(1) - 1)
    def _finalize():
        out_ref[:] = acc[:]


def _pad_to(x, mult, axis, value):
    n = x.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, rem)
    return jnp.pad(x, widths, constant_values=value)


def _pad_operands(C, thresh, x_row):
    """Pad to kernel tile multiples. C pads with +_POS_BIG (excluded by
    the threshold test AND exp-underflows to 0); padded rows get a
    -_POS_BIG threshold so their mask is empty."""
    Cp = _pad_to(_pad_to(C, _TN, 0, _POS_BIG), _TM, 1, _POS_BIG)
    th = _pad_to(
        thresh.astype(jnp.float32), _TN, 0, -_POS_BIG
    ).reshape(-1, 1)
    xr = _pad_to(x_row.astype(jnp.uint32), _TN, 0, 0).reshape(-1, 1)
    return Cp, th, xr


@functools.partial(
    jax.jit, static_argnames=("tau", "noised", "interpret", "valid_rows")
)
def masked_row_min(C, thresh, x_row, *, tau: float, noised: bool,
                   interpret: bool = False,
                   valid_rows: int | None = None):
    """min_m { f32(C[n, m]) : key(n, m) <= thresh[n] } -> f32[N].

    Exact (f32 min carries no rounding), so it is bit-identical to the
    XLA path's ``min(where(mask, C, inf))`` over the same mask."""
    n = valid_rows if valid_rows is not None else C.shape[0]
    Cp, th, xr = _pad_operands(C, thresh, x_row)
    np_, mp = Cp.shape
    out = pl.pallas_call(
        functools.partial(_row_min_kernel, tau=tau, noised=noised),
        grid=(np_ // _TN, mp // _TM),
        in_specs=[
            pl.BlockSpec((_TN, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((_TN, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((_TN, _TM), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((_TN, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((np_, 1), jnp.float32),
        scratch_shapes=[pltpu.VMEM((_TN, 1), jnp.float32)],
        interpret=interpret,
    )(xr, th, Cp)
    return out[:n, 0]


@functools.partial(
    jax.jit,
    static_argnames=("eps", "tau", "noised", "interpret", "valid_rows"),
)
def masked_row_matvec(C, thresh, x_row, rowmin, v, *, eps: float,
                      tau: float, noised: bool, interpret: bool = False,
                      valid_rows: int | None = None):
    """r = P @ v without materializing P -> f32[N]. ``v`` has the
    original column count (padded columns contribute exact zeros)."""
    n = valid_rows if valid_rows is not None else C.shape[0]
    Cp, th, xr = _pad_operands(C, thresh, x_row)
    rm = _pad_to(rowmin.astype(jnp.float32), _TN, 0, 0.0).reshape(-1, 1)
    vp = _pad_to(v.astype(jnp.float32), _TM, 0, 0.0).reshape(1, -1)
    np_, mp = Cp.shape
    out = pl.pallas_call(
        functools.partial(
            _row_matvec_kernel, eps=eps, tau=tau, noised=noised
        ),
        grid=(np_ // _TN, mp // _TM),
        in_specs=[
            pl.BlockSpec((_TN, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((_TN, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((_TN, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, _TM), lambda i, j: (0, j)),
            pl.BlockSpec((_TN, _TM), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((_TN, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((np_, 1), jnp.float32),
        scratch_shapes=[pltpu.VMEM((_TN, 1), jnp.float32)],
        interpret=interpret,
    )(xr, th, rm, vp, Cp)
    return out[:n, 0]


@functools.partial(
    jax.jit,
    static_argnames=("eps", "tau", "noised", "interpret", "valid_cols"),
)
def masked_col_matvec(C, thresh, x_row, rowmin, u, *, eps: float,
                      tau: float, noised: bool, interpret: bool = False,
                      valid_cols: int | None = None):
    """c = u @ P without materializing P -> f32[M] (the scatter-free
    column accumulation; padded rows carry u = 0)."""
    m = valid_cols if valid_cols is not None else C.shape[1]
    Cp, th, xr = _pad_operands(C, thresh, x_row)
    rm = _pad_to(rowmin.astype(jnp.float32), _TN, 0, 0.0).reshape(-1, 1)
    up = _pad_to(u.astype(jnp.float32), _TN, 0, 0.0).reshape(-1, 1)
    np_, mp = Cp.shape
    out = pl.pallas_call(
        functools.partial(
            _col_matvec_kernel, eps=eps, tau=tau, noised=noised
        ),
        grid=(mp // _TM, np_ // _TN),
        in_specs=[
            pl.BlockSpec((_TN, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((_TN, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((_TN, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((_TN, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((_TN, _TM), lambda j, i: (i, j)),
        ],
        out_specs=pl.BlockSpec((1, _TM), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((1, mp), jnp.float32),
        scratch_shapes=[pltpu.VMEM((1, _TM), jnp.float32)],
        interpret=interpret,
    )(xr, th, rm, up, Cp)
    return out[0, :m]
