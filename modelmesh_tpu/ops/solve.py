"""End-to-end single-device global placement solve: cost -> Sinkhorn -> auction.

This is the compute kernel behind the ``jax`` PlacementStrategy
(placement/jax_engine.py) and the benchmark target in BASELINE.json:
recompute global placement for 100k models x 1k instances in <50 ms p99 on
one TPU v5e chip, vs >30 s for the reference's serial janitor/reaper loops
(ModelMesh.java:5876-6835).

For the multi-chip (1M x 10k) scale see parallel/sharded_solver.py.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from modelmesh_tpu.ops import costs as costs_mod
from modelmesh_tpu.ops.auction import MAX_COPIES as auction_mod_MAX_COPIES
from modelmesh_tpu.ops.auction import auction as _auction
from modelmesh_tpu.ops.sinkhorn import plan_logits as _plan_logits
from modelmesh_tpu.ops.sinkhorn import sinkhorn as _sinkhorn


class SolveConfig(NamedTuple):
    eps: float = 0.05
    sinkhorn_iters: int = 10
    auction_iters: int = 40
    eta: float = 0.5
    # Gumbel sampling temperature for integral rounding; 0 disables
    # sampling. Scores are plan log-probs ((f+g-C)/eps), so tau=1.0 means
    # Gumbel-top-k samples placements ~ the transport plan itself — the
    # plan is (near-)capacity-feasible by construction, so sampled
    # rounding inherits that and prices only mop up residuals. Cost-term
    # gaps (move=1.0, preference=0.75) are eps-amplified to 20/15 in
    # log-odds, so stickiness and preference dominate sampling noise.
    tau: float = 1.0
    # Placement-preference weights (static: part of the compiled program).
    weights: costs_mod.CostWeights = costs_mod.CostWeights()
    # Sinkhorn LSE backend: "auto" = Pallas kernels on TPU, XLA elsewhere.
    lse_impl: str = "auto"
    # Auction implied-load histogram: "auto" = fused compare-reduce on TPU
    # (duplicate-index scatter-add serializes there), scatter elsewhere.
    load_impl: str = "auto"
    # Rounding-noise generator: "hash" (counter-based murmur mix — ~5x
    # cheaper than threefry on a 1e8-element draw and identical
    # single-device vs sharded) or "threefry" (JAX PRNG). Rounding quality
    # is statistically indistinguishable between the two (overflow 0.04-
    # 0.2% of demand for both across seeds at 20k x 256, matched spread).
    noise_impl: str = "hash"
    # Epilogue competitor to the best price iterate: "exact" full top-k,
    # "approx" approx_max_k, "none" best-iterate only.
    final_select: str = "exact"
    dtype: jnp.dtype = jnp.bfloat16


class Placement(NamedTuple):
    """Integral global placement plan (device arrays)."""

    indices: jax.Array   # i32[N, MAX_COPIES]
    valid: jax.Array     # bool[N, MAX_COPIES]
    load: jax.Array      # f32[M]
    overflow: jax.Array  # f32[]
    row_err: jax.Array   # f32[] sinkhorn marginal diagnostic
    f: jax.Array | None = None  # f32[N] row potentials (warm-start carry)
    g: jax.Array | None = None  # f32[M] column potentials


class SolveInit(NamedTuple):
    """Warm-start carry from a previous solve (SURVEY.md section 7 hard
    part #4: incremental solves as cluster state churns). Columns must be
    id-aligned to the CURRENT problem's column order by the caller
    (placement/jax_engine.py scatters by instance id). Only g is carried:
    Sinkhorn's first iteration derives f entirely from g."""

    g0: jax.Array        # f32[M] column potentials


@partial(jax.jit, static_argnames=("config",))
def solve_placement(
    problem: costs_mod.PlacementProblem,
    config: SolveConfig = SolveConfig(),
    seed: jax.Array | int = 0x5EED,
    init: SolveInit | None = None,
) -> Placement:
    """Solve one global placement. ``seed`` is traced — vary it per solve
    (e.g. janitor pass counter) so an unlucky rounding draw isn't frozen
    forever; changing it never recompiles. ``init`` warm-starts the
    Sinkhorn potentials from the previous refresh (same iteration budget,
    tighter convergence)."""
    C = costs_mod.assemble_cost(problem, weights=config.weights, dtype=config.dtype)
    # Clamp copies to what rounding can actually place, BEFORE building the
    # transport marginals — otherwise the prior reserves phantom capacity.
    copies = jnp.minimum(problem.copies, auction_mod_MAX_COPIES)
    row_mass = problem.sizes * copies.astype(jnp.float32)
    free = jnp.maximum(problem.capacity - problem.reserved, 0.0)
    sk = _sinkhorn(
        C, row_mass, free, eps=config.eps, iters=config.sinkhorn_iters,
        lse_impl=config.lse_impl,
        g0=None if init is None else init.g0,
    )
    logits = _plan_logits(C, sk.f, sk.g, config.eps)
    res = _auction(
        logits,
        problem.sizes,
        copies,
        free,
        problem.feasible,
        seed,
        iters=config.auction_iters,
        eta=config.eta,
        tau=config.tau,
        load_impl=config.load_impl,
        noise_impl=config.noise_impl,
        final_select=config.final_select,
    )
    return Placement(
        indices=res.indices,
        valid=res.valid,
        load=res.load,
        overflow=res.overflow,
        row_err=sk.row_err,
        f=sk.f,
        g=sk.g,
    )
