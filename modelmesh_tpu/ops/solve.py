"""End-to-end single-device global placement solve: cost -> Sinkhorn -> auction.

This is the compute kernel behind the ``jax`` PlacementStrategy
(placement/jax_engine.py) and the benchmark target in BASELINE.json:
recompute global placement for 100k models x 1k instances in <50 ms p99 on
one TPU v5e chip, vs >30 s for the reference's serial janitor/reaper loops
(ModelMesh.java:5876-6835).

For the multi-chip (1M x 10k) scale see parallel/sharded_solver.py.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from modelmesh_tpu.ops import costs as costs_mod
from modelmesh_tpu.ops.auction import MAX_COPIES as auction_mod_MAX_COPIES
from modelmesh_tpu.ops.auction import auction as _auction
from modelmesh_tpu.ops.sinkhorn import plan_logits as _plan_logits
from modelmesh_tpu.ops.sinkhorn import sinkhorn as _sinkhorn


class SolveConfig(NamedTuple):
    eps: float = 0.05
    sinkhorn_iters: int = 10
    auction_iters: int = 40
    eta: float = 0.5
    # Convergence-gated early exit (steady-state refresh fast path). 0
    # disables both gates (fixed iteration budgets — the cold-solve
    # default). sinkhorn_tol gates on relative L1 row-marginal error,
    # checked every sinkhorn_chunk iterations; auction_stall_tol gates on
    # per-round price movement / best-overflow improvement (see
    # ops.auction._stall_gated_rounds). With a gate enabled the iteration
    # budgets round up to whole chunks/rounds.
    sinkhorn_tol: float = 0.0
    sinkhorn_chunk: int = 4
    auction_stall_tol: float = 0.0
    # Gumbel sampling temperature for integral rounding; 0 disables
    # sampling. Scores are plan log-probs ((f+g-C)/eps), so tau=1.0 means
    # Gumbel-top-k samples placements ~ the transport plan itself — the
    # plan is (near-)capacity-feasible by construction, so sampled
    # rounding inherits that and prices only mop up residuals. Cost-term
    # gaps (move=1.0, preference=0.75) are eps-amplified to 20/15 in
    # log-odds, so stickiness and preference dominate sampling noise.
    tau: float = 1.0
    # Placement-preference weights (static: part of the compiled program).
    weights: costs_mod.CostWeights = costs_mod.CostWeights()
    # Sinkhorn LSE backend: "auto" = Pallas kernels on TPU, XLA elsewhere.
    lse_impl: str = "auto"
    # Auction implied-load histogram: "auto" = fused compare-reduce on TPU
    # (duplicate-index scatter-add serializes there), scatter elsewhere.
    load_impl: str = "auto"
    # Rounding-noise generator: "hash" (counter-based murmur mix — ~5x
    # cheaper than threefry on a 1e8-element draw and identical
    # single-device vs sharded) or "threefry" (JAX PRNG). Rounding quality
    # is statistically indistinguishable between the two (overflow 0.04-
    # 0.2% of demand for both across seeds at 20k x 256, matched spread).
    noise_impl: str = "hash"
    # Epilogue competitor to the best price iterate: "exact" full top-k,
    # "approx" approx_max_k, "none" best-iterate only.
    final_select: str = "exact"
    # Sparse top-K candidate width: 0 solves dense; > 0 routes through
    # ops/sparse.py (one cost pass + top-k gather, then K-wide Sinkhorn
    # rows and a fixed-candidate auction). Exact whenever every row has
    # <= topk feasible instances; otherwise an approximation of terms
    # that underflow to ~0 anyway. Requires noise_impl="hash" when
    # tau > 0 (the positional draw is what keeps sparse/dense noise
    # identical). The dispatch layer (placement/jax_engine.dispatch_solve)
    # sets this from problem shape + MM_SOLVER_SPARSE / MM_SOLVER_TOPK.
    topk: int = 0
    # Sparse-path per-iteration selection width: 0 = MAX_COPIES. The
    # dispatch layer narrows it to the snapshot's real max copy count
    # (bucketed to a power of two so the jit-entry set stays tiny) —
    # top-8-of-K every price iteration is the single biggest line in the
    # sparse profile, and a fleet whose hottest model wants 3 copies
    # never needs more than a top-4. MUST be >= the problem's max copy
    # count or high-copy rows silently lose slots; ignored by the dense
    # path (whose narrow rounds are already K_CAND-bounded).
    sel_width: int = 0
    dtype: jnp.dtype = jnp.bfloat16
    # When the dispatch layer routes a solve sparse, knobs the operator
    # left at their dense defaults (auction_iters, auction_stall_tol,
    # sinkhorn_tol — judged by value + the MM_SOLVER_* env registry) are
    # swapped for the sparse-tier defaults. A programmatic caller whose
    # explicitly-constructed config deliberately wants those exact dense
    # values (e.g. auction_stall_tol=0.0 for a fixed, reproducible
    # iteration budget) sets tier_defaults=False to forbid the rewrite —
    # value-equality alone cannot distinguish "chose the default" from
    # "left unset".
    tier_defaults: bool = True
    # Sparse-path kernel backend: "auto" = fused Pallas mask+matvec
    # kernels (ops/pallas_sparse.py) on TPU backends, the XLA
    # scaled-kernel path elsewhere. Explicit "pallas" off-TPU runs the
    # kernels in interpret mode (the parity-gate configuration —
    # correctness, not speed). Env knob: MM_SOLVER_SPARSE_IMPL.
    sparse_impl: str = "auto"


class Placement(NamedTuple):
    """Integral global placement plan (device arrays)."""

    indices: jax.Array   # i32[N, MAX_COPIES]
    valid: jax.Array     # bool[N, MAX_COPIES]
    load: jax.Array      # f32[M]
    overflow: jax.Array  # f32[]
    row_err: jax.Array   # f32[] sinkhorn marginal diagnostic
    f: jax.Array | None = None  # f32[N] row potentials (warm-start carry)
    g: jax.Array | None = None  # f32[M] column potentials
    # f32[M] last-iterate congestion prices (warm-start carry for the next
    # refresh's SolveInit.price0).
    prices: jax.Array | None = None
    # i32[] iterations each stage actually ran (== the configured budgets
    # when the early-exit gates are off; fewer on a converged warm solve).
    sinkhorn_iters_run: jax.Array | None = None
    auction_iters_run: jax.Array | None = None


class SolveInit(NamedTuple):
    """Warm-start carry from a previous solve (SURVEY.md section 7 hard
    part #4: incremental solves as cluster state churns). Columns must be
    id-aligned to the CURRENT problem's column order by the caller
    (placement/jax_engine.py scatters by instance id). Only column state is
    carried: Sinkhorn's first iteration derives f entirely from g, and the
    auction's selection derives entirely from prices."""

    g0: jax.Array        # f32[M] column potentials
    # f32[M] congestion prices (None = cold prices; kept optional so
    # existing g0-only carries keep their jit cache entries).
    price0: jax.Array | None = None


def _solve_placement_impl(
    problem: costs_mod.PlacementProblem,
    config: SolveConfig,
    seed: jax.Array | int,
    init: SolveInit | None,
) -> Placement:
    if config.topk > 0 and config.topk < problem.num_instances:
        # Sparse top-K pipeline (ops/sparse.py): same Placement pytree,
        # same warm carries, same convergence gates — config is static,
        # so each width compiles its own executable exactly like any
        # other config change.
        from modelmesh_tpu.ops.sparse import solve_sparse

        return solve_sparse(problem, config, seed, init)
    C = costs_mod.assemble_cost(problem, weights=config.weights, dtype=config.dtype)
    # Clamp copies to what rounding can actually place, BEFORE building the
    # transport marginals — otherwise the prior reserves phantom capacity.
    copies = jnp.minimum(problem.copies, auction_mod_MAX_COPIES)
    row_mass = problem.sizes * copies.astype(jnp.float32)
    free = jnp.maximum(problem.capacity - problem.reserved, 0.0)
    sk = _sinkhorn(
        C, row_mass, free, eps=config.eps, iters=config.sinkhorn_iters,
        lse_impl=config.lse_impl,
        g0=None if init is None else init.g0,
        tol=config.sinkhorn_tol, chunk=config.sinkhorn_chunk,
    )
    logits = _plan_logits(C, sk.f, sk.g, config.eps)
    res = _auction(
        logits,
        problem.sizes,
        copies,
        free,
        problem.feasible,
        seed,
        iters=config.auction_iters,
        eta=config.eta,
        tau=config.tau,
        load_impl=config.load_impl,
        noise_impl=config.noise_impl,
        final_select=config.final_select,
        stall_tol=config.auction_stall_tol,
        price0=None if init is None else init.price0,
    )
    return Placement(
        indices=res.indices,
        valid=res.valid,
        load=res.load,
        overflow=res.overflow,
        row_err=sk.row_err,
        f=sk.f,
        g=sk.g,
        prices=res.prices,
        sinkhorn_iters_run=sk.iters_run,
        auction_iters_run=res.iters_run,
    )


@partial(jax.jit, static_argnames=("config",))
def solve_placement(
    problem: costs_mod.PlacementProblem,
    config: SolveConfig = SolveConfig(),
    seed: jax.Array | int = 0x5EED,
    init: SolveInit | None = None,
) -> Placement:
    """Solve one global placement. ``seed`` is traced — vary it per solve
    (e.g. janitor pass counter) so an unlucky rounding draw isn't frozen
    forever; changing it never recompiles. ``init`` warm-starts the
    Sinkhorn potentials (and, when ``init.price0`` is set, the auction
    prices) from the previous refresh — same iteration budgets, tighter
    convergence, and with the config's early-exit gates enabled the
    budgets are actually cut short once converged."""
    return _solve_placement_impl(problem, config, seed, init)


# Steady-state variant: identical program, but the warm-start carry (init:
# g0 + price0) is DONATED — XLA reuses those HBM buffers for the outputs,
# so a double-buffered refresh loop (placement/refresh_loop.py) never
# reallocates the carry buffers. Kept as a SEPARATE jit entry: donation is
# part of the executable signature, and the plain entry must keep accepting
# non-donatable inputs (e.g. a numpy g0 the host still owns). CPU backends
# ignore donation (harmless warning), so callers gate on platform.
solve_placement_donated = partial(
    jax.jit,
    static_argnames=("config",),
    donate_argnames=("init",),
)(_solve_placement_impl)


@partial(jax.jit, static_argnames=("config",))
def solve_placement_incremental(
    problem: costs_mod.PlacementProblem,
    config: SolveConfig,
    seed: jax.Array | int,
    dirty_rows: jax.Array,      # i32[D] row ids, padded with >= N sentinel
    base_indices: jax.Array,    # i32[N, MAX_COPIES] previous assignment
    base_valid: jax.Array,      # bool[N, MAX_COPIES]
    g0: jax.Array,              # f32[M] frozen column potentials
    price0: jax.Array,          # f32[M] frozen congestion prices
    base_row_err: jax.Array,    # f32[] frozen Sinkhorn diagnostic
) -> Placement:
    """Incremental dirty-row re-solve (ops/sparse.py): only the rows in
    ``dirty_rows`` are re-selected, against the FROZEN column potentials
    and prices of the base solve, and merged into the base assignment.
    ``seed`` must be the base solve's (frozen-epoch) seed so the
    positional noise draw matches — the dispatch layer enforces that,
    plus the dirty-fraction and overflow fallback gates."""
    from modelmesh_tpu.ops.sparse import resolve_dirty_rows

    return resolve_dirty_rows(
        problem, config, seed, dirty_rows, base_indices, base_valid,
        g0, price0, base_row_err,
    )
