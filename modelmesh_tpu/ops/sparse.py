"""Sparse top-K placement solve + incremental dirty-row re-solve.

The dense pipeline (ops/solve.py) touches the full [N, M] cost matrix ~20
times per solve (Sinkhorn row/col LSEs per iteration, plan logits, the
auction's full-width shortlists and epilogue). Most models can only
plausibly land on a few dozen instances — feasibility masks, zone
affinity, capacity — so almost all of that width is spent summing terms
that underflow to exactly 0. This module exploits that (AutoShard,
PAPERS.md, is the cost-model-guided-sparsification precedent):

1. **Candidate shortlist** (``topk_candidates``): ONE pass over the
   assembled cost matrix gathers the top-K cheapest instances per model
   into ``[N, K]`` cost/index/feasibility columns (K = SolveConfig.topk,
   env-tunable via MM_SOLVER_TOPK). The selection key is the cost plus a
   dedicated Gumbel draw at candidate-selection scale (``GATHER_TAU``):
   without it near-identical rows all shortlist the SAME cheap columns
   and the un-gathered majority of the fleet becomes unreachable —
   measured 35% rounding overflow at 20k x 256 vs 0.007% with the noise.
   Infeasible pairs carry the additive INFEASIBLE penalty, which drowns
   the noise, so feasible candidates always sort first and the gather
   contains EVERY feasible instance whenever a row has <= K of them —
   the regime where the sparse solve is exact.
2. **Sparse Sinkhorn** (``sparse_sinkhorn``): iterations run in the
   scaled-kernel form over a masked kernel matrix
   ``P = exp((rowmin - C) / eps) * mask`` precomputed ONCE — each
   iteration is two exp-free matvecs (``P @ v`` and ``u @ P``) instead
   of two full log-sum-exp passes, and the column "scatter-add back to
   [M]" is the ``u @ P`` product (scatter-free: XLA CPU/TPU scatter-adds
   with duplicate indices serialize — the same reason the auction's
   implied-load has a fused path). Row shifts (``rowmin``) keep the
   kernel in f32 range, and the f/g updates are algebraically identical
   to ops/sinkhorn.py's log-domain ones, so potentials match the dense
   solver to float rounding. Entries outside the mask are treated as
   infeasible, which is exact whenever K covers every feasible instance
   of a row and an approximation (of terms that were ~0 anyway)
   otherwise.
3. **Sparse auction** (``sparse_auction``): the gathered columns ARE the
   candidate shortlist, held fixed across price rounds (the dense
   narrow-round machinery re-shortlists from full width; here raw scores
   are already gathered so selection is exact at any price within the
   candidates). ``sel_k`` optionally narrows the per-iteration top-k to
   the problem's real max copy count (the dispatch layer derives it from
   the snapshot — top-8-of-K every price iteration is the single biggest
   line in the sparse profile). Convergence gates, best-iterate tracking
   and the warm probe are the shared ops.auction helpers.
4. **Incremental re-solve** (``resolve_dirty_rows``): re-selects ONLY
   the dirty rows the delta-snapshot path already tracks, against the
   frozen column potentials and prices of the last full solve, then
   merges them into the previous assignment and recomputes the exact
   load/overflow. O(D·M) instead of O(iters·N·M).

Rounding noise is the positional ``hash_gumbel_at`` draw — a pure
function of (row, col, seed) — so gathered, sharded, incremental and
dense evaluations of the same (row, col) see the SAME draw and
``Placement`` stays bit-compatible with the dense path when K covers the
feasible set (pinned by tests/test_sparse_solver.py).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from modelmesh_tpu.ops import costs as costs_mod
from modelmesh_tpu.ops.auction import (
    MAX_COPIES,
    RESHORTLIST_EVERY,
    _NEG_INF,
    _implied_load,
    _stall_gated_rounds,
    check_rounding_config,
    final_candidate,
    hash_gumbel_at,
    price_step,
    resolve_load_impl,
    select_from_candidates,
    warm_probe,
)
from modelmesh_tpu.ops.pallas_sparse import (
    masked_col_matvec,
    masked_row_matvec,
    masked_row_min,
    noise_row_state,
    resolve_sparse_impl,
)
from modelmesh_tpu.ops.sinkhorn import SinkhornResult, gated_sinkhorn_loop

# Gumbel scale for the candidate-selection draw (cost units; the cost
# terms are O(1)-scaled, so 0.5 spreads near-tied rows across the fleet
# without letting a genuinely-cheaper instance lose its slot). Distinct
# salt so the draw is independent of the rounding noise at the same
# (row, col, seed) counter.
GATHER_TAU: float = 0.5
_GATHER_SALT = 0x9E3779B9

# Numerical floor shared by the scaled-kernel iterations (matches the
# log-domain solver's log clamp).
_TINY = 1e-30


class FusedGather(NamedTuple):
    """Per-solve state the fused Pallas kernels (ops/pallas_sparse.py)
    need to recompute the candidate mask in-tile instead of reading a
    materialized bool[N, M]: the row's K-th selection key and the
    row-side hash state of the noise draw. ``tau``/``noised``/
    ``interpret`` are trace-time Python values (captured from the static
    SolveConfig), not traced operands."""

    thresh: jax.Array    # f32[N] K-th (tie-inclusive) selection key
    x_row: jax.Array     # u32[N] row-side hash state (noise_row_state)
    tau: float
    noised: bool
    interpret: bool


def topk_candidates(
    C: jax.Array,
    feasible: jax.Array,
    k: int,
    seed: jax.Array | None = None,
    gather_tau: float = GATHER_TAU,
    row_offset: jax.Array | int = 0,
    *,
    return_thresh: bool = False,
):
    """Gather each row's K cheapest instances from the assembled cost.

    Returns ``(cost_k, idx_k, feas_k, mask)``: costs in C's dtype (the
    sparse Sinkhorn upcasts exactly like the dense one), i32 column ids,
    the gathered feasibility mask, and a full-width ``bool[N, M]`` mask of
    every entry at-or-under the row's K-th selection key (the kernel mask
    ``sparse_sinkhorn`` consumes — a tie-inclusive superset of the
    gathered columns, computable without a scatter).

    Selection is by noisy cost (``gather_tau`` Gumbel at a salted
    counter; ``seed=None`` or ``gather_tau=0`` disables) so near-tied
    rows de-herd across the fleet. The INFEASIBLE penalty in C drowns the
    noise, so feasible candidates always outrank infeasible ones and
    whenever a row has <= K feasible instances the gather contains ALL of
    them — the sparse solve is exact for that row. ``row_offset`` shifts
    the noise counter for a model-axis shard so a sharded gather equals
    the corresponding rows of the single-device one.

    ``return_thresh=True`` appends the f32[N] K-th selection key (the
    mask's row threshold) for the fused Pallas path, which re-derives
    mask membership in-kernel instead of consuming the bool[N, M].
    """
    k = min(k, C.shape[1])
    key = C.astype(jnp.float32)
    if seed is not None and gather_tau > 0:
        rows = jax.lax.broadcasted_iota(
            jnp.uint32, C.shape, 0
        ) + jnp.asarray(row_offset, jnp.uint32)
        cols = jax.lax.broadcasted_iota(jnp.uint32, C.shape, 1)
        salted = jnp.asarray(seed, jnp.uint32) ^ jnp.uint32(_GATHER_SALT)
        key = key - gather_tau * hash_gumbel_at(rows, cols, salted)
    neg_vals, idx = jax.lax.top_k(-key, k)
    idx = idx.astype(jnp.int32)
    # K-th selection key via min(), NOT neg_vals[:, -1:]: slicing a
    # top_k output defeats XLA CPU's sort->TopK custom-call rewrite (the
    # extra slice merges into the sort's k-window slice and the pattern
    # no longer matches), silently falling back to a full O(M log M)
    # variadic sort — measured 1.3 s vs 150 ms for this exact gather at
    # 20k x 256. min() over the (descending) values is bit-identical.
    kth = -jnp.min(neg_vals, axis=1)
    mask = key <= kth[:, None]
    out = (
        jnp.take_along_axis(C, idx, axis=1),
        idx,
        jnp.take_along_axis(feasible, idx, axis=1),
        mask,
    )
    return out + (kth,) if return_thresh else out


def sparse_sinkhorn(
    C: jax.Array,            # [N, M] assembled cost (bf16 ok)
    mask: jax.Array,         # bool[N, M] candidate mask (topk_candidates)
    row_mass: jax.Array,     # f32[N]
    col_mass: jax.Array,     # f32[M] FULL-width capacity caps
    *,
    eps: float,
    iters: int,
    g0: jax.Array | None = None,
    tol: float = 0.0,
    chunk: int = 4,
    col_psum=None,
    dg_reduce=None,
    fused: FusedGather | None = None,
) -> SinkhornResult:
    """Semi-unbalanced Sinkhorn over the masked candidate set (rows
    equalities, columns CAPS via g <= 0 — must match ops/sinkhorn.py; the
    sparse parity test compares potentials).

    Scaled-kernel iterations: ``P = exp((rowmin - C) / eps) * mask`` is
    built once (row-shifted into f32 range; masked-out entries are exact
    zeros, i.e. treated as infeasible), then each iteration is

        v = exp(g / eps);  r = P @ v
        f = eps * (log a - log r) + rowmin          # row update
        u = a / r                                   # == exp((f-rowmin)/eps)
        g = min(0, eps * (log b - log(u @ P)))      # column update

    — algebraically the log-domain updates with the exp factored out of
    the inner loops, and ``u @ P`` standing in for the column scatter-add
    (exact, scatter-free). ``col_psum`` sums the per-shard column
    products (and the marginal-error sums) across a model-axis mesh —
    None on a single device; ``dg_reduce`` replicates the warm-probe
    scalar as in ``gated_sinkhorn_loop``. Columns nobody gathered get the
    empty-sum floor, which lands their potential at the g = 0 cap —
    exactly where a zero-demand column sits in the dense solve.

    With ``fused`` set (single-device only), the mask and P never
    materialize: the Pallas kernels (ops/pallas_sparse.py) recompute
    mask membership from ``fused.thresh``/``fused.x_row`` and the
    row-shifted exp in-tile, streaming only the bf16 cost matrix —
    ``mask`` is ignored and may be None.
    """
    row_mass = row_mass.astype(jnp.float32)
    col_mass = col_mass.astype(jnp.float32)
    log_a = jnp.log(jnp.maximum(row_mass, _TINY))
    log_b = jnp.log(jnp.maximum(col_mass, _TINY))
    if fused is not None:
        if col_psum is not None:
            raise ValueError(
                "fused sparse kernels are single-device only "
                "(sharded solves keep the XLA scaled-kernel path)"
            )
        rowmin = masked_row_min(
            C, fused.thresh, fused.x_row, tau=fused.tau,
            noised=fused.noised, interpret=fused.interpret,
        )

        def row_prod(v):
            return masked_row_matvec(
                C, fused.thresh, fused.x_row, rowmin, v, eps=eps,
                tau=fused.tau, noised=fused.noised,
                interpret=fused.interpret,
            )

        def col_prod(u):
            return masked_col_matvec(
                C, fused.thresh, fused.x_row, rowmin, u, eps=eps,
                tau=fused.tau, noised=fused.noised,
                interpret=fused.interpret,
            )
    else:
        Cf = C.astype(jnp.float32)
        rowmin = jnp.min(jnp.where(mask, Cf, jnp.inf), axis=1)  # finite: >=K masked
        P = jnp.where(mask, jnp.exp((rowmin[:, None] - Cf) / eps), 0.0)

        def row_prod(v):
            return P @ v

        def col_prod(u):
            return u @ P

    def row_terms(g):
        v = jnp.exp(g / eps)
        r = jnp.maximum(row_prod(v), _TINY)
        return r

    def body(carry, _):
        _f, g = carry
        r = row_terms(g)
        f = eps * (log_a - jnp.log(r)) + rowmin
        u = row_mass / r                       # exp((f - rowmin) / eps)
        c = col_prod(u)
        if col_psum is not None:
            c = col_psum(c)
        g = jnp.minimum(0.0, eps * (log_b - jnp.log(jnp.maximum(c, _TINY))))
        return (f, g), None

    def run_iters(f, g, length):
        (f, g), _ = jax.lax.scan(body, (f, g), None, length=length)
        return f, g

    def marginal_err(f, g):
        # sum/sum == the dense path's mean/mean relative-L1 diagnostic;
        # written as sums so the sharded combine is a plain psum pair.
        row_sum = jnp.exp((f - rowmin) / eps) * row_terms(g)
        num = jnp.sum(jnp.abs(row_sum - row_mass))
        den = jnp.sum(row_mass)
        if col_psum is not None:
            # Row-mass sums live on the model axis; reuse the column
            # combiner (it is the same psum over the model axis).
            num, den = col_psum(num), col_psum(den)
        return num / jnp.maximum(den, _TINY)

    f_init = jnp.zeros_like(log_a)
    g_init = (
        jnp.minimum(0.0, g0.astype(jnp.float32))  # g <= 0 invariant
        if g0 is not None else jnp.zeros_like(log_b)
    )
    if tol <= 0.0 or chunk <= 0 or iters <= 0:
        f, g = run_iters(f_init, g_init, iters)
        return SinkhornResult(
            f=f, g=g, row_err=marginal_err(f, g),
            iters_run=jnp.asarray(iters, jnp.int32),
        )
    f, g, row_err, iters_run = gated_sinkhorn_loop(
        run_iters, marginal_err, f_init, g_init,
        eps=eps, iters=iters, tol=tol, chunk=chunk, dg_reduce=dg_reduce,
    )
    return SinkhornResult(f=f, g=g, row_err=row_err, iters_run=iters_run)


def sparse_auction(
    scores_k: jax.Array,    # f32[N, K] noised+masked plan logits (gathered)
    idx_k: jax.Array,       # i32[N, K]
    sizes: jax.Array,       # f32[N]
    copies: jax.Array,      # i32[N]
    capacity: jax.Array,    # f32[M] full-width caps
    *,
    iters: int,
    eta: float,
    load_impl: str = "auto",
    final_select: str = "exact",
    stall_tol: float = 0.0,
    price0: jax.Array | None = None,
    sel_k: int = MAX_COPIES,
    axis_psum=None,
):
    """Price repair over a FIXED candidate set — the dense auction's
    narrow rounds minus the re-shortlisting (the top-K gather already
    holds raw scores, so selection is exact at any price within the
    candidates; spill outside them is what the overflow diagnostic and
    the dispatch-layer quality gates watch). Gates, best-iterate
    tracking and the warm probe are the shared ops.auction helpers so
    the convergence semantics cannot fork from the dense solvers.

    ``axis_psum`` sums per-shard load/demand across a model-axis mesh
    (None on a single device) — with it every gate scalar is replicated
    and all devices branch identically. Returns the
    ``(idx, valid, load, prices, overflow, iters_run)`` tuple shared
    with ``parallel/sharded_solver._sharded_auction``.
    """
    num_instances = capacity.shape[0]
    cap = jnp.maximum(capacity.astype(jnp.float32), 1e-6)
    copies = jnp.minimum(copies, MAX_COPIES)
    load_impl = resolve_load_impl(load_impl)
    n = scores_k.shape[0]

    nsel = min(sel_k, MAX_COPIES)

    def implied_load(idx, valid):
        # Slots past sel_k are _finalize_topk padding (never valid):
        # skip them so the per-iteration histogram scatters sel_k
        # entries per row, not MAX_COPIES.
        local = _implied_load(
            idx[:, :nsel], valid[:, :nsel], sizes, num_instances, load_impl
        )
        return axis_psum(local) if axis_psum is not None else local

    def select(price):
        # The gathered columns ARE the candidate shortlist: the dense
        # narrow rounds' selection epilogue applies verbatim.
        return select_from_candidates(scores_k, idx_k, copies, price, nsel)

    def narrow_round(carry, length):
        def body(carry, _):
            price, bp, bi, bv, bl, bo = carry
            idx, valid = select(price)
            load = implied_load(idx, valid)
            of = jnp.sum(jnp.maximum(load - cap, 0.0))
            better = of < bo
            # Best-iterate SELECTION prices — the warm-start carry, same
            # as ops.auction (last-iterate prices are mid-cobweb).
            bp = jnp.where(better, price, bp)
            bi = jnp.where(better, idx, bi)
            bv = jnp.where(better, valid, bv)
            bl = jnp.where(better, load, bl)
            bo = jnp.minimum(of, bo)
            return (
                price_step(load, cap, price, eta), bp, bi, bv, bl, bo,
            ), None

        carry, _ = jax.lax.scan(body, carry, None, length=length)
        return carry

    p_init = (
        jnp.maximum(price0.astype(jnp.float32), 0.0)  # price >= 0 invariant
        if price0 is not None
        else jnp.zeros((num_instances,), jnp.float32)
    )

    def epilogue(carry, iters_run):
        price, best_price, best_idx, best_valid, best_load, best_of = carry
        if final_select == "none":
            return (best_idx, best_valid, best_load, best_price, best_of,
                    iters_run)
        idx_l, valid_l = select(price)
        load_l = implied_load(idx_l, valid_l)
        of_l = jnp.sum(jnp.maximum(load_l - cap, 0.0))
        use_last = of_l <= best_of
        idx = jnp.where(use_last, idx_l, best_idx)
        valid = jnp.where(use_last, valid_l, best_valid)
        load = jnp.where(use_last, load_l, best_load)
        overflow = jnp.minimum(of_l, best_of)
        return (idx, valid, load, jnp.where(use_last, price, best_price),
                overflow, iters_run)

    carry = (
        p_init,
        p_init,
        jnp.zeros((n, MAX_COPIES), jnp.int32),
        jnp.zeros((n, MAX_COPIES), bool),
        jnp.zeros((num_instances,), jnp.float32),
        jnp.asarray(jnp.inf, jnp.float32),
    )
    if stall_tol <= 0.0:
        for length in [RESHORTLIST_EVERY] * (iters // RESHORTLIST_EVERY) + (
            [iters % RESHORTLIST_EVERY] if iters % RESHORTLIST_EVERY else []
        ):
            carry = narrow_round(carry, length)
        return epilogue(carry, jnp.asarray(iters, jnp.int32))

    total_demand = jnp.sum(sizes * copies.astype(jnp.float32))
    if axis_psum is not None:
        total_demand = axis_psum(total_demand)
    if final_select == "none":
        # Mirror ops.auction: "none" keeps epilogue-grade selections out
        # of the loop — gate the rounds only.
        carry2, iters_run = _stall_gated_rounds(
            narrow_round, carry, iters, stall_tol, total_demand,
        )
        return epilogue(carry2, iters_run)

    idx_p, valid_p, load_p, of_p, p_probe, probe_ok = warm_probe(
        select, p_init, cap, implied_load, eta, stall_tol, total_demand,
    )

    def _probe_exit(_):
        return (idx_p, valid_p, load_p, p_probe, of_p,
                jnp.asarray(1, jnp.int32))

    def _rounds(_):
        seeded = (p_probe, p_init, idx_p, valid_p, load_p, of_p)
        carry2, iters_run = _stall_gated_rounds(
            narrow_round, seeded, iters, stall_tol, total_demand,
        )
        return epilogue(carry2, iters_run + 1)

    return jax.lax.cond(probe_ok, _probe_exit, _rounds, None)


def check_sparse_config(config) -> None:
    """Trace-time validation shared by the single-device and sharded
    sparse entry points."""
    check_rounding_config(
        config.noise_impl, config.final_select, config.auction_iters
    )
    if config.tau > 0 and config.noise_impl != "hash":
        # The positional draw is what keeps gathered/incremental noise
        # identical to the dense draw; threefry cannot be evaluated at
        # scattered (row, col) positions without materializing the full
        # matrix the sparse path exists to avoid.
        raise ValueError(
            "sparse solve requires noise_impl='hash' "
            f"(got {config.noise_impl!r})"
        )
    if config.sel_width and not 0 < config.sel_width <= MAX_COPIES:
        raise ValueError(
            f"sel_width={config.sel_width} (expected 1..{MAX_COPIES}, "
            "or 0 for the MAX_COPIES default)"
        )


def perturb_gathered(
    logits_k: jax.Array, idx_k: jax.Array, feas_k: jax.Array,
    tau: float, seed: jax.Array, row_offset: jax.Array | int = 0,
) -> jax.Array:
    """Noise + feasibility mask for gathered plan logits — the sparse
    twin of ops.auction's perturb-then-mask prologue. ``row_offset``
    shifts row ids for a model-axis shard so the draw equals the
    single-device one bit-for-bit."""
    scores = logits_k.astype(jnp.float32)
    if tau > 0:
        rows = jax.lax.broadcasted_iota(
            jnp.uint32, idx_k.shape, 0
        ) + jnp.asarray(row_offset, jnp.uint32)
        scores = scores + tau * hash_gumbel_at(rows, idx_k, seed)
    return jnp.where(feas_k, scores, _NEG_INF)


def solve_sparse(problem, config, seed, init):
    """Sparse-pipeline twin of ops.solve._solve_placement_impl: cost ->
    top-K gather -> sparse Sinkhorn -> sparse auction. Same Placement
    pytree (f/g/prices full-width, so SolveInit warm carries and the
    donated steady-state entry work unchanged)."""
    from modelmesh_tpu.ops.solve import Placement

    check_sparse_config(config)
    seed = jnp.asarray(seed, jnp.uint32)
    C = costs_mod.assemble_cost(
        problem, weights=config.weights, dtype=config.dtype
    )
    use_pallas = resolve_sparse_impl(config.sparse_impl) == "pallas"
    cost_k, idx_k, feas_k, mask, kth = topk_candidates(
        C, problem.feasible, config.topk, seed=seed, return_thresh=True
    )
    fused = None
    if use_pallas:
        # Explicit "pallas" off-TPU runs the kernels interpreted — the
        # parity-gate configuration, not a performance path.
        fused = FusedGather(
            thresh=kth,
            x_row=noise_row_state(
                C.shape[0], seed ^ jnp.uint32(_GATHER_SALT)
            ),
            tau=GATHER_TAU,
            noised=GATHER_TAU > 0,
            interpret=jax.default_backend() != "tpu",
        )
    copies = jnp.minimum(problem.copies, MAX_COPIES)
    row_mass = problem.sizes * copies.astype(jnp.float32)
    free = jnp.maximum(problem.capacity - problem.reserved, 0.0)
    sk = sparse_sinkhorn(
        C, mask, row_mass, free,
        eps=config.eps, iters=config.sinkhorn_iters,
        g0=None if init is None else init.g0,
        tol=config.sinkhorn_tol, chunk=config.sinkhorn_chunk,
        fused=fused,
    )
    # Per-element arithmetic (and the dtype quantization) match
    # ops.sinkhorn.plan_logits so gathered scores equal the dense ones.
    logits_k = (
        (sk.f[:, None] + sk.g[idx_k] - cost_k.astype(jnp.float32))
        / config.eps
    ).astype(config.dtype)
    scores_k = perturb_gathered(
        logits_k, idx_k, feas_k, config.tau, seed
    )
    idx, valid, load, prices, overflow, au_iters = sparse_auction(
        scores_k, idx_k, problem.sizes, copies, free,
        iters=config.auction_iters, eta=config.eta,
        load_impl=config.load_impl, final_select=config.final_select,
        stall_tol=config.auction_stall_tol,
        price0=None if init is None else init.price0,
        sel_k=config.sel_width or MAX_COPIES,
    )
    return Placement(
        indices=idx, valid=valid, load=load, overflow=overflow,
        row_err=sk.row_err, f=sk.f, g=sk.g, prices=prices,
        sinkhorn_iters_run=sk.iters_run, auction_iters_run=au_iters,
    )


def resolve_dirty_rows(
    problem, config, seed, dirty_rows, base_indices, base_valid,
    g0, price0, base_row_err,
):
    """Incremental re-solve: new assignments for the dirty rows only,
    merged into the previous solve's placement.

    The column state (Sinkhorn potentials ``g0``, congestion prices
    ``price0``) is FROZEN from the base solve — re-solving a small dirty
    fraction cannot move the fleet-wide equilibrium materially, and the
    dispatch layer falls back to a full solve when the dirty fraction or
    the resulting overflow says otherwise. Each dirty row gets: an exact
    row potential against the frozen g (one [D, M] row LSE — rows are
    transport equalities, so f is exact given g), plan logits quantized
    like the dense path, the SAME positional noise draw as the base
    solve (the frozen epoch seed must be passed in), and an exact
    full-width selection at the frozen prices. The merged load/overflow
    are recomputed exactly over the whole assignment (O(N·MAX_COPIES)
    scatter, not O(N·M)).

    ``dirty_rows`` is host-padded with an out-of-range sentinel
    (>= base_indices row count): padded entries gather a clamped row but
    ``copies = 0`` voids their selection and the merge scatter drops
    them. ``base_row_err`` rides through as the (frozen) Sinkhorn
    diagnostic."""
    from modelmesh_tpu.ops.solve import Placement

    check_sparse_config(config)
    n = problem.num_models
    m = problem.num_instances
    rows = jnp.clip(dirty_rows, 0, n - 1)
    pad = dirty_rows >= n
    C_d = costs_mod.assemble_cost_rows(
        problem, rows, weights=config.weights, dtype=config.dtype
    )
    Cf = C_d.astype(jnp.float32)
    copies_d = jnp.where(
        pad, 0, jnp.minimum(problem.copies[rows], MAX_COPIES)
    )
    row_mass_d = problem.sizes[rows] * copies_d.astype(jnp.float32)
    g = jnp.minimum(0.0, g0.astype(jnp.float32))
    prices = jnp.maximum(price0.astype(jnp.float32), 0.0)
    lse = jax.nn.logsumexp((g[None, :] - Cf) / config.eps, axis=1)
    f_d = config.eps * (
        jnp.log(jnp.maximum(row_mass_d, _TINY)) - lse
    )
    logits_d = (
        (f_d[:, None] + g[None, :] - Cf) / config.eps
    ).astype(config.dtype)
    scores = logits_d.astype(jnp.float32)
    if config.tau > 0:
        cols = jax.lax.broadcasted_iota(jnp.uint32, Cf.shape, 1)
        rows_mat = jnp.broadcast_to(
            rows[:, None].astype(jnp.uint32), Cf.shape
        )
        scores = scores + config.tau * hash_gumbel_at(
            rows_mat, cols, jnp.asarray(seed, jnp.uint32)
        )
    scores = jnp.where(problem.feasible[rows], scores, _NEG_INF)
    idx_d, valid_d = final_candidate(
        scores - prices[None, :], copies_d, "exact"
    )
    indices = base_indices.at[dirty_rows].set(idx_d, mode="drop")
    valid = base_valid.at[dirty_rows].set(valid_d, mode="drop")
    load = _implied_load(
        indices, valid, problem.sizes, m,
        resolve_load_impl(config.load_impl),
    )
    free = jnp.maximum(problem.capacity - problem.reserved, 0.0)
    overflow = jnp.sum(
        jnp.maximum(load - jnp.maximum(free, 1e-6), 0.0)
    )
    zero = jnp.asarray(0, jnp.int32)
    return Placement(
        indices=indices, valid=valid, load=load, overflow=overflow,
        row_err=base_row_err, f=None, g=g0, prices=price0,
        sinkhorn_iters_run=zero, auction_iters_run=zero,
    )
