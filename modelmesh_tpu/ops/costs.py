"""Cost-matrix assembly for the global placement problem.

The reference decides placement greedily per request/per janitor pass using
``PLACEMENT_ORDER`` (ModelMesh.java:4646 — prefer instances with most free
space, then least-recently-used cache age) plus the cache-miss LB walk
(ModelMesh.java:4757-5004: type constraints, upgrade-replicaset exclusion,
free-space/LRU shortlists, busyness filter). Here the same preferences become
terms of a dense ``[num_models, num_instances]`` cost matrix consumed by the
Sinkhorn/auction solver (ops.sinkhorn / ops.auction).

All inputs are plain arrays so the assembly jits cleanly and shards along
either axis. Output is bf16 by default (HBM-bandwidth bound at the 100k x 1k
scale and beyond); intermediates are f32.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

# Additive penalty marking an infeasible (model, instance) pair. Large enough
# that exp(-INFEASIBLE/eps) == 0 for any sane eps, small enough for bf16.
INFEASIBLE: float = 1.0e4


@dataclasses.dataclass(frozen=True)
class CostWeights:
    """Relative weights of the placement-preference terms (all O(1) scaled)."""

    move: float = 1.0       # migration stickiness: cost of placing where not loaded
    utilization: float = 0.5  # prefer instances with more free capacity
    balance: float = 0.35   # spread high-rate models away from busy instances
    # Soft penalty for placing a model OFF its type's preferred label set
    # (TypeConstraintManager.java:242-248 getPreferredInstances) — a
    # preference term, not a mask: preferred pools win under equal load but
    # never block placement. Sized BELOW the move term (1.0) so preference
    # steers NEW placements without migrating already-loaded copies. In the
    # sampled rounding, cost gaps are amplified by 1/eps (=20 at the
    # default SolveConfig.eps=0.05) into plan-logit units: this 0.75 gap
    # becomes 15 logits against Gumbel(0, tau=1.0) noise (std ~1.3), so
    # preference decides effectively every otherwise-equal draw.
    preference: float = 0.75
    lru_age: float = 0.25   # prefer instances whose cache is oldest (easy eviction)
    zone_spread: float = 0.15  # prefer spreading copies across zones/versions
    # One-hot width for zone ids. Zone ids MUST be dense in [0, num_zones);
    # ids >= num_zones would alias (wrap), corrupting the spread term — the
    # strategy layer densifies zone names before building the problem.
    num_zones: int = 8


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PlacementProblem:
    """Array-level snapshot of cluster state for one global solve.

    Shapes: N = number of models, M = number of instances.
    Mirrors the state the reference reads in its placement paths:
    InstanceRecord capacity/used/lru/busyness (InstanceRecord.java:37-108),
    ModelRecord size/instanceIds (ModelRecord.java:61-126), RateTracker RPM
    (RateTracker.java:26-115), TypeConstraintManager candidate sets
    (TypeConstraintManager.java:242-248).
    """

    sizes: jax.Array        # f32[N] model size in cache units
    copies: jax.Array       # i32[N] desired copy count (>=1)
    rates: jax.Array        # f32[N] requests/min
    loaded: jax.Array       # bool[N, M] currently-loaded placement
    feasible: jax.Array     # bool[N, M] type/label constraints & exclusions
    capacity: jax.Array     # f32[M] total cache units per instance
    # Units consumed by things the solver does NOT place: runtime overhead,
    # unload buffer, out-of-registry entries. The mass of currently-loaded
    # *managed* models (``loaded`` x ``sizes``) must NOT be included here —
    # the solver re-places that mass itself and would double-count it.
    reserved: jax.Array     # f32[M]
    lru_age: jax.Array      # f32[M] age (secs) of oldest cache entry; 0 = empty-ish
    busyness: jax.Array     # f32[M] request-load proxy (RPM over recent window)
    zone: jax.Array         # i32[M] zone id per instance
    preferred: jax.Array    # bool[N, M] type-preference (all-True = none)

    @property
    def num_models(self) -> int:
        return self.sizes.shape[0]

    @property
    def num_instances(self) -> int:
        return self.capacity.shape[0]


def _minmax_norm(x: jax.Array) -> jax.Array:
    """Scale a vector to [0, 1]; constant vectors map to 0."""
    lo = jnp.min(x)
    span = jnp.max(x) - lo
    return jnp.where(span > 0, (x - lo) / jnp.maximum(span, 1e-30), 0.0)


@partial(jax.jit, static_argnames=("weights", "dtype"))
def assemble_cost(
    problem: PlacementProblem,
    weights: CostWeights = CostWeights(),
    dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    """Build the [N, M] placement cost matrix.

    cost[m, i] =
        move * (1 - loaded[m, i])            # keep existing placements
      + utilization * used_frac[i]           # fill free instances first
      + balance * rate_norm[m] * busy[i]     # hot models -> quiet instances
      - lru_age * age_norm[i]                # old caches are cheap to evict into
      + zone_spread * zone_crowding[m, i]    # spread copies across zones
      + preference * (1 - preferred[m, i])   # prefer labeled pools
      + INFEASIBLE * (1 - feasible[m, i])

    used_frac counts reserved (unmanaged) units plus the mass of currently
    loaded managed models, i.e. actual instance fullness.
    """
    w = weights
    loaded_f = problem.loaded.astype(jnp.float32)
    # sizes @ loaded: the same column sums as loaded.T @ sizes but as a
    # row-streaming vec-mat product — the explicit transpose walked the
    # [N, M] buffer column-major and cost ~35 ms alone at 20k x 256.
    loaded_mass = problem.sizes @ loaded_f  # [M]
    used_frac = jnp.clip(
        (problem.reserved + loaded_mass) / jnp.maximum(problem.capacity, 1.0),
        0.0,
        1.5,
    )
    busy = _minmax_norm(problem.busyness)
    age = _minmax_norm(problem.lru_age)
    rate = _minmax_norm(problem.rates)

    # Zone crowding: fraction of a model's current copies already in the
    # instance's zone (encourages copy spread like the reference's
    # location/zone placement terms).
    zone_onehot = jax.nn.one_hot(
        problem.zone, w.num_zones, dtype=jnp.float32
    )  # [M, Z]; out-of-range ids one-hot to all-zeros (no spread term)
    copies_per_zone = loaded_f @ zone_onehot    # [N, Z]
    denom = jnp.maximum(jnp.sum(copies_per_zone, axis=1, keepdims=True), 1.0)
    # Gather the instance's zone column instead of a second one-hot
    # matmul: each row of the matmul had exactly one non-zero term, so
    # the gather is bit-identical and one [N, Z] x [Z, M] product cheaper.
    # Out-of-range zone ids one-hot to all-zero columns above, so their
    # (clamped) gather must be forced back to the matmul's 0.
    crowding = jnp.where(
        (problem.zone >= 0) & (problem.zone < w.num_zones),
        (copies_per_zone / denom)[:, problem.zone],
        0.0,
    )  # [N, M]

    per_instance = w.utilization * used_frac - w.lru_age * age  # [M]
    cost = (
        w.move * (1.0 - loaded_f)
        + per_instance[None, :]
        + w.balance * rate[:, None] * busy[None, :]
        + w.zone_spread * crowding
        + w.preference * (1.0 - problem.preferred.astype(jnp.float32))
        + INFEASIBLE * (1.0 - problem.feasible.astype(jnp.float32))
    )
    return cost.astype(dtype)


def assemble_cost_rows(
    problem: PlacementProblem,
    rows: jax.Array,
    weights: CostWeights = CostWeights(),
    dtype: jnp.dtype = jnp.bfloat16,
) -> jax.Array:
    """Cost matrix for a ROW SUBSET: ``assemble_cost(...)[rows]`` without
    materializing the full [N, M] result — the incremental dirty-row
    re-solve's assembly stage (ops/sparse.py).

    Every normalization statistic (rate min/max, busyness/age norms, the
    per-column loaded mass) is computed over the FULL problem, exactly as
    the dense assembly does — normalizing over the subset would make a
    dirty row's cost depend on which OTHER rows happen to be dirty, and
    the re-solved rows must price against the same cost surface the base
    solve used. Pinned against ``assemble_cost`` by the parity test.
    ``rows`` must be in-range; callers clamp padded sentinels first.
    """
    w = weights
    loaded_mass = problem.sizes @ problem.loaded.astype(jnp.float32)  # [M]
    used_frac = jnp.clip(
        (problem.reserved + loaded_mass) / jnp.maximum(problem.capacity, 1.0),
        0.0,
        1.5,
    )
    busy = _minmax_norm(problem.busyness)
    age = _minmax_norm(problem.lru_age)
    rate = _minmax_norm(problem.rates)[rows]                      # [D]

    loaded_d = problem.loaded[rows].astype(jnp.float32)           # [D, M]
    zone_onehot = jax.nn.one_hot(
        problem.zone, w.num_zones, dtype=jnp.float32
    )
    copies_per_zone = loaded_d @ zone_onehot                      # [D, Z]
    denom = jnp.maximum(jnp.sum(copies_per_zone, axis=1, keepdims=True), 1.0)
    crowding = jnp.where(
        (problem.zone >= 0) & (problem.zone < w.num_zones),
        (copies_per_zone / denom)[:, problem.zone],
        0.0,
    )  # [D, M]

    per_instance = w.utilization * used_frac - w.lru_age * age
    cost = (
        w.move * (1.0 - loaded_d)
        + per_instance[None, :]
        + w.balance * rate[:, None] * busy[None, :]
        + w.zone_spread * crowding
        + w.preference * (1.0 - problem.preferred[rows].astype(jnp.float32))
        + INFEASIBLE * (1.0 - problem.feasible[rows].astype(jnp.float32))
    )
    return cost.astype(dtype)


def random_problem(
    key: jax.Array,
    num_models: int,
    num_instances: int,
    *,
    max_copies: int = 2,
    capacity_slack: float = 2.0,
    feasible_frac: float = 1.0,
) -> PlacementProblem:
    """Synthetic problem generator (Zipf-ish rates, lognormal sizes).

    Used by tests and the benchmark ladder in BASELINE.json. ``capacity_slack``
    scales total instance capacity relative to total demanded copy mass.
    """
    ks = jax.random.split(key, 8)
    sizes = jnp.exp(jax.random.normal(ks[0], (num_models,)) * 0.8 + 3.0)
    copies = 1 + (
        jax.random.uniform(ks[1], (num_models,)) < 0.15
    ).astype(jnp.int32) * jax.random.randint(ks[2], (num_models,), 0, max_copies)
    ranks = jnp.arange(1, num_models + 1, dtype=jnp.float32)
    rates = 2000.0 / ranks  # Zipf request rates
    rates = jax.random.permutation(ks[3], rates)
    demand = jnp.sum(sizes * copies)
    cap_base = jax.random.uniform(ks[4], (num_instances,), minval=0.5, maxval=1.5)
    # Slack applies to capacity net of the unmanaged reservation below.
    reserved_frac = jax.random.uniform(ks[5], (num_instances,), maxval=0.3)
    capacity = (
        cap_base / jnp.sum(cap_base) * demand * capacity_slack
        / jnp.mean(1.0 - reserved_frac)
    )
    reserved = capacity * reserved_frac
    lru_age = jax.random.uniform(ks[6], (num_instances,), maxval=3600.0)
    busyness = jax.random.uniform(ks[7], (num_instances,), maxval=4000.0)
    zone = jnp.arange(num_instances, dtype=jnp.int32) % 3
    loaded = jnp.zeros((num_models, num_instances), dtype=bool)
    if feasible_frac >= 1.0:
        feasible = jnp.ones((num_models, num_instances), dtype=bool)
    else:
        # Deterministic type partition: model type = m % 4, instance serves
        # types whose hash matches with prob feasible_frac.
        fkey = jax.random.fold_in(key, 99)
        feasible = jax.random.uniform(fkey, (4, num_instances)) < feasible_frac
        feasible = feasible[jnp.arange(num_models) % 4]
        # Every model keeps at least one feasible instance.
        feasible = feasible.at[:, 0].set(True)
    # Mixed preference mask (~70% preferred) so parity/quality tests
    # exercise the preference cost term; all-True would zero it out.
    pkey = jax.random.fold_in(key, 101)
    preferred = jax.random.uniform(pkey, (4, num_instances)) < 0.7
    preferred = preferred[jnp.arange(num_models) % 4]
    return PlacementProblem(
        sizes=sizes,
        copies=copies,
        rates=rates,
        loaded=loaded,
        feasible=feasible,
        capacity=capacity,
        reserved=reserved,
        lru_age=lru_age,
        busyness=busyness,
        zone=zone,
        preferred=preferred,
    )
