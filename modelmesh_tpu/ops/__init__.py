"""JAX kernels for the global placement solver."""

from modelmesh_tpu.ops.auction import MAX_COPIES, AuctionResult, auction
from modelmesh_tpu.ops.costs import (
    INFEASIBLE,
    CostWeights,
    PlacementProblem,
    assemble_cost,
    random_problem,
)
from modelmesh_tpu.ops.sinkhorn import SinkhornResult, plan_logits, sinkhorn
from modelmesh_tpu.ops.solve import Placement, SolveConfig, solve_placement

__all__ = [
    "MAX_COPIES",
    "AuctionResult",
    "auction",
    "INFEASIBLE",
    "CostWeights",
    "PlacementProblem",
    "assemble_cost",
    "random_problem",
    "SinkhornResult",
    "plan_logits",
    "sinkhorn",
    "Placement",
    "SolveConfig",
    "solve_placement",
]
