"""Greedy placement: functional re-derivation of the reference heuristics.

Default strategy and the correctness oracle for the JAX strategy. Decision
rules re-derived from (not copied out of) the reference:

- Load placement (cache-miss LB, ModelMesh.java:4757-5004): rank live,
  non-excluded instances by PLACEMENT_ORDER — most free capacity first,
  then oldest cache LRU (cheapest eviction); shortlist everything "close"
  to the best (within a free-space ratio and an LRU window); among the
  shortlist prefer the least busy. If the requester itself is in the
  shortlist, it loads locally (saves a hop).
- Serve balancing (ForwardingLB, ModelMesh.java:4309-4393): among loaded,
  live, non-excluded copies prefer the least busy instance; copies loaded
  long ago are preferred to freshly-loading ones.
"""

from __future__ import annotations

from typing import Optional

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.placement.strategy import (
    LOAD_HERE,
    ClusterView,
    PlacementRequest,
    PlacementStrategy,
)
from modelmesh_tpu.records import InstanceRecord, ModelRecord
from modelmesh_tpu.reconfig.rolling import upversion_shortlist
from modelmesh_tpu.serving.route_cache import ServeCandidate

# Shortlist thresholds (tunable analogs of the reference's proximity rules).
FREE_SPACE_SHORTLIST_RATIO = 0.75   # candidates with >= 75% of best free
LRU_SHORTLIST_WINDOW_MS = 5 * 60_000
# Warming fallback when no TimeStats is attached (per-type mean+3σ is the
# reference behavior, MM/TimeStats.java used at ModelMesh.java:4351).
# Single source of truth: timestats' no-evidence default.
from modelmesh_tpu.serving.timestats import DEFAULT_EXPECT_MS as RECENT_LOAD_PENALTY_MS  # noqa: E501


class GreedyStrategy(PlacementStrategy):
    def __init__(self, time_stats=None, constraints=None):
        # serving/timestats.TimeStats — attached by the instance so warming
        # penalties and wait-vs-reroute decisions use per-type load times.
        self.time_stats = time_stats
        # serving/constraints.TypeConstraints — `preferred` labels shape the
        # shortlist (TypeConstraintManager.java:242-248): when any shortlist
        # member matches the type's preferred labels, only those compete;
        # otherwise preference is moot and the full shortlist stands.
        self.constraints = constraints

    def _expect_ms(self, model_type: str) -> float:
        if self.time_stats is not None:
            return self.time_stats.expect_ms(model_type)
        return float(RECENT_LOAD_PENALTY_MS)

    def choose_load_target(
        self, req: PlacementRequest, view: ClusterView
    ) -> Optional[str]:
        candidates: list[tuple[str, InstanceRecord]] = [
            (iid, rec)
            for iid, rec in view.placeable()
            if iid not in req.exclude and iid not in req.model.instance_ids
        ]
        if not candidates:
            return None
        with_room = [
            (iid, rec) for iid, rec in candidates
            if rec.free_units >= req.required_units
        ]
        pool = with_room or candidates  # full cluster: evict somewhere
        best_free = max(rec.free_units for _, rec in pool)
        oldest_lru = min(
            (rec.lru_ts or 0) for _, rec in pool
        )
        shortlist = [
            (iid, rec) for iid, rec in pool
            if rec.free_units >= best_free * FREE_SPACE_SHORTLIST_RATIO
            or (rec.lru_ts or 0) <= oldest_lru + LRU_SHORTLIST_WINDOW_MS
        ] or pool
        if self.constraints is not None:
            pref = [
                (iid, rec) for iid, rec in shortlist
                if self.constraints.is_preferred(
                    req.model.model_type, rec.labels
                )
            ]
            if pref:
                shortlist = pref
        # Rolling-upgrade bias (reconfig/rolling.py): while the fleet
        # spans versions, only newest-version instances compete — applied
        # BEFORE the load-here shortcut so a down-version requester can't
        # capture the load and migrate the model backward.
        shortlist = upversion_shortlist(shortlist)
        if any(iid == req.requesting_instance for iid, _ in shortlist):
            return LOAD_HERE
        # Least busy; stable tie-break on free space then id. min() over a
        # key is the single-pass form of sort()[0] (same winner: min is
        # leftmost among key-ties, exactly what a stable sort put first).
        return min(
            shortlist,
            key=lambda p: (p[1].req_per_minute, -p[1].free_units, p[0]),
        )[0]

    def choose_group_targets(
        self, req: PlacementRequest, view: ClusterView,
        shard_count: int, shard_units: int,
    ) -> Optional[dict[str, int]]:
        """Group planning with the same candidate filters as
        ``choose_load_target``: type-constraint preferred labels and the
        rolling-upgrade upversion bias shape the pool before the
        capacity-greedy pick; existing same-index members stay sticky
        (a top-up re-plan must not shuffle landed shards). Atomic: all
        ``shard_count`` distinct members or None."""
        keep: dict[str, int] = {}
        taken: set[int] = set()
        for iid, idx in req.model.shard_instances.items():
            if (
                0 <= idx < shard_count
                and idx not in taken
                and iid not in req.exclude
                and iid in view.live_map
                and not view.live_map[iid].draining
            ):
                keep[iid] = idx
                taken.add(idx)
        pool = [
            (iid, rec) for iid, rec in view.placeable()
            if iid not in req.exclude and iid not in keep
            and rec.free_units >= shard_units
        ]
        if self.constraints is not None:
            pref = [
                (iid, rec) for iid, rec in pool
                if self.constraints.is_preferred(
                    req.model.model_type, rec.labels
                )
            ]
            missing_n = shard_count - len(taken)
            if len(pref) >= missing_n:
                pool = pref
        pool = upversion_shortlist(pool)
        pool.sort(key=lambda p: (-p[1].free_units, p[0]))
        missing = [i for i in range(shard_count) if i not in taken]
        if len(pool) < len(missing):
            return None
        assignments = dict(keep)
        for idx, (iid, _) in zip(missing, pool):
            assignments[iid] = idx
        return assignments

    def choose_serve_target(
        self, model: ModelRecord, view: ClusterView, exclude: frozenset[str]
    ) -> Optional[str]:
        # Shared per-snapshot id->record map (ClusterView caches it across
        # requests); single-pass running-minimum selection — the per-request
        # cost is O(copies), with no dict build and no candidate sort.
        live = view.live_map
        now = now_ms()
        expect = self._expect_ms(model.model_type)
        best_key: Optional[tuple] = None
        best: Optional[str] = None
        for iid, load_ts in model.instance_ids.items():
            if iid in exclude:
                continue
            rec = live.get(iid)
            if rec is None:
                continue
            # DRAINING copies rank behind every healthy one (reconfig/:
            # traffic shifts to survivors the moment their copies are
            # servable) but stay eligible — during the pre-copy window
            # the draining instance may hold the ONLY copy, and serving
            # it is exactly what makes the drain zero-gap. Per-type
            # warming penalty: a slow-loading type stays deprioritized
            # longer after activation than a fast one.
            key = (
                rec.draining, now - load_ts < expect,
                rec.req_per_minute, iid,
            )
            if best_key is None or key < best_key:
                best_key, best = key, iid
        if best is not None:
            return best
        # No READY copy: wait-vs-go-elsewhere on LOADING copies (reference
        # ModelMesh.java:4351). A copy loading for less than the type's
        # mean+3σ is healthy — forward to it and ride its load (a second
        # cold load elsewhere would cost the full load time again). One
        # loading beyond the bound is probably stuck: return None so the
        # cache-miss loop places a fresh copy elsewhere. With no per-type
        # evidence yet, ride unconditionally — the 10s default would call
        # every healthy slow FIRST load stuck and duplicate copies across
        # the fleet on cold start; the target's own flat wait bound
        # still catches genuinely dead loads.
        no_evidence = (
            self.time_stats is not None
            and self.time_stats.samples(model.model_type)
            < self.time_stats.min_samples
        )
        # Longest-elapsed healthy copy: closest to completion, so the
        # forwarded request waits the least. Running max, no list build.
        best_load: Optional[tuple[int, str]] = None
        for iid, claim_ts in model.loading_instances.items():
            if iid in exclude or iid not in live:
                continue
            elapsed = now - claim_ts
            if elapsed <= expect or no_evidence:
                cand = (elapsed, iid)
                if best_load is None or cand > best_load:
                    best_load = cand
        return best_load[1] if best_load is not None else None

    def rank_serve_candidates(
        self, model: ModelRecord, view: ClusterView, exclude: frozenset[str]
    ) -> list[ServeCandidate]:
        """The serve-target ranking as a SET: every eligible ready copy
        in exactly ``choose_serve_target``'s preference order (draining
        behind healthy, warming behind settled, then least busy), with a
        capability weight per candidate — advertised capacity normalized
        against the set's mean, so mixed hardware generations draw
        proportional traffic from the d-choices pick. When no ready copy
        exists, the wait-vs-reroute loading pick (if any) is returned as
        a single ``loading=True`` candidate: the route cache memoizes it
        like the old single-winner cache did but never load-balances it.
        ``rank[0]`` always equals ``choose_serve_target`` on the same
        inputs (parity-pinned in tests/test_route_cache.py) — the two
        must not fork."""
        live = view.live_map
        now = now_ms()
        expect = self._expect_ms(model.model_type)
        ranked: list[tuple[tuple, str, InstanceRecord]] = []
        for iid, load_ts in model.instance_ids.items():
            if iid in exclude:
                continue
            rec = live.get(iid)
            if rec is None:
                continue
            key = (
                rec.draining, now - load_ts < expect,
                rec.req_per_minute, iid,
            )
            ranked.append((key, iid, rec))
        if ranked:
            ranked.sort(key=lambda t: t[0])
            caps = [max(rec.capacity_units, 1) for _, _, rec in ranked]
            mean_cap = sum(caps) / len(caps)
            return [
                ServeCandidate(
                    iid, draining=rec.draining,
                    weight=max(rec.capacity_units, 1) / mean_cap,
                )
                for _, iid, rec in ranked
            ]
        loading = self.choose_serve_target(model, view, exclude)
        if loading is None:
            return []
        return [ServeCandidate(loading, loading=True)]
