"""Greedy placement: functional re-derivation of the reference heuristics.

Default strategy and the correctness oracle for the JAX strategy. Decision
rules re-derived from (not copied out of) the reference:

- Load placement (cache-miss LB, ModelMesh.java:4757-5004): rank live,
  non-excluded instances by PLACEMENT_ORDER — most free capacity first,
  then oldest cache LRU (cheapest eviction); shortlist everything "close"
  to the best (within a free-space ratio and an LRU window); among the
  shortlist prefer the least busy. If the requester itself is in the
  shortlist, it loads locally (saves a hop).
- Serve balancing (ForwardingLB, ModelMesh.java:4309-4393): among loaded,
  live, non-excluded copies prefer the least busy instance; copies loaded
  long ago are preferred to freshly-loading ones.
"""

from __future__ import annotations

from typing import Optional

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.placement.strategy import (
    LOAD_HERE,
    ClusterView,
    PlacementRequest,
    PlacementStrategy,
)
from modelmesh_tpu.records import InstanceRecord, ModelRecord

# Shortlist thresholds (tunable analogs of the reference's proximity rules).
FREE_SPACE_SHORTLIST_RATIO = 0.75   # candidates with >= 75% of best free
LRU_SHORTLIST_WINDOW_MS = 5 * 60_000
# Warming fallback when no TimeStats is attached (per-type mean+3σ is the
# reference behavior, MM/TimeStats.java used at ModelMesh.java:4351).
# Single source of truth: timestats' no-evidence default.
from modelmesh_tpu.serving.timestats import DEFAULT_EXPECT_MS as RECENT_LOAD_PENALTY_MS  # noqa: E501


class GreedyStrategy(PlacementStrategy):
    def __init__(self, time_stats=None, constraints=None):
        # serving/timestats.TimeStats — attached by the instance so warming
        # penalties and wait-vs-reroute decisions use per-type load times.
        self.time_stats = time_stats
        # serving/constraints.TypeConstraints — `preferred` labels shape the
        # shortlist (TypeConstraintManager.java:242-248): when any shortlist
        # member matches the type's preferred labels, only those compete;
        # otherwise preference is moot and the full shortlist stands.
        self.constraints = constraints

    def _expect_ms(self, model_type: str) -> float:
        if self.time_stats is not None:
            return self.time_stats.expect_ms(model_type)
        return float(RECENT_LOAD_PENALTY_MS)

    def choose_load_target(
        self, req: PlacementRequest, view: ClusterView
    ) -> Optional[str]:
        candidates: list[tuple[str, InstanceRecord]] = [
            (iid, rec)
            for iid, rec in view.placeable()
            if iid not in req.exclude and iid not in req.model.instance_ids
        ]
        if not candidates:
            return None
        with_room = [
            (iid, rec) for iid, rec in candidates
            if rec.free_units >= req.required_units
        ]
        pool = with_room or candidates  # full cluster: evict somewhere
        best_free = max(rec.free_units for _, rec in pool)
        oldest_lru = min(
            (rec.lru_ts or 0) for _, rec in pool
        )
        shortlist = [
            (iid, rec) for iid, rec in pool
            if rec.free_units >= best_free * FREE_SPACE_SHORTLIST_RATIO
            or (rec.lru_ts or 0) <= oldest_lru + LRU_SHORTLIST_WINDOW_MS
        ] or pool
        if self.constraints is not None:
            pref = [
                (iid, rec) for iid, rec in shortlist
                if self.constraints.is_preferred(
                    req.model.model_type, rec.labels
                )
            ]
            if pref:
                shortlist = pref
        if any(iid == req.requesting_instance for iid, _ in shortlist):
            return LOAD_HERE
        # Least busy; stable tie-break on free space then id.
        shortlist.sort(key=lambda p: (p[1].req_per_minute, -p[1].free_units, p[0]))
        return shortlist[0][0]

    def choose_serve_target(
        self, model: ModelRecord, view: ClusterView, exclude: frozenset[str]
    ) -> Optional[str]:
        live = {iid: rec for iid, rec in view.live()}
        now = now_ms()
        expect = self._expect_ms(model.model_type)
        candidates: list[tuple[tuple, str]] = []
        for iid, load_ts in model.instance_ids.items():
            if iid in exclude or iid not in live:
                continue
            # Per-type warming penalty: a slow-loading type stays
            # deprioritized longer after activation than a fast one.
            warming = now - load_ts < expect
            candidates.append(((warming, live[iid].req_per_minute, iid), iid))
        if candidates:
            candidates.sort()
            return candidates[0][1]
        # No READY copy: wait-vs-go-elsewhere on LOADING copies (reference
        # ModelMesh.java:4351). A copy loading for less than the type's
        # mean+3σ is healthy — forward to it and ride its load (a second
        # cold load elsewhere would cost the full load time again). One
        # loading beyond the bound is probably stuck: return None so the
        # cache-miss loop places a fresh copy elsewhere. With no per-type
        # evidence yet, ride unconditionally — the 10s default would call
        # every healthy slow FIRST load stuck and duplicate copies across
        # the fleet on cold start; the target's own flat wait bound
        # still catches genuinely dead loads.
        no_evidence = (
            self.time_stats is not None
            and self.time_stats.samples(model.model_type)
            < self.time_stats.min_samples
        )
        loading = [
            (elapsed, iid)
            for iid, claim_ts in model.loading_instances.items()
            if iid not in exclude and iid in live
            and ((elapsed := now - claim_ts) <= expect or no_evidence)
        ]
        if loading:
            # Longest-elapsed healthy copy: closest to completion, so the
            # forwarded request waits the least.
            return max(loading)[1]
        return None
