"""Greedy placement: functional re-derivation of the reference heuristics.

Default strategy and the correctness oracle for the JAX strategy. Decision
rules re-derived from (not copied out of) the reference:

- Load placement (cache-miss LB, ModelMesh.java:4757-5004): rank live,
  non-excluded instances by PLACEMENT_ORDER — most free capacity first,
  then oldest cache LRU (cheapest eviction); shortlist everything "close"
  to the best (within a free-space ratio and an LRU window); among the
  shortlist prefer the least busy. If the requester itself is in the
  shortlist, it loads locally (saves a hop).
- Serve balancing (ForwardingLB, ModelMesh.java:4309-4393): among loaded,
  live, non-excluded copies prefer the least busy instance; copies loaded
  long ago are preferred to freshly-loading ones.
"""

from __future__ import annotations

from typing import Optional

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.placement.strategy import (
    LOAD_HERE,
    ClusterView,
    PlacementRequest,
    PlacementStrategy,
)
from modelmesh_tpu.records import InstanceRecord, ModelRecord

# Shortlist thresholds (tunable analogs of the reference's proximity rules).
FREE_SPACE_SHORTLIST_RATIO = 0.75   # candidates with >= 75% of best free
LRU_SHORTLIST_WINDOW_MS = 5 * 60_000
# A copy loaded less than this ago may still be warming (reference uses
# per-type load-time stats, TimeStats; a flat floor is the simple analog).
RECENT_LOAD_PENALTY_MS = 10_000


class GreedyStrategy(PlacementStrategy):
    def choose_load_target(
        self, req: PlacementRequest, view: ClusterView
    ) -> Optional[str]:
        candidates: list[tuple[str, InstanceRecord]] = [
            (iid, rec)
            for iid, rec in view.placeable()
            if iid not in req.exclude and iid not in req.model.instance_ids
        ]
        if not candidates:
            return None
        with_room = [
            (iid, rec) for iid, rec in candidates
            if rec.free_units >= req.required_units
        ]
        pool = with_room or candidates  # full cluster: evict somewhere
        best_free = max(rec.free_units for _, rec in pool)
        oldest_lru = min(
            (rec.lru_ts or 0) for _, rec in pool
        )
        shortlist = [
            (iid, rec) for iid, rec in pool
            if rec.free_units >= best_free * FREE_SPACE_SHORTLIST_RATIO
            or (rec.lru_ts or 0) <= oldest_lru + LRU_SHORTLIST_WINDOW_MS
        ] or pool
        if any(iid == req.requesting_instance for iid, _ in shortlist):
            return LOAD_HERE
        # Least busy; stable tie-break on free space then id.
        shortlist.sort(key=lambda p: (p[1].req_per_minute, -p[1].free_units, p[0]))
        return shortlist[0][0]

    def choose_serve_target(
        self, model: ModelRecord, view: ClusterView, exclude: frozenset[str]
    ) -> Optional[str]:
        live = {iid: rec for iid, rec in view.live()}
        now = now_ms()
        candidates: list[tuple[tuple, str]] = []
        for iid, load_ts in model.instance_ids.items():
            if iid in exclude or iid not in live:
                continue
            warming = now - load_ts < RECENT_LOAD_PENALTY_MS
            candidates.append(((warming, live[iid].req_per_minute, iid), iid))
        if not candidates:
            return None
        candidates.sort()
        return candidates[0][1]
