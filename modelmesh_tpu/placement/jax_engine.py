"""JAX global placement strategy: per-request decisions from a TPU-solved plan.

The north-star architecture (BASELINE.json): cluster state (registry +
instance advertisements + rates) is assembled into a PlacementProblem,
solved as one batched Sinkhorn/auction assignment on the accelerator
(ops/solve.py single-chip, parallel/sharded_solver.py multi-chip), and the
resulting plan serves `choose_load_target` lookups until the next refresh.

Plans are ADVISORY (SURVEY.md section 7, hard part #4): per-instance local
guards (churn age, unload accounting, capacity) remain authoritative, and
any miss — model not in the plan, planned instances all excluded, plan
older than its TTL — falls back to the greedy oracle strategy. This mirrors
how the reference lets the placement heuristics be overridden per-decision
but never bypasses local admission control.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.placement.greedy import GreedyStrategy
from modelmesh_tpu.placement.strategy import (
    LOAD_HERE,
    ClusterView,
    PlacementRequest,
    PlacementStrategy,
)
from modelmesh_tpu.records import InstanceRecord, ModelRecord

log = logging.getLogger(__name__)


def build_problem(
    models: Sequence[tuple[str, ModelRecord]],
    instances: Sequence[tuple[str, InstanceRecord]],
    rpm_fn: Optional[Callable[[str], int]] = None,
    default_size_units: int = 128,
    max_copies: int = 8,
    constraints=None,
):
    """Assemble a PlacementProblem from registry/instance snapshots.

    Returns (problem, model_ids, instance_ids) — the id lists map array rows
    and columns back to the mesh. Zone names are densified to ids.
    """
    import jax.numpy as jnp

    from modelmesh_tpu.ops.costs import PlacementProblem

    model_ids = [mid for mid, _ in models]
    instance_ids = [iid for iid, _ in instances]
    n, m = len(model_ids), len(instance_ids)
    inst_index = {iid: j for j, iid in enumerate(instance_ids)}
    zones = sorted({rec.zone for _, rec in instances})
    zone_id = {z: i for i, z in enumerate(zones)}

    now = now_ms()
    sizes = np.empty(n, np.float32)
    copies = np.empty(n, np.int32)
    rates = np.empty(n, np.float32)
    loaded = np.zeros((n, m), bool)
    for i, (mid, mr) in enumerate(models):
        sizes[i] = mr.size_units or default_size_units
        copies[i] = min(max(mr.copy_count, 1), max_copies)
        rpm = rpm_fn(mid) if rpm_fn is not None else 0
        if rpm > 0:
            rates[i] = rpm
        else:
            # Recency proxy: rpm_fn is typically the refresher's *local*
            # rate view, which reads 0 for models served on other instances
            # — fall back rather than ranking remote-hot models as cold.
            age_min = max(0.0, (now - mr.last_used) / 60_000.0)
            rates[i] = 1000.0 / (1.0 + age_min)
        for iid in mr.instance_ids:
            j = inst_index.get(iid)
            if j is not None:
                loaded[i, j] = True

    capacity = np.empty(m, np.float32)
    reserved = np.empty(m, np.float32)
    lru_age = np.empty(m, np.float32)
    busy = np.empty(m, np.float32)
    zone = np.empty(m, np.int32)
    feasible_cols = np.empty(m, bool)
    for j, (iid, rec) in enumerate(instances):
        capacity[j] = max(rec.capacity_units, 1)
        managed = float(sizes[loaded[:, j]].sum())
        # reserved = advertised usage not attributable to planned models.
        reserved[j] = max(0.0, rec.used_units - managed)
        lru_age[j] = max(0.0, (now - rec.lru_ts) / 1000.0) if rec.lru_ts else 0.0
        busy[j] = rec.req_per_minute
        zone[j] = zone_id[rec.zone]
        feasible_cols[j] = not rec.shutting_down and not rec.disabled
    feasible = np.broadcast_to(feasible_cols, (n, m)).copy()
    preferred = np.ones((n, m), bool)
    if constraints is not None:
        # Type-constraint masks: one row pattern per model type. `required`
        # is a hard mask (feasible); `preferred` a soft cost term.
        type_mask: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        for i, (mid, mr) in enumerate(models):
            masks = type_mask.get(mr.model_type)
            if masks is None:
                req = np.array([
                    constraints.is_candidate(mr.model_type, rec.labels)
                    for _, rec in instances
                ])
                pref = np.array([
                    constraints.is_preferred(mr.model_type, rec.labels)
                    for _, rec in instances
                ])
                masks = type_mask[mr.model_type] = (req, pref)
            feasible[i] &= masks[0]
            preferred[i] = masks[1]

    problem = PlacementProblem(
        sizes=jnp.asarray(sizes),
        copies=jnp.asarray(copies),
        rates=jnp.asarray(rates),
        loaded=jnp.asarray(loaded),
        feasible=jnp.asarray(feasible),
        capacity=jnp.asarray(capacity),
        reserved=jnp.asarray(reserved),
        lru_age=jnp.asarray(lru_age),
        busyness=jnp.asarray(busy),
        zone=jnp.asarray(zone),
        preferred=jnp.asarray(preferred),
    )
    return problem, model_ids, instance_ids


class GlobalPlan:
    """Solved assignment: model -> ordered preferred instances.

    Plans travel: the leader solves and publishes the serialized plan to the
    KV store (placement/plan_sync.py) and every instance adopts it from a
    watch — the analog of the reference's leader-computed placement
    decisions propagating via the shared registry (ModelMesh.java:6616-6747),
    except here the whole assignment ships as one artifact. ``age_ms`` is
    measured from *local adoption time* so follower TTLs don't depend on
    clock agreement with the leader: a dead leader stops publishing and
    plans expire everywhere on their own clocks.
    """

    def __init__(
        self, placements: dict[str, list[str]], solved_at_ms: int,
        solve_ms: float, generation: int = 0,
    ):
        self.placements = placements
        self.solved_at_ms = solved_at_ms
        self.solve_ms = solve_ms
        self.generation = generation
        self.adopted_at_ms = solved_at_ms

    def age_ms(self) -> int:
        return now_ms() - self.adopted_at_ms

    # -- wire format (zlib'd JSON; compact keys — plans can cover 100k models)

    def to_bytes(self) -> bytes:
        import json
        import zlib

        payload = json.dumps(
            {
                "g": self.generation,
                "t": self.solved_at_ms,
                "ms": self.solve_ms,
                "p": self.placements,
            },
            separators=(",", ":"),
        )
        return zlib.compress(payload.encode(), level=1)

    @classmethod
    def from_bytes(cls, data: bytes) -> "GlobalPlan":
        import json
        import zlib

        d = json.loads(zlib.decompress(data).decode())
        plan = cls(d["p"], d["t"], d["ms"], d.get("g", 0))
        plan.adopted_at_ms = now_ms()
        return plan


def solve_plan(
    models: Sequence[tuple[str, ModelRecord]],
    instances: Sequence[tuple[str, InstanceRecord]],
    rpm_fn: Optional[Callable[[str], int]] = None,
    seed: int = 0,
    constraints=None,
) -> GlobalPlan:
    """One global solve -> GlobalPlan (blocking; runs on the JAX device)."""
    import jax

    from modelmesh_tpu.ops.solve import solve_placement

    if not models or not instances:
        return GlobalPlan({}, now_ms(), 0.0)
    t0 = time.perf_counter()
    problem, model_ids, instance_ids = build_problem(
        models, instances, rpm_fn, constraints=constraints
    )
    sol = jax.block_until_ready(solve_placement(problem, seed=seed))
    idx = np.asarray(sol.indices)
    valid = np.asarray(sol.valid)
    # Hottest-first insertion order: publish_plan truncates from the tail
    # under its byte budget, so the models that lose central placement must
    # be the coldest, not whichever ones the registry iterated last.
    order = np.argsort(-np.asarray(problem.rates), kind="stable")
    placements = {
        model_ids[i]: [instance_ids[j] for j in idx[i][valid[i]]]
        for i in order
    }
    solve_ms = (time.perf_counter() - t0) * 1e3
    return GlobalPlan(placements, now_ms(), solve_ms)


class JaxPlacementStrategy(PlacementStrategy):
    """Plan-serving strategy with greedy fallback.

    ``refresher`` mode: call ``refresh(models, instances, rpm_fn)``
    periodically (the reaper/janitor cadence, or a dedicated thread via
    ``start_auto_refresh``). Decisions read the latest plan lock-free.
    """

    def __init__(
        self,
        # Must exceed the publish cadence (the leader reaper's
        # reaper_interval_s, default 420 s) or followers spend most of each
        # cycle TTL-expired and silently serving greedy.
        plan_ttl_ms: int = 15 * 60_000,
        fallback: Optional[PlacementStrategy] = None,
        constraints=None,
    ):
        self.plan_ttl_ms = plan_ttl_ms
        self.fallback = fallback or GreedyStrategy()
        # serving/constraints.TypeConstraints — attached by the instance
        # (like greedy's) so solves honor required masks and preferred
        # labels (build_problem feasible/preferred).
        self.constraints = constraints
        self._plan: Optional[GlobalPlan] = None
        self._seed = 0
        self._refresh_lock = threading.Lock()

    @property
    def plan(self) -> Optional[GlobalPlan]:
        return self._plan

    def refresh(
        self,
        models: Sequence[tuple[str, ModelRecord]],
        instances: Sequence[tuple[str, InstanceRecord]],
        rpm_fn: Optional[Callable[[str], int]] = None,
    ) -> GlobalPlan:
        with self._refresh_lock:
            self._seed += 1
            plan = solve_plan(
                models, instances, rpm_fn, seed=self._seed,
                constraints=self.constraints,
            )
            plan.generation = self._seed
            self._plan = plan
            log.info(
                "placement plan refreshed: %d models x %d instances in %.1f ms",
                len(plan.placements), len(instances), plan.solve_ms,
            )
            return plan

    def adopt(self, plan: Optional[GlobalPlan]) -> None:
        """Install a plan published by the leader (watch-fed; None clears).

        Adoption order is the KV watch's event order — the store serializes
        publishes, so the latest delivered plan is the freshest and no
        generation comparison against a possibly-restarted leader is needed.
        """
        self._plan = plan

    # -- SPI ----------------------------------------------------------------

    def choose_load_target(
        self, req: PlacementRequest, view: ClusterView
    ) -> Optional[str]:
        plan = self._plan
        if plan is not None and plan.age_ms() <= self.plan_ttl_ms:
            desired = plan.placements.get(req.model_id)
            if desired:
                live = {iid for iid, rec in view.placeable()}
                for iid in desired:
                    if iid in req.exclude or iid not in live:
                        continue
                    if iid in req.model.instance_ids:
                        continue  # already loaded there
                    return LOAD_HERE if iid == req.requesting_instance else iid
        return self.fallback.choose_load_target(req, view)

    def choose_serve_target(
        self, model: ModelRecord, view: ClusterView, exclude: frozenset[str]
    ) -> Optional[str]:
        # Serve balancing stays local/greedy: it needs fresh busyness, not a
        # global solve.
        return self.fallback.choose_serve_target(model, view, exclude)
