"""JAX global placement strategy: per-request decisions from a TPU-solved plan.

The north-star architecture (BASELINE.json): cluster state (registry +
instance advertisements + rates) is assembled into a PlacementProblem,
solved as one batched Sinkhorn/auction assignment on the accelerator
(ops/solve.py single-chip, parallel/sharded_solver.py multi-chip), and the
resulting plan serves `choose_load_target` lookups until the next refresh.

Plans are ADVISORY (SURVEY.md section 7, hard part #4): per-instance local
guards (churn age, unload accounting, capacity) remain authoritative, and
any miss — model not in the plan, planned instances all excluded, plan
older than its TTL — falls back to the greedy oracle strategy. This mirrors
how the reference lets the placement heuristics be overridden per-decision
but never bypasses local admission control.
"""

from __future__ import annotations

import logging
import threading
import time
from collections.abc import Mapping
from typing import Callable, NamedTuple, Optional, Sequence, Union

RpmSource = Union[Callable[[str], int], Mapping[str, int]]

import numpy as np

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.placement.greedy import GreedyStrategy
from modelmesh_tpu.placement.strategy import (
    LOAD_HERE,
    ClusterView,
    PlacementRequest,
    PlacementStrategy,
)
from modelmesh_tpu.records import InstanceRecord, ModelRecord

log = logging.getLogger(__name__)


class ProblemColumns(NamedTuple):
    """Columnar host snapshot of cluster state — O(N + M + nnz + T·M) bytes.

    The dense [N, M] arrays (loaded/feasible/preferred) are NOT materialized
    on the host: at the 100k×1k tier they total ~300 MB and would dominate
    both assembly time and the host→device transfer (which on a remote-TPU
    link is the whole budget). Instead the snapshot carries loaded as COO
    index pairs and the type-constraint masks as one [T, M] row pattern per
    model type plus a [N] type index; ``_expand_problem_device`` expands
    them on the device where the expansion is an HBM-bandwidth memset.
    """

    model_ids: list
    instance_ids: list
    sizes: np.ndarray       # f32[N]
    copies: np.ndarray      # i32[N]
    rates: np.ndarray       # f32[N]
    loaded_rows: np.ndarray  # i32[nnz] COO of the loaded matrix
    loaded_cols: np.ndarray  # i32[nnz]
    type_idx: np.ndarray    # i32[N] model -> type row in the masks
    req_masks: np.ndarray   # bool[T, M] hard type-constraint rows
    pref_masks: np.ndarray  # bool[T, M] soft preference rows
    capacity: np.ndarray    # f32[M]
    reserved: np.ndarray    # f32[M]
    lru_age: np.ndarray     # f32[M]
    busy: np.ndarray        # f32[M]
    zone: np.ndarray        # i32[M]
    placeable: np.ndarray   # bool[M] not shutting down / not disabled


def snapshot_columns(
    models: Sequence[tuple[str, ModelRecord]],
    instances: Sequence[tuple[str, InstanceRecord]],
    rpm_fn: Optional[RpmSource] = None,
    default_size_units: int = 128,
    max_copies: int = 8,
    constraints=None,
) -> ProblemColumns:
    """Vectorized snapshot: one C-speed pass per column, no per-model Python
    loop bodies (round-2 VERDICT weak #2 — the old row loop cost seconds at
    100k models, dwarfing the device solve it fed)."""
    model_ids = [mid for mid, _ in models]
    instance_ids = [iid for iid, _ in instances]
    n, m = len(model_ids), len(instance_ids)
    inst_index = {iid: j for j, iid in enumerate(instance_ids)}
    zones = sorted({rec.zone for _, rec in instances})
    zone_id = {z: i for i, z in enumerate(zones)}
    now = now_ms()

    recs = [mr for _, mr in models]
    sizes = np.fromiter(
        (mr.size_units or default_size_units for mr in recs), np.float32, n
    )
    copies = np.clip(
        np.fromiter((mr.copy_count for mr in recs), np.int64, n),
        1, max_copies,
    ).astype(np.int32)
    last_used = np.fromiter((mr.last_used for mr in recs), np.int64, n)
    if rpm_fn is None:
        rpm = np.zeros(n, np.float32)
    else:
        lookup = rpm_fn.get if isinstance(rpm_fn, Mapping) else rpm_fn
        rpm = np.fromiter((lookup(mid) or 0 for mid in model_ids), np.float32, n)
    # Recency proxy where the rate view reads 0 (rpm_fn is typically the
    # refresher's *local* rate view, blind to models served elsewhere).
    age_min = np.maximum(0.0, (now - last_used) / 60_000.0)
    rates = np.where(rpm > 0, rpm, 1000.0 / (1.0 + age_min)).astype(np.float32)

    pairs = [
        (i, inst_index[iid])
        for i, mr in enumerate(recs)
        for iid in mr.instance_ids
        if iid in inst_index
    ]
    if pairs:
        loaded_rows = np.fromiter((p[0] for p in pairs), np.int32, len(pairs))
        loaded_cols = np.fromiter((p[1] for p in pairs), np.int32, len(pairs))
    else:
        loaded_rows = np.empty(0, np.int32)
        loaded_cols = np.empty(0, np.int32)

    # Type-constraint masks: one [M] row pattern per distinct model type
    # (`required` is a hard mask, `preferred` a soft cost term); models
    # reference their type's row via type_idx. T is small (#types), so the
    # Python work here is O(T·M), not O(N·M).
    tmap: dict[str, int] = {}
    type_idx = np.fromiter(
        (tmap.setdefault(mr.model_type, len(tmap)) for mr in recs),
        np.int32, n,
    )
    t = max(1, len(tmap))
    if constraints is not None and tmap:
        req_masks = np.empty((t, m), bool)
        pref_masks = np.empty((t, m), bool)
        for mtype, ti in tmap.items():
            for j, (_, rec) in enumerate(instances):
                req_masks[ti, j] = constraints.is_candidate(mtype, rec.labels)
                pref_masks[ti, j] = constraints.is_preferred(mtype, rec.labels)
    else:
        req_masks = np.ones((t, m), bool)
        pref_masks = np.ones((t, m), bool)

    irecs = [rec for _, rec in instances]
    capacity = np.maximum(
        np.fromiter((rec.capacity_units for rec in irecs), np.float32, m), 1.0
    )
    used = np.fromiter((rec.used_units for rec in irecs), np.float32, m)
    # reserved = advertised usage not attributable to managed (loaded) mass.
    managed = np.bincount(
        loaded_cols, weights=sizes[loaded_rows], minlength=m
    ).astype(np.float32) if m else np.empty(0, np.float32)
    reserved = np.maximum(0.0, used - managed)
    lru_ts = np.fromiter((rec.lru_ts for rec in irecs), np.int64, m)
    lru_age = np.where(
        lru_ts > 0, np.maximum(0.0, (now - lru_ts) / 1000.0), 0.0
    ).astype(np.float32)
    busy = np.fromiter((rec.req_per_minute for rec in irecs), np.float32, m)
    zone = np.fromiter((zone_id[rec.zone] for rec in irecs), np.int32, m)
    placeable = np.fromiter(
        (not rec.shutting_down and not rec.disabled for rec in irecs), bool, m
    )
    return ProblemColumns(
        model_ids, instance_ids, sizes, copies, rates, loaded_rows,
        loaded_cols, type_idx, req_masks, pref_masks, capacity, reserved,
        lru_age, busy, zone, placeable,
    )


def _bucket(x: int, floor: int = 256) -> int:
    """Next padded size: powers of two plus three-quarter points (≤33%
    overhead). Stable shapes keep solve_placement's jit cache warm across
    refreshes — without padding every model-count change recompiles
    (~20-40 s on TPU)."""
    if x <= floor:
        return floor
    p = 1 << (x - 1).bit_length()  # next power of two >= x
    three_q = (p // 4) * 3
    return three_q if x <= three_q else p


def _expand_problem_device(cols: ProblemColumns, pad: bool, mesh=None):
    """Build the PlacementProblem ON DEVICE from columnar inputs.

    With ``pad=True``, N/M/nnz are padded to buckets; padded rows are inert
    (sizes=0, copies=0 → zero transport mass, zero valid copies) and padded
    columns are inert (placeable=False → infeasible, free capacity 0).
    Norm-sensitive vectors (rates/busy/lru_age) pad with their real minimum
    so _minmax_norm of the real entries is unchanged by padding.

    With ``mesh``, the assembled problem comes out with the sharded
    solver's layout (model-axis arrays on ``mdl``, instance-axis on
    ``inst``, matrices on both) — GSPMD partitions the expansion so no
    device materializes the full [N, M] masks.
    """
    import jax.numpy as jnp

    n, m = len(cols.model_ids), len(cols.instance_ids)
    nnz = len(cols.loaded_rows)
    if pad:
        n_p, m_p, nnz_p = _bucket(n), _bucket(m, 64), _bucket(max(nnz, 1), 64)
    else:
        n_p, m_p, nnz_p = n, m, max(nnz, 0)

    def padv(a, size, fill):
        if size == len(a):
            return a
        out = np.full(size, fill, a.dtype)
        out[: len(a)] = a
        return out

    min_or = lambda a, d: float(a.min()) if len(a) else d  # noqa: E731
    sizes = padv(cols.sizes, n_p, 0.0)
    copies = padv(cols.copies, n_p, 0)
    rates = padv(cols.rates, n_p, min_or(cols.rates, 0.0))
    type_idx = padv(cols.type_idx, n_p, 0)
    # Padded COO entries point past the padded row range: scatter-drop.
    rows = padv(cols.loaded_rows, nnz_p, n_p)
    ccols = padv(cols.loaded_cols, nnz_p, 0)
    capacity = padv(cols.capacity, m_p, 1.0)
    reserved = padv(cols.reserved, m_p, 1.0)
    lru_age = padv(cols.lru_age, m_p, min_or(cols.lru_age, 0.0))
    busy = padv(cols.busy, m_p, min_or(cols.busy, 0.0))
    zone = padv(cols.zone, m_p, 0)
    placeable = padv(cols.placeable, m_p, False)
    req_masks = cols.req_masks
    pref_masks = cols.pref_masks
    if m_p != m:
        req_masks = np.pad(req_masks, ((0, 0), (0, m_p - m)))
        pref_masks = np.pad(pref_masks, ((0, 0), (0, m_p - m)))
    return _ensure_assemble_jit(mesh)(
        jnp.asarray(sizes), jnp.asarray(copies), jnp.asarray(rates),
        jnp.asarray(rows), jnp.asarray(ccols), jnp.asarray(type_idx),
        jnp.asarray(req_masks), jnp.asarray(pref_masks),
        jnp.asarray(capacity), jnp.asarray(reserved), jnp.asarray(lru_age),
        jnp.asarray(busy), jnp.asarray(zone), jnp.asarray(placeable),
    )


def _assemble(sizes, copies, rates, rows, ccols, type_idx, req_masks,
              pref_masks, capacity, reserved, lru_age, busy, zone, placeable):
    import jax.numpy as jnp

    from modelmesh_tpu.ops.costs import PlacementProblem

    n, m = sizes.shape[0], capacity.shape[0]
    loaded = jnp.zeros((n, m), bool).at[rows, ccols].set(True, mode="drop")
    feasible = req_masks[type_idx] & placeable[None, :]
    preferred = pref_masks[type_idx]
    return PlacementProblem(
        sizes=sizes, copies=copies, rates=rates, loaded=loaded,
        feasible=feasible, capacity=capacity, reserved=reserved,
        lru_age=lru_age, busyness=busy, zone=zone, preferred=preferred,
    )


_assemble_jits: dict = {}  # keyed by mesh (None = default device)


def _ensure_assemble_jit(mesh=None):
    fn = _assemble_jits.get(mesh)
    if fn is None:
        import jax

        if mesh is None:
            fn = jax.jit(_assemble)
        else:
            from modelmesh_tpu.parallel.mesh import problem_shardings

            fn = jax.jit(_assemble, out_shardings=problem_shardings(mesh))
        _assemble_jits[mesh] = fn
    return fn


_sharded_solvers: dict = {}


def _solver_for(mesh, config=None):
    """jitted sharded solver per (mesh, config) (rebuilding would
    recompile)."""
    key = (mesh, config)
    solver = _sharded_solvers.get(key)
    if solver is None:
        from modelmesh_tpu.parallel.sharded_solver import make_sharded_solver

        solver = _sharded_solvers[key] = make_sharded_solver(
            mesh, *(() if config is None else (config,))
        )
    return solver


def solve_config_from_env():
    """SolveConfig overridden by the MM_SOLVER_* operator knobs.

    Returns the plain default config when nothing is set, so the jit
    static-arg cache key stays the literal SolveConfig() default."""
    from modelmesh_tpu.ops.solve import SolveConfig
    from modelmesh_tpu.utils import envs

    base = SolveConfig()
    overrides = {}
    for field, env, cast in (
        ("sinkhorn_iters", "MM_SOLVER_SINKHORN_ITERS", int),
        ("auction_iters", "MM_SOLVER_AUCTION_ITERS", int),
        ("tau", "MM_SOLVER_TAU", float),
        ("lse_impl", "MM_SOLVER_LSE_IMPL", str),
        ("load_impl", "MM_SOLVER_LOAD_IMPL", str),
        ("noise_impl", "MM_SOLVER_NOISE_IMPL", str),
        ("final_select", "MM_SOLVER_FINAL_SELECT", str),
    ):
        raw = envs.get(env)
        if raw not in (None, ""):
            overrides[field] = cast(raw)
    return base._replace(**overrides) if overrides else base


def build_problem(
    models: Sequence[tuple[str, ModelRecord]],
    instances: Sequence[tuple[str, InstanceRecord]],
    rpm_fn: Optional[RpmSource] = None,
    default_size_units: int = 128,
    max_copies: int = 8,
    constraints=None,
    pad: bool = False,
):
    """Assemble a PlacementProblem from registry/instance snapshots.

    Returns (problem, model_ids, instance_ids) — the id lists map array rows
    and columns back to the mesh. Zone names are densified to ids. With
    ``pad=True`` the arrays are bucket-padded (see _expand_problem_device);
    callers must slice solver output back to len(model_ids).
    """
    cols = snapshot_columns(
        models, instances, rpm_fn, default_size_units, max_copies, constraints
    )
    problem = _expand_problem_device(cols, pad=pad)
    return problem, cols.model_ids, cols.instance_ids


class GlobalPlan:
    """Solved assignment: model -> ordered preferred instances.

    Plans travel: the leader solves and publishes the serialized plan to the
    KV store (placement/plan_sync.py) and every instance adopts it from a
    watch — the analog of the reference's leader-computed placement
    decisions propagating via the shared registry (ModelMesh.java:6616-6747),
    except here the whole assignment ships as one artifact. ``age_ms`` is
    measured from *local adoption time* so follower TTLs don't depend on
    clock agreement with the leader: a dead leader stops publishing and
    plans expire everywhere on their own clocks.
    """

    def __init__(
        self, placements: Optional[dict[str, list[str]]], solved_at_ms: int,
        solve_ms: float, generation: int = 0,
    ):
        self._placements = placements
        # Columnar alternative representation (from_columnar / from_bytes
        # v2): (model_ids, counts u8[n], flat instance indices, inst_ids).
        # The 100k-entry dict-of-lists is only materialized if someone asks
        # for `.placements` — the solve -> publish path never does, which
        # keeps ~2-400 ms of Python object churn out of the refresh loop.
        self._columnar: Optional[tuple[list, np.ndarray, np.ndarray, list]] = None
        self._index: Optional[dict[str, int]] = None
        self._offsets: Optional[np.ndarray] = None
        self.solved_at_ms = solved_at_ms
        self.solve_ms = solve_ms
        self.generation = generation
        self.adopted_at_ms = solved_at_ms
        # Local-only stage timings from solve_plan (not serialized).
        self.stats: dict[str, float] = {}
        # Per-instance column potentials for warm-starting the next solve
        # (local-only: followers never need it, only the refresher does).
        self.warm_g: Optional[dict[str, float]] = None

    @classmethod
    def from_columnar(
        cls, model_ids: list, counts: np.ndarray, flat: np.ndarray,
        inst_ids: list, solved_at_ms: int, solve_ms: float,
        generation: int = 0,
    ) -> "GlobalPlan":
        """Wrap solver output without building the per-model dict.

        ``counts[i]`` targets for model ``model_ids[i]`` live at
        ``flat[offsets[i]:offsets[i]+counts[i]]`` (indices into inst_ids).
        """
        counts = np.asarray(counts)
        if counts.size and int(counts.max()) > 255:
            # u8 casts below would wrap silently and desynchronize the flat
            # index stream for every later model (wire corruption). Nothing
            # upstream produces >255 targets (auction caps at MAX_COPIES=8),
            # so treat it as a caller bug, loudly.
            raise ValueError("per-model target count exceeds 255")
        plan = cls(None, solved_at_ms, solve_ms, generation)
        plan._columnar = (model_ids, counts.astype(np.uint8),
                          np.asarray(flat), inst_ids)
        return plan

    @property
    def placements(self) -> dict[str, list[str]]:
        if self._placements is None:
            model_ids, counts, flat, inst_ids = self._columnar
            flat_list = flat.tolist()
            placements: dict[str, list[str]] = {}
            pos = 0
            for mid, c in zip(model_ids, counts.tolist()):
                placements[mid] = [inst_ids[j] for j in flat_list[pos:pos + c]]
                pos += c
            self._placements = placements
        return self._placements

    def num_models(self) -> int:
        if self._placements is not None:
            return len(self._placements)
        return len(self._columnar[0])

    def ensure_index(self) -> None:
        """Build the lookup index eagerly (PlanFollower calls this from the
        watch thread so the first routed request never pays for it)."""
        if self._columnar is not None and self._index is None:
            model_ids, counts, _, _ = self._columnar
            off = np.zeros(len(model_ids) + 1, np.int64)
            np.cumsum(counts, out=off[1:])
            # _offsets before _index: concurrent lock-free lookup()s treat a
            # non-None _index as "ready" and immediately read _offsets.
            self._offsets = off
            self._index = {mid: i for i, mid in enumerate(model_ids)}

    def lookup(self, model_id: str) -> Optional[list[str]]:
        """Targets for one model (routing hot path; no full dict needed)."""
        if self._placements is not None:
            return self._placements.get(model_id)
        self.ensure_index()
        row = self._index.get(model_id)
        if row is None:
            return None
        _, counts, flat, inst_ids = self._columnar
        # int() both operands: python_int + np.uint8 coerces INTO uint8
        # under NumPy 2 and overflows at offset 256.
        start = int(self._offsets[row])
        end = start + int(counts[row])
        return [inst_ids[j] for j in flat[start:end].tolist()]

    def truncate(self, keep: int) -> "GlobalPlan":
        """First ``keep`` models (placement order = hottest first), for the
        publisher's byte-budget trim."""
        if self._columnar is not None:
            model_ids, counts, flat, inst_ids = self._columnar
            cut = int(np.sum(counts[:keep], dtype=np.int64))
            flat_cut = flat[:cut]
            # Re-index against only the instances the kept rows reference:
            # the publisher's byte-budget trim relies on the payload
            # actually shrinking, and a full fleet-sized id table would put
            # a floor under it.
            used = np.unique(flat_cut)
            plan = GlobalPlan.from_columnar(
                model_ids[:keep], counts[:keep],
                np.searchsorted(used, flat_cut),
                [inst_ids[int(j)] for j in used],
                self.solved_at_ms, self.solve_ms, self.generation,
            )
        else:
            items = list(self._placements.items())[:keep]
            plan = GlobalPlan(
                dict(items), self.solved_at_ms, self.solve_ms, self.generation
            )
        plan.adopted_at_ms = self.adopted_at_ms
        return plan

    def age_ms(self) -> int:
        return now_ms() - self.adopted_at_ms

    # -- wire format -------------------------------------------------------
    #
    # Columnar binary v2 (zlib'd): header JSON + instance-id table +
    # model-id table (placement order preserved — publish_plan's tail
    # truncation depends on hottest-first ordering) + per-model copy
    # counts (u8) + flattened instance indices (u16/u32 by fleet size).
    # At 100k models this serializes ~10x faster and ~3x smaller than the
    # v1 JSON dict (which cost 300-500 ms per publish — a large slice of
    # the whole e2e refresh). from_bytes still decodes v1 payloads so a
    # mixed-version fleet keeps adopting during a rolling update.

    _MAGIC_V2 = b"MMP2"

    def to_bytes(self) -> bytes:
        import json
        import zlib

        if self._columnar is not None and self._placements is None:
            # Columnar fast path: the solver's arrays serialize directly —
            # no dict walk, no inst-table rebuild.
            model_ids, counts, flat, inst_ids = self._columnar
            if not any("\n" in s for s in model_ids) and not any(
                "\n" in s for s in inst_ids
            ):
                idx_dtype = (
                    np.uint16 if len(inst_ids) < 65_536 else np.uint32
                )
                return self._pack_v2(
                    inst_ids, model_ids, counts,
                    np.asarray(flat, idx_dtype), idx_dtype,
                )
            # fall through to the dict path (materializes placements)
        # Newlines delimit the id tables and copy counts ride a u8 column;
        # a pathological id containing "\n" or a row with >255 targets
        # (nothing upstream produces either, but the format must not
        # corrupt) falls back to the JSON encoding.
        if any(
            len(kv[1]) > 255 or "\n" in kv[0] or any("\n" in t for t in kv[1])
            for kv in self.placements.items()
        ):
            payload = json.dumps({
                "g": self.generation, "t": self.solved_at_ms,
                "ms": self.solve_ms, "p": self.placements,
            }, separators=(",", ":"))
            return zlib.compress(payload.encode(), level=1)
        inst_table: dict[str, int] = {}
        counts = np.empty(len(self.placements), np.uint8)
        flat: list[int] = []
        for i, targets in enumerate(self.placements.values()):
            counts[i] = len(targets)
            for t in targets:
                flat.append(inst_table.setdefault(t, len(inst_table)))
        idx_dtype = np.uint16 if len(inst_table) < 65_536 else np.uint32
        return self._pack_v2(
            list(inst_table), list(self.placements), counts,
            np.asarray(flat, idx_dtype), idx_dtype,
        )

    def _pack_v2(self, inst_ids, model_ids, counts, flat, idx_dtype) -> bytes:
        import json
        import zlib

        header = json.dumps({
            "g": self.generation, "t": self.solved_at_ms,
            "ms": self.solve_ms, "n": len(model_ids),
            "w": int(np.dtype(idx_dtype).itemsize),
        }, separators=(",", ":")).encode()

        def framed(b: bytes) -> list[bytes]:
            return [len(b).to_bytes(4, "big"), b]

        parts = [
            self._MAGIC_V2,
            *framed(header),
            *framed("\n".join(inst_ids).encode()),
            *framed("\n".join(model_ids).encode()),
            np.ascontiguousarray(counts, np.uint8).tobytes(),
            np.ascontiguousarray(flat, idx_dtype).tobytes(),
        ]
        return zlib.compress(b"".join(parts), level=1)

    @classmethod
    def from_bytes(cls, data: bytes) -> "GlobalPlan":
        import json
        import zlib

        raw = zlib.decompress(data)
        if not raw.startswith(cls._MAGIC_V2):
            # v1: zlib'd JSON dict (pre-round-3 leaders).
            d = json.loads(raw.decode())
            plan = cls(d["p"], d["t"], d["ms"], d.get("g", 0))
            plan.adopted_at_ms = now_ms()
            return plan
        off = len(cls._MAGIC_V2)

        def take(n):
            nonlocal off
            out = raw[off:off + n]
            off += n
            return out

        hlen = int.from_bytes(take(4), "big")
        h = json.loads(take(hlen).decode())
        inst_ids = take(int.from_bytes(take(4), "big")).decode().split("\n")
        model_blob = take(int.from_bytes(take(4), "big")).decode()
        model_ids = model_blob.split("\n") if model_blob else []
        n = h["n"]
        counts = np.frombuffer(take(n), np.uint8)
        idx_dtype = np.uint16 if h["w"] == 2 else np.uint32
        flat = np.frombuffer(raw[off:], idx_dtype)
        # Stay columnar: followers route via lookup(); the dict-of-lists is
        # only built if a consumer iterates .placements.
        plan = cls.from_columnar(
            model_ids, counts, flat, inst_ids, h["t"], h["ms"], h.get("g", 0)
        )
        plan.adopted_at_ms = now_ms()
        return plan


def solve_plan(
    models: Sequence[tuple[str, ModelRecord]],
    instances: Sequence[tuple[str, InstanceRecord]],
    rpm_fn: Optional[RpmSource] = None,
    seed: int = 0,
    constraints=None,
    mesh=None,
    warm_g: Optional[Mapping[str, float]] = None,
    config=None,
) -> GlobalPlan:
    """One global solve -> GlobalPlan (blocking; runs on the JAX device).

    Stage timings land in ``plan.stats`` (snapshot / device solve / plan
    extraction, milliseconds) — the e2e refresh cost, not just the kernel
    (round-2 VERDICT weak #2). Shapes are bucket-padded so consecutive
    refreshes with drifting model counts reuse the compiled solver.

    ``mesh``: a parallel.mesh device mesh shards the solve across chips
    (parallel/sharded_solver.py) — the 1M x 10k ladder path. Bucket sizes
    are powers of two or 3·2^k, so any power-of-two mesh axis ≤ the pad
    floors (256 rows, 64 cols) divides them evenly.

    ``config``: a SolveConfig overriding the solver defaults (None keeps
    the compiled-default cache entry). The strategy builds one from the
    MM_SOLVER_* env knobs (solve_config_from_env).

    ``warm_g``: per-instance-id column potentials from the previous solve
    (``plan.warm_g``) — warm-starts Sinkhorn (SURVEY.md section 7 hard
    part #4, incremental solves as state churns). Only g needs carrying:
    the first iteration derives f entirely from g, and keying by instance
    id makes the carry robust to models/instances joining or leaving.
    """
    import jax

    from modelmesh_tpu.ops.solve import solve_placement

    if not models or not instances:
        return GlobalPlan({}, now_ms(), 0.0)
    t0 = time.perf_counter()
    cols = snapshot_columns(models, instances, rpm_fn, constraints=constraints)
    t1 = time.perf_counter()
    # Warm-start column potentials, id-aligned to this snapshot's column
    # order; instances unknown to the carry (new pods) start at 0 = cold.
    # ALWAYS materialized (zeros = cold): switching the jitted solve's
    # init between None and an array would change the argument pytree and
    # force a full recompile on the first warm refresh.
    g0 = np.zeros(_bucket(len(cols.instance_ids), 64), np.float32)
    if warm_g:
        for j, iid in enumerate(cols.instance_ids):
            g0[j] = warm_g.get(iid, 0.0)
    if mesh is not None:
        from modelmesh_tpu.parallel.mesh import INSTANCE_AXIS, MODEL_AXIS

        if MODEL_AXIS not in mesh.shape or INSTANCE_AXIS not in mesh.shape:
            raise ValueError(
                f"mesh axes {tuple(mesh.shape)} != "
                f"({MODEL_AXIS!r}, {INSTANCE_AXIS!r}); build with "
                "parallel.mesh.make_mesh"
            )
        n_mdl, n_inst = mesh.shape[MODEL_AXIS], mesh.shape[INSTANCE_AXIS]
        if _bucket(len(cols.model_ids)) % n_mdl or (
            _bucket(len(cols.instance_ids), 64) % n_inst
        ):
            raise ValueError(
                f"mesh {dict(mesh.shape)} does not divide the padded problem"
            )
        problem = _expand_problem_device(cols, pad=True, mesh=mesh)
        sol = jax.block_until_ready(
            _solver_for(mesh, config)(problem, seed=seed, g0=g0)
        )
    else:
        from modelmesh_tpu.ops.solve import SolveInit

        problem = _expand_problem_device(cols, pad=True)
        kw = {} if config is None else {"config": config}
        sol = jax.block_until_ready(
            solve_placement(problem, seed=seed, init=SolveInit(g0=g0), **kw)
        )
    t2 = time.perf_counter()
    # Compact readback: u16 indices + per-row valid counts instead of the
    # raw i32[N,K] + bool[N,K] (2.1 MB vs 5.2 MB at the padded 100k tier —
    # the D2H link, not the solve, dominates the refresh on a remote
    # device). `valid` is a prefix mask by construction (slot < copies is a
    # prefix; top-k values are descending so the threshold cut is too), so
    # counts lose nothing. Pinned by test_jax_engine's compact-vs-mask test.
    packed_dev = _compact_result(
        sol, narrow=len(cols.instance_ids) < 65_536
    )
    packed = jax.device_get(packed_dev)
    n = len(cols.model_ids)
    idxa = packed[:n, :-1]
    counts = packed[:n, -1].astype(np.uint8)
    # Hottest-first order: publish_plan truncates from the tail under its
    # byte budget, so the models that lose central placement must be the
    # coldest, not whichever ones the registry iterated last.
    order = np.argsort(-cols.rates, kind="stable")
    idxo = idxa[order]
    counts = counts[order]
    valid = np.arange(idxo.shape[1], dtype=np.uint8)[None, :] < counts[:, None]
    flat = idxo[valid]
    model_ids = [cols.model_ids[i] for i in order.tolist()]
    t3 = time.perf_counter()
    plan = GlobalPlan.from_columnar(
        model_ids, counts, flat, cols.instance_ids, now_ms(), (t3 - t0) * 1e3
    )
    plan.stats = {
        "snapshot_ms": (t1 - t0) * 1e3,
        "solve_ms": (t2 - t1) * 1e3,
        "extract_ms": (t3 - t2) * 1e3,
        "warm": bool(warm_g),
    }
    # Warm-start carry for the NEXT refresh (~4 KB at 1k instances).
    if sol.g is not None:
        g_host = np.asarray(jax.device_get(sol.g))[: len(cols.instance_ids)]
        plan.warm_g = dict(
            zip(cols.instance_ids, g_host.astype(float).tolist())
        )
    return plan


_compact_jits: dict = {}


def _compact_result(sol, narrow: bool):
    """Jitted epilogue shrinking the solver result before D2H transfer.

    Packs indices and per-row valid counts into ONE [N, K+1] array so the
    readback is a single transfer — on a remote-device link every array
    costs a full round trip (~65 ms on the measured axon tunnel), which
    dwarfs the extra byte-per-row of carrying counts at index width."""
    import jax
    import jax.numpy as jnp

    fn = _compact_jits.get(narrow)
    if fn is None:
        dtype = jnp.uint16 if narrow else jnp.int32

        def compact(idx, valid):
            cnt = valid.sum(1).astype(dtype)
            return jnp.concatenate(
                [idx.astype(dtype), cnt[:, None]], axis=1
            )

        fn = _compact_jits[narrow] = jax.jit(compact)
    return fn(sol.indices, sol.valid)


class JaxPlacementStrategy(PlacementStrategy):
    """Plan-serving strategy with greedy fallback.

    ``refresher`` mode: call ``refresh(models, instances, rpm_fn)``
    periodically — in production the leader reaper does this and
    publishes the result fleet-wide (serving/tasks.py); followers adopt
    via PlanFollower. Decisions read the latest plan lock-free.
    """

    def __init__(
        self,
        # Must exceed the publish cadence (the leader reaper's
        # reaper_interval_s, default 420 s) or followers spend most of each
        # cycle TTL-expired and silently serving greedy.
        plan_ttl_ms: int = 15 * 60_000,
        fallback: Optional[PlacementStrategy] = None,
        constraints=None,
        mesh=None,
        solve_config="env",
    ):
        self.plan_ttl_ms = plan_ttl_ms
        self.fallback = fallback or GreedyStrategy()
        # serving/constraints.TypeConstraints — attached by the instance
        # (like greedy's) so solves honor required masks and preferred
        # labels (build_problem feasible/preferred).
        self.constraints = constraints
        # mesh=None solves on the default device; mesh="auto" shards
        # refreshes across all visible devices (multi-chip leader hosts —
        # the 1M x 10k ladder tier); a parallel.mesh Mesh is explicit.
        # Opt-in rather than defaulted: an instance's JAX devices are not
        # necessarily a placement-solver pool.
        if mesh == "auto":
            import jax

            from modelmesh_tpu.parallel.mesh import make_mesh

            devs = jax.devices()
            # Largest power-of-two subset: bucket-padded shapes are 2^k or
            # 3·2^k, so power-of-two axes always divide them; a 6- or
            # 12-device host must not turn every refresh into a ValueError.
            usable = 1 << (len(devs).bit_length() - 1)
            mesh = make_mesh(devices=devs[:usable]) if usable > 1 else None
        self.mesh = mesh
        # "env" -> MM_SOLVER_* knobs (solve_config_from_env); None -> the
        # compiled defaults; or an explicit SolveConfig.
        if solve_config == "env":
            cfg = solve_config_from_env()
            from modelmesh_tpu.ops.solve import SolveConfig

            solve_config = None if cfg == SolveConfig() else cfg
        self.solve_config = solve_config
        self._plan: Optional[GlobalPlan] = None
        self._seed = 0
        self._refresh_lock = threading.Lock()
        # Column-potential carry across refreshes (solve_plan warm_g).
        self._warm_g: Optional[dict[str, float]] = None

    @property
    def plan(self) -> Optional[GlobalPlan]:
        return self._plan

    def refresh(
        self,
        models: Sequence[tuple[str, ModelRecord]],
        instances: Sequence[tuple[str, InstanceRecord]],
        rpm_fn: Optional[RpmSource] = None,
    ) -> GlobalPlan:
        with self._refresh_lock:
            self._seed += 1
            plan = solve_plan(
                models, instances, rpm_fn, seed=self._seed,
                constraints=self.constraints, mesh=self.mesh,
                warm_g=self._warm_g, config=self.solve_config,
            )
            if plan.warm_g is not None:
                # Keep the carry across empty-snapshot blips (registry
                # rebuild / watch reconnect): a transiently empty refresh
                # must not force the next real solve cold.
                self._warm_g = plan.warm_g
            plan.generation = self._seed
            self._plan = plan
            log.info(
                "placement plan refreshed: %d models x %d instances in %.1f ms",
                plan.num_models(), len(instances), plan.solve_ms,
            )
            return plan

    def adopt(self, plan: Optional[GlobalPlan]) -> None:
        """Install a plan published by the leader (watch-fed; None clears).

        Adoption order is the KV watch's event order — the store serializes
        publishes, so the latest delivered plan is the freshest and no
        generation comparison against a possibly-restarted leader is needed.
        """
        self._plan = plan

    # -- SPI ----------------------------------------------------------------

    def choose_load_target(
        self, req: PlacementRequest, view: ClusterView
    ) -> Optional[str]:
        plan = self._plan
        if plan is not None and plan.age_ms() <= self.plan_ttl_ms:
            desired = plan.lookup(req.model_id)
            if desired:
                live = {iid for iid, rec in view.placeable()}
                for iid in desired:
                    if iid in req.exclude or iid not in live:
                        continue
                    if iid in req.model.instance_ids:
                        continue  # already loaded there
                    return LOAD_HERE if iid == req.requesting_instance else iid
        return self.fallback.choose_load_target(req, view)

    def choose_serve_target(
        self, model: ModelRecord, view: ClusterView, exclude: frozenset[str]
    ) -> Optional[str]:
        # Serve balancing stays local/greedy: it needs fresh busyness, not a
        # global solve.
        return self.fallback.choose_serve_target(model, view, exclude)
