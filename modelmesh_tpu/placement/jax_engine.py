"""JAX global placement strategy: per-request decisions from a TPU-solved plan.

The north-star architecture (BASELINE.json): cluster state (registry +
instance advertisements + rates) is assembled into a PlacementProblem,
solved as one batched Sinkhorn/auction assignment on the accelerator
(ops/solve.py single-chip, parallel/sharded_solver.py multi-chip), and the
resulting plan serves `choose_load_target` lookups until the next refresh.

Plans are ADVISORY (SURVEY.md section 7, hard part #4): per-instance local
guards (churn age, unload accounting, capacity) remain authoritative, and
any miss — model not in the plan, planned instances all excluded, plan
older than its TTL — falls back to the greedy oracle strategy. This mirrors
how the reference lets the placement heuristics be overridden per-decision
but never bypasses local admission control.
"""

from __future__ import annotations

import copy
import dataclasses
import logging
import threading
import time
from collections import OrderedDict
from collections.abc import Mapping
from typing import Callable, NamedTuple, Optional, Sequence, Union

RpmSource = Union[Callable[[str], int], Mapping[str, int]]

import numpy as np

from modelmesh_tpu.cache.lru import now_ms
from modelmesh_tpu.placement.greedy import GreedyStrategy
from modelmesh_tpu.placement.strategy import (
    LOAD_HERE,
    ClusterView,
    PlacementRequest,
    PlacementStrategy,
)
from modelmesh_tpu.records import InstanceRecord, ModelRecord
from modelmesh_tpu.utils.lockdebug import mm_lock

log = logging.getLogger(__name__)


class ProblemColumns(NamedTuple):
    """Columnar host snapshot of cluster state — O(N + M + nnz + T·M) bytes.

    The dense [N, M] arrays (loaded/feasible/preferred) are NOT materialized
    on the host: at the 100k×1k tier they total ~300 MB and would dominate
    both assembly time and the host→device transfer (which on a remote-TPU
    link is the whole budget). Instead the snapshot carries loaded as COO
    index pairs and the type-constraint masks as one [T, M] row pattern per
    model type plus a [N] type index; ``_expand_problem_device`` expands
    them on the device where the expansion is an HBM-bandwidth memset.
    """

    model_ids: list
    instance_ids: list
    sizes: np.ndarray       # f32[N]
    copies: np.ndarray      # i32[N]
    rates: np.ndarray       # f32[N]
    loaded_rows: np.ndarray  # i32[nnz] COO of the loaded matrix
    loaded_cols: np.ndarray  # i32[nnz]
    type_idx: np.ndarray    # i32[N] model -> type row in the masks
    req_masks: np.ndarray   # bool[T, M] hard type-constraint rows
    pref_masks: np.ndarray  # bool[T, M] soft preference rows
    capacity: np.ndarray    # f32[M]
    reserved: np.ndarray    # f32[M]
    lru_age: np.ndarray     # f32[M]
    busy: np.ndarray        # f32[M]
    zone: np.ndarray        # i32[M]
    placeable: np.ndarray   # bool[M] not shutting down / not disabled


class SnapshotCache:
    """Everything needed to PATCH the last snapshot instead of rebuilding it
    (the delta-snapshot fast path). Holds the raw per-record inputs the
    derived ``ProblemColumns`` arrays were computed from (last_used /
    used / lru_ts; rpm is NOT cached — every patch re-reads it fresh from
    ``rpm_fn``) plus the id->index maps, so a steady-state refresh only
    touches the dirty records — O(dirty + nnz + M) instead of the full
    O(N) Python pass over every record.

    Mutation discipline: ``patch`` copies an array before changing it, so
    ProblemColumns handed out by earlier snapshots stay frozen even while
    an in-flight solve still reads them (the pipelined refresh overlap)."""

    __slots__ = (
        "cols", "last_used", "used", "lru_ts", "model_pos",
        "inst_pos", "zone_id", "tmap", "default_size_units", "max_copies",
        "constraints",
    )

    def __init__(self, cols, last_used, used, lru_ts, zone_id, tmap,
                 default_size_units, max_copies, constraints):
        self.cols = cols
        self.last_used = last_used
        self.used = used
        self.lru_ts = lru_ts
        self.model_pos = {mid: i for i, mid in enumerate(cols.model_ids)}
        self.inst_pos = {iid: j for j, iid in enumerate(cols.instance_ids)}
        self.zone_id = zone_id
        self.tmap = tmap
        self.constraints = constraints
        self.default_size_units = default_size_units
        self.max_copies = max_copies


def _rpm_column(rpm_fn: Optional[RpmSource], model_ids, n: int) -> np.ndarray:
    """Fresh per-model rpm read — shared by ``snapshot_columns`` and
    ``patch_columns`` (the delta path's contract is bit-identical output,
    including the all-zeros ``rpm_fn=None`` case)."""
    if rpm_fn is None:
        return np.zeros(n, np.float32)
    lookup = rpm_fn.get if isinstance(rpm_fn, Mapping) else rpm_fn
    return np.fromiter((lookup(mid) or 0 for mid in model_ids), np.float32, n)


def _derived_columns(rpm, last_used, sizes, loaded_rows, loaded_cols,
                     used, lru_ts, now, m: int):
    """Time/traffic-derived columns — one definition shared by
    ``snapshot_columns`` and ``patch_columns`` so a formula tweak cannot
    desync patched snapshots from full rebuilds. Returns
    (rates, reserved, lru_age)."""
    # Recency proxy where the rate view reads 0 (rpm_fn is typically the
    # refresher's *local* rate view, blind to models served elsewhere).
    age_min = np.maximum(0.0, (now - last_used) / 60_000.0)
    rates = np.where(rpm > 0, rpm, 1000.0 / (1.0 + age_min)).astype(np.float32)
    # reserved = advertised usage not attributable to managed (loaded) mass.
    managed = np.bincount(
        loaded_cols, weights=sizes[loaded_rows], minlength=m
    ).astype(np.float32) if m else np.empty(0, np.float32)
    reserved = np.maximum(0.0, used - managed)
    lru_age = np.where(
        lru_ts > 0, np.maximum(0.0, (now - lru_ts) / 1000.0), 0.0
    ).astype(np.float32)
    return rates, reserved, lru_age


def snapshot_columns(
    models: Sequence[tuple[str, ModelRecord]],
    instances: Sequence[tuple[str, InstanceRecord]],
    rpm_fn: Optional[RpmSource] = None,
    default_size_units: int = 128,
    max_copies: int = 8,
    constraints=None,
    return_cache: bool = False,
):
    """Vectorized snapshot: one C-speed pass per column, no per-model Python
    loop bodies (round-2 VERDICT weak #2 — the old row loop cost seconds at
    100k models, dwarfing the device solve it fed). With ``return_cache``
    the (cols, SnapshotCache) pair comes back so later refreshes can use
    ``patch_columns`` instead of a full rebuild."""
    model_ids = [mid for mid, _ in models]
    instance_ids = [iid for iid, _ in instances]
    n, m = len(model_ids), len(instance_ids)
    inst_index = {iid: j for j, iid in enumerate(instance_ids)}
    zones = sorted({rec.zone for _, rec in instances})
    zone_id = {z: i for i, z in enumerate(zones)}
    now = now_ms()

    recs = [mr for _, mr in models]
    sizes = np.fromiter(
        (mr.size_units or default_size_units for mr in recs), np.float32, n
    )
    copies = np.clip(
        np.fromiter((mr.copy_count for mr in recs), np.int64, n),
        1, max_copies,
    ).astype(np.int32)
    last_used = np.fromiter((mr.last_used for mr in recs), np.int64, n)
    rpm = _rpm_column(rpm_fn, model_ids, n)

    pairs = [
        (i, inst_index[iid])
        for i, mr in enumerate(recs)
        for iid in mr.instance_ids
        if iid in inst_index
    ]
    if pairs:
        loaded_rows = np.fromiter((p[0] for p in pairs), np.int32, len(pairs))
        loaded_cols = np.fromiter((p[1] for p in pairs), np.int32, len(pairs))
    else:
        loaded_rows = np.empty(0, np.int32)
        loaded_cols = np.empty(0, np.int32)

    # Type-constraint masks: one [M] row pattern per distinct model type
    # (`required` is a hard mask, `preferred` a soft cost term); models
    # reference their type's row via type_idx. T is small (#types), so the
    # Python work here is O(T·M), not O(N·M).
    tmap: dict[str, int] = {}
    type_idx = np.fromiter(
        (tmap.setdefault(mr.model_type, len(tmap)) for mr in recs),
        np.int32, n,
    )
    t = max(1, len(tmap))
    if constraints is not None and tmap:
        req_masks = np.empty((t, m), bool)
        pref_masks = np.empty((t, m), bool)
        for mtype, ti in tmap.items():
            for j, (_, rec) in enumerate(instances):
                req_masks[ti, j] = constraints.is_candidate(mtype, rec.labels)
                pref_masks[ti, j] = constraints.is_preferred(mtype, rec.labels)
    else:
        req_masks = np.ones((t, m), bool)
        pref_masks = np.ones((t, m), bool)

    irecs = [rec for _, rec in instances]
    capacity = np.maximum(
        np.fromiter((rec.capacity_units for rec in irecs), np.float32, m), 1.0
    )
    used = np.fromiter((rec.used_units for rec in irecs), np.float32, m)
    lru_ts = np.fromiter((rec.lru_ts for rec in irecs), np.int64, m)
    rates, reserved, lru_age = _derived_columns(
        rpm, last_used, sizes, loaded_rows, loaded_cols, used, lru_ts, now, m
    )
    busy = np.fromiter((rec.req_per_minute for rec in irecs), np.float32, m)
    zone = np.fromiter((zone_id[rec.zone] for rec in irecs), np.int32, m)
    placeable = np.fromiter(
        (not rec.shutting_down and not rec.disabled for rec in irecs), bool, m
    )
    cols = ProblemColumns(
        model_ids, instance_ids, sizes, copies, rates, loaded_rows,
        loaded_cols, type_idx, req_masks, pref_masks, capacity, reserved,
        lru_age, busy, zone, placeable,
    )
    if not return_cache:
        return cols
    return cols, SnapshotCache(
        cols, last_used, used, lru_ts, zone_id, tmap,
        default_size_units, max_copies, constraints,
    )


# Consecutive delta refreshes before JaxPlacementStrategy forces a full
# rebuild: bounds how long the frozen noise epoch can pin an unlucky
# Gumbel draw (and how long an unmarked-dirty record can stay stale)
# when perpetual small churn never trips the dirty-fraction fallback.
# At the default 1 s steady cadence this rotates the draw about once a
# minute — one cold-cost solve amortized over 63 fast ones.
MAX_DELTA_STREAK = 64

# Above this dirty fraction a patch stops paying: the per-record Python
# work approaches the full rebuild's, and the rebuild resets any drift.
MAX_DIRTY_FRAC = 0.25


def patch_columns(
    cache: SnapshotCache,
    models: Sequence[tuple[str, ModelRecord]],
    instances: Sequence[tuple[str, InstanceRecord]],
    rpm_fn: Optional[RpmSource] = None,
    dirty_models: Optional[set] = None,
    dirty_instances: Optional[set] = None,
    constraints=None,
    max_dirty_frac: float = MAX_DIRTY_FRAC,
):
    """Delta snapshot: patch the cached ``ProblemColumns`` for the dirty
    records only. Returns the new cols (and updates ``cache`` in place), or
    ``None`` when a patch is unsafe/unprofitable and the caller must fall
    back to a full ``snapshot_columns`` rebuild:

    - the model/instance lists changed shape (joins/leaves re-index rows
      and columns — the COO and the warm carries key off positions),
    - a dirty id is unknown or no longer at its cached position,
    - a dirty record introduces a new model type or zone (both would need
      new mask/id rows),
    - ``constraints`` is not the object the snapshot was built under
      (the cached masks' provenance must match),
    - the dirty fraction exceeds ``max_dirty_frac``.

    Callers must mark every changed record dirty (the tracking contract —
    ``JaxPlacementStrategy.mark_dirty``); unmarked changes go stale until
    the next full rebuild. Columns that can move WITHOUT a record change
    are recomputed for ALL records every patch: the time-derived ones
    (rates' recency proxy, lru_age) vectorized from the cached raw inputs,
    and rpm re-read from ``rpm_fn`` (traffic shifts don't touch records,
    so rpm staleness cannot be dirty-tracked — one dict get per model,
    a sliver of the full rebuild's per-record Python work)."""
    cols = cache.cols
    n, m = len(cols.model_ids), len(cols.instance_ids)
    if len(models) != n or len(instances) != m:
        return None
    if constraints is not cache.constraints:
        # The cached masks were built under a different constraints
        # object — patching dirty columns with the new one would mix
        # provenances; force a rebuild (which re-primes the cache).
        return None
    dm = dirty_models or set()
    di = dirty_instances or set()
    if (len(dm) + len(di)) > max_dirty_frac * (n + m):
        return None
    now = now_ms()

    sizes, copies, type_idx = cols.sizes, cols.copies, cols.type_idx
    last_used = cache.last_used
    # Fresh rpm for EVERYONE (shared _rpm_column): a model whose traffic
    # moved from 0 to hot without any record change would otherwise serve
    # a stale recency-proxy rate until the next full rebuild.
    rpm = _rpm_column(rpm_fn, cols.model_ids, n)
    loaded_rows, loaded_cols = cols.loaded_rows, cols.loaded_cols
    if dm:
        rows_i = []
        for mid in dm:
            i = cache.model_pos.get(mid)
            if i is None or models[i][0] != mid:
                return None
            mr = models[i][1]
            if mr.model_type not in cache.tmap:
                return None
            rows_i.append(i)
        sizes, copies, type_idx = (
            np.array(sizes), np.array(copies), np.array(type_idx)
        )
        last_used = np.array(last_used)
        for i in rows_i:
            mr = models[i][1]
            sizes[i] = mr.size_units or cache.default_size_units
            copies[i] = min(max(mr.copy_count, 1), cache.max_copies)
            last_used[i] = mr.last_used
            type_idx[i] = cache.tmap[mr.model_type]
        # COO patch: drop the dirty rows' pairs, append their fresh ones.
        dirty_idx = np.asarray(rows_i, np.int32)
        keep = ~np.isin(loaded_rows, dirty_idx)
        new_pairs = [
            (i, cache.inst_pos[iid])
            for i in rows_i
            for iid in models[i][1].instance_ids
            if iid in cache.inst_pos
        ]
        loaded_rows = np.concatenate([
            loaded_rows[keep],
            np.fromiter((p[0] for p in new_pairs), np.int32, len(new_pairs)),
        ])
        loaded_cols = np.concatenate([
            loaded_cols[keep],
            np.fromiter((p[1] for p in new_pairs), np.int32, len(new_pairs)),
        ])

    capacity, busy, zone, placeable = (
        cols.capacity, cols.busy, cols.zone, cols.placeable
    )
    used, lru_ts = cache.used, cache.lru_ts
    req_masks, pref_masks = cols.req_masks, cols.pref_masks
    if di:
        cols_j = []
        for iid in di:
            j = cache.inst_pos.get(iid)
            if j is None or instances[j][0] != iid:
                return None
            if instances[j][1].zone not in cache.zone_id:
                return None
            cols_j.append(j)
        capacity, busy, zone, placeable = (
            np.array(capacity), np.array(busy), np.array(zone),
            np.array(placeable),
        )
        used, lru_ts = np.array(used), np.array(lru_ts)
        patch_masks = constraints is not None and cache.tmap
        if patch_masks:
            req_masks = np.array(req_masks)
            pref_masks = np.array(pref_masks)
        for j in cols_j:
            rec = instances[j][1]
            capacity[j] = max(rec.capacity_units, 1.0)
            used[j] = rec.used_units
            lru_ts[j] = rec.lru_ts
            busy[j] = rec.req_per_minute
            zone[j] = cache.zone_id[rec.zone]
            placeable[j] = not rec.shutting_down and not rec.disabled
            if patch_masks:
                for mtype, ti in cache.tmap.items():
                    req_masks[ti, j] = constraints.is_candidate(
                        mtype, rec.labels
                    )
                    pref_masks[ti, j] = constraints.is_preferred(
                        mtype, rec.labels
                    )

    # Derived columns: recomputed VECTORIZED for everyone (shared
    # _derived_columns) — time moves for clean records too, and `reserved`
    # couples instances to the loaded mass of (possibly dirty) models.
    rates, reserved, lru_age = _derived_columns(
        rpm, last_used, sizes, loaded_rows, loaded_cols, used, lru_ts, now, m
    )

    new_cols = ProblemColumns(
        cols.model_ids, cols.instance_ids, sizes, copies, rates,
        loaded_rows, loaded_cols, type_idx, req_masks, pref_masks,
        capacity, reserved, lru_age, busy, zone, placeable,
    )
    cache.cols = new_cols
    cache.last_used = last_used
    cache.used, cache.lru_ts = used, lru_ts
    return new_cols


def _bucket(x: int, floor: int = 256) -> int:
    """Next padded size: powers of two plus three-quarter points (≤33%
    overhead). Stable shapes keep solve_placement's jit cache warm across
    refreshes — without padding every model-count change recompiles
    (~20-40 s on TPU)."""
    if x <= floor:
        return floor
    p = 1 << (x - 1).bit_length()  # next power of two >= x
    three_q = (p // 4) * 3
    return three_q if x <= three_q else p


def _expand_problem_device(cols: ProblemColumns, pad: bool, mesh=None):
    """Build the PlacementProblem ON DEVICE from columnar inputs.

    With ``pad=True``, N/M/nnz are padded to buckets; padded rows are inert
    (sizes=0, copies=0 → zero transport mass, zero valid copies) and padded
    columns are inert (placeable=False → infeasible, free capacity 0).
    Norm-sensitive vectors (rates/busy/lru_age) pad with their real minimum
    so _minmax_norm of the real entries is unchanged by padding.

    With ``mesh``, the assembled problem comes out with the sharded
    solver's layout (model-axis arrays on ``mdl``, instance-axis on
    ``inst``, matrices on both) — GSPMD partitions the expansion so no
    device materializes the full [N, M] masks.
    """
    import jax.numpy as jnp

    n, m = len(cols.model_ids), len(cols.instance_ids)
    nnz = len(cols.loaded_rows)
    if pad:
        n_p, m_p, nnz_p = _bucket(n), _bucket(m, 64), _bucket(max(nnz, 1), 64)
    else:
        n_p, m_p, nnz_p = n, m, max(nnz, 0)

    def padv(a, size, fill):
        if size == len(a):
            return a
        out = np.full(size, fill, a.dtype)
        out[: len(a)] = a
        return out

    min_or = lambda a, d: float(a.min()) if len(a) else d  # noqa: E731
    sizes = padv(cols.sizes, n_p, 0.0)
    copies = padv(cols.copies, n_p, 0)
    rates = padv(cols.rates, n_p, min_or(cols.rates, 0.0))
    type_idx = padv(cols.type_idx, n_p, 0)
    # Padded COO entries point past the padded row range: scatter-drop.
    rows = padv(cols.loaded_rows, nnz_p, n_p)
    ccols = padv(cols.loaded_cols, nnz_p, 0)
    capacity = padv(cols.capacity, m_p, 1.0)
    reserved = padv(cols.reserved, m_p, 1.0)
    lru_age = padv(cols.lru_age, m_p, min_or(cols.lru_age, 0.0))
    busy = padv(cols.busy, m_p, min_or(cols.busy, 0.0))
    zone = padv(cols.zone, m_p, 0)
    placeable = padv(cols.placeable, m_p, False)
    req_masks = cols.req_masks
    pref_masks = cols.pref_masks
    if m_p != m:
        req_masks = np.pad(req_masks, ((0, 0), (0, m_p - m)))
        pref_masks = np.pad(pref_masks, ((0, 0), (0, m_p - m)))
    return _ensure_assemble_jit(mesh)(
        jnp.asarray(sizes), jnp.asarray(copies), jnp.asarray(rates),
        jnp.asarray(rows), jnp.asarray(ccols), jnp.asarray(type_idx),
        jnp.asarray(req_masks), jnp.asarray(pref_masks),
        jnp.asarray(capacity), jnp.asarray(reserved), jnp.asarray(lru_age),
        jnp.asarray(busy), jnp.asarray(zone), jnp.asarray(placeable),
    )


def _assemble(sizes, copies, rates, rows, ccols, type_idx, req_masks,
              pref_masks, capacity, reserved, lru_age, busy, zone, placeable):
    import jax.numpy as jnp

    from modelmesh_tpu.ops.costs import PlacementProblem

    n, m = sizes.shape[0], capacity.shape[0]
    loaded = jnp.zeros((n, m), bool).at[rows, ccols].set(True, mode="drop")
    feasible = req_masks[type_idx] & placeable[None, :]
    preferred = pref_masks[type_idx]
    return PlacementProblem(
        sizes=sizes, copies=copies, rates=rates, loaded=loaded,
        feasible=feasible, capacity=capacity, reserved=reserved,
        lru_age=lru_age, busyness=busy, zone=zone, preferred=preferred,
    )


# Bounded jit-entry caches. Every distinct (mesh, config) used to leak a
# compiled executable for the process lifetime — a long-lived leader that
# cycles through solver configs (sparse widths, gate tunings, transient
# meshes) accumulated dead XLA programs without bound. An LRU of depth
# _JIT_CACHE_CAP keeps the steady-state entries hot (production uses one
# or two) while letting churned ones be collected with their executables.
_JIT_CACHE_CAP = 8
_jit_cache_lock = mm_lock("jax_engine._jit_cache_lock")
# keyed by mesh (None = default device)
_assemble_jits: "OrderedDict" = OrderedDict()  #: guarded-by: _jit_cache_lock
_sharded_solvers: "OrderedDict" = OrderedDict()  #: guarded-by: _jit_cache_lock


def _cache_get_or_build(cache: "OrderedDict", key, build):
    """LRU lookup shared by the jit-entry caches. The build runs OUTSIDE
    the lock (jit wrapping is cheap but make_sharded_solver traces
    nothing either — still, never hold a registered lock across anything
    that could reach a compile); the brief double-build race just makes
    one extra uncompiled wrapper that loses the insert."""
    with _jit_cache_lock:
        fn = cache.get(key)
        if fn is not None:
            cache.move_to_end(key)
            return fn
    fn = build()
    with _jit_cache_lock:
        won = cache.setdefault(key, fn)
        cache.move_to_end(key)
        while len(cache) > _JIT_CACHE_CAP:
            cache.popitem(last=False)
    return won


def _ensure_assemble_jit(mesh=None):
    def build():
        import jax

        if mesh is None:
            return jax.jit(_assemble)
        from modelmesh_tpu.parallel.mesh import problem_shardings

        return jax.jit(_assemble, out_shardings=problem_shardings(mesh))

    return _cache_get_or_build(_assemble_jits, mesh, build)


def _solver_for(mesh, config=None):
    """jitted sharded solver per (mesh, config) (rebuilding would
    recompile)."""

    def build():
        from modelmesh_tpu.parallel.sharded_solver import make_sharded_solver

        return make_sharded_solver(
            mesh, *(() if config is None else (config,))
        )

    return _cache_get_or_build(_sharded_solvers, (mesh, config), build)


# Sparse-dispatch policy (ROADMAP item 1: top-k-sparsified cost columns).
# Default candidate width when MM_SOLVER_TOPK is unset, and the auto
# rule's floor: the sparse path pays one full-width cost pass + top-k
# gather up front, so it only wins when the padded instance count is
# several times the candidate width. 24 measured both faster AND
# tighter-rounding than 32 at the 20k x 256 / 85%-utilization tier
# (0.32% vs 0.44% overflow of demand) — candidate quality saturates
# well before K reaches the fleet's plausible-placement width.
SPARSE_TOPK_DEFAULT = 24
SPARSE_AUTO_MIN_INSTANCES = 192

# Quality gate for the incremental dirty-row path: a merged re-solve
# whose rounding overflow DRIFTS more than this fraction of demand past
# the base full solve's own overflow triggers a full re-solve (the
# frozen column potentials/prices no longer price the fleet honestly).
# Same magnitude as the sparse path's dense-parity overflow budget.
INCREMENTAL_OVERFLOW_FRAC = 0.005

# Traffic drift that re-selects a CLEAN row on the incremental path: a
# row whose rate moved by more than this fraction of the base solve's
# hottest rate since the base froze is treated as dirty (a 30x spike on
# a warm model clears it; rpm jitter on cold models — whose balance
# cost term is negligible either way — does not). The dirty-frac
# ceiling then bounds the expanded set like any other churn.
RATE_DRIFT_FRAC = 0.2


def _resolve_sparse_config(config, m_pad: int, max_copies: int):
    """Pick dense vs sparse for this dispatch and finalize the config.

    Returns ``(config, sparse)``. The decision: an explicit
    ``config.topk`` (or MM_SOLVER_SPARSE=1 pin) forces sparse,
    MM_SOLVER_SPARSE=0 forces dense, and the default ("auto") goes
    sparse when the padded instance count clears both
    SPARSE_AUTO_MIN_INSTANCES and 4x the candidate width — below that
    the up-front full-width gather costs more than the width it saves.
    Sparse mode also requires the positional "hash" noise (the draw the
    gathered kernels can evaluate at scattered columns).

    A sparse dispatch narrows ``sel_width`` to the snapshot's real max
    copy count (bucketed to 2/4/8 so the jit-entry set stays tiny) and,
    for knobs the operator did NOT pin (``SolveConfig.tier_defaults=False``
    forbids these rewrites — a programmatic config's deliberate
    dense-default values are indistinguishable by value), swaps in the
    sparse-tier defaults: a ``auction_iters=8`` budget under the stall gate and the
    steady-state Sinkhorn tolerance — with exact in-candidate selection
    the price loop converges in one round where the dense solver needs
    five (measured 0.19% residual overflow at 20k x 256 vs the 0.5%
    dense-parity budget; docs/performance.md has the table).
    """
    from modelmesh_tpu.ops.solve import SolveConfig
    from modelmesh_tpu.utils import envs

    def _densified(c):
        # The dispatch decided dense: strip a caller-set topk so the
        # backends — solve_placement's own topk gate and the sharded
        # kernel's — cannot route sparse anyway and diverge from the
        # solver_path this dispatch reports.
        if c is not None and c.topk > 0:
            return c._replace(topk=0)
        return c

    cfg = SolveConfig() if config is None else config
    pin = (envs.get("MM_SOLVER_SPARSE") or "auto").strip().lower()
    if pin in ("0", "false", "no", "off"):
        return _densified(config), False
    topk = cfg.topk
    if topk <= 0:
        raw = envs.get("MM_SOLVER_TOPK")
        topk = int(raw) if raw not in (None, "") else SPARSE_TOPK_DEFAULT
    forced = pin in ("1", "true", "yes", "on") or cfg.topk > 0
    auto_ok = (
        m_pad >= SPARSE_AUTO_MIN_INSTANCES and m_pad >= 4 * topk
    )
    if not (forced or auto_ok) or topk >= m_pad:
        return _densified(config), False
    if cfg.tau > 0 and cfg.noise_impl != "hash":
        # threefry pin: sparse cannot match the draw
        return _densified(config), False
    if cfg.sel_width <= 0:
        sel = 2 if max_copies <= 2 else (4 if max_copies <= 4 else 8)
        cfg = cfg._replace(sel_width=sel)
    overrides = {"topk": topk}
    # "Did the operator pin it" is judged by value-equals-default + the
    # env registry; a programmatic config that DELIBERATELY wants the
    # dense-default gate values opts out via tier_defaults=False
    # (SolveConfig) — value equality alone cannot tell the two apart.
    if cfg.tier_defaults:
        if cfg.auction_iters == 40 and not envs.get(
            "MM_SOLVER_AUCTION_ITERS"
        ):
            overrides["auction_iters"] = 8
        if cfg.auction_stall_tol == 0.0 and not envs.get(
            "MM_SOLVER_AUCTION_STALL_TOL"
        ):
            overrides["auction_stall_tol"] = 1e-3
        if cfg.sinkhorn_tol == 0.0 and not envs.get(
            "MM_SOLVER_SINKHORN_TOL"
        ):
            overrides["sinkhorn_tol"] = 0.02
    return cfg._replace(**overrides), True


class SolveBase(NamedTuple):
    """Frozen state of the last full solve, the incremental dirty-row
    path's merge target (device arrays, padded shapes). ``seed`` is the
    noise epoch the base was solved under — the incremental re-solve is
    only valid while the strategy's frozen epoch still matches (the
    carried prices, potentials and the Gumbel draw are a matched
    triple)."""

    indices: object      # i32[n_pad, MAX_COPIES]
    valid: object        # bool[n_pad, MAX_COPIES]
    g: object            # f32[m_pad] frozen column potentials
    prices: object       # f32[m_pad] frozen congestion prices
    row_err: object      # f32[] frozen Sinkhorn diagnostic
    seed: int
    # The FULL solve's rounding overflow (host float): the incremental
    # quality gate bounds the DRIFT a merged re-solve adds on top of
    # this, not the absolute overflow — a loaded fleet legitimately
    # carries ~0.5% residual overflow even on a clean full solve, and an
    # absolute bar would make the incremental path unreachable exactly
    # where it matters. Frozen at the full solve (NOT advanced by
    # successful increments), so cumulative drift since the last full
    # solve stays bounded by the gate.
    overflow: float = 0.0
    # f32[n] host copy of the rates column the full solve ranked under.
    # rpm is re-read for EVERY record on each delta patch (traffic
    # shifts don't touch records, so rpm staleness cannot be
    # dirty-tracked) — the balance cost term moves without any dirty
    # mark. Clean rows whose rate drifted materially since this freeze
    # are re-selected as if dirty (RATE_DRIFT_FRAC); like the overflow
    # reference, frozen at the full solve so persistent drift keeps
    # re-selecting (or trips the ceiling) until a full solve re-freezes.
    rates: object = None


def solve_config_from_env():
    """SolveConfig overridden by the MM_SOLVER_* operator knobs.

    Returns the plain default config when nothing is set, so the jit
    static-arg cache key stays the literal SolveConfig() default."""
    from modelmesh_tpu.ops.solve import SolveConfig
    from modelmesh_tpu.utils import envs

    base = SolveConfig()
    overrides = {}
    for field, env, cast in (
        ("sinkhorn_iters", "MM_SOLVER_SINKHORN_ITERS", int),
        ("auction_iters", "MM_SOLVER_AUCTION_ITERS", int),
        ("tau", "MM_SOLVER_TAU", float),
        ("lse_impl", "MM_SOLVER_LSE_IMPL", str),
        ("load_impl", "MM_SOLVER_LOAD_IMPL", str),
        ("noise_impl", "MM_SOLVER_NOISE_IMPL", str),
        ("final_select", "MM_SOLVER_FINAL_SELECT", str),
        ("sinkhorn_tol", "MM_SOLVER_SINKHORN_TOL", float),
        ("sinkhorn_chunk", "MM_SOLVER_SINKHORN_CHUNK", int),
        ("auction_stall_tol", "MM_SOLVER_AUCTION_STALL_TOL", float),
        ("sparse_impl", "MM_SOLVER_SPARSE_IMPL", str),
    ):
        raw = envs.get(env)
        if raw not in (None, ""):
            overrides[field] = cast(raw)
    return base._replace(**overrides) if overrides else base


def build_problem(
    models: Sequence[tuple[str, ModelRecord]],
    instances: Sequence[tuple[str, InstanceRecord]],
    rpm_fn: Optional[RpmSource] = None,
    default_size_units: int = 128,
    max_copies: int = 8,
    constraints=None,
    pad: bool = False,
):
    """Assemble a PlacementProblem from registry/instance snapshots.

    Returns (problem, model_ids, instance_ids) — the id lists map array rows
    and columns back to the mesh. Zone names are densified to ids. With
    ``pad=True`` the arrays are bucket-padded (see _expand_problem_device);
    callers must slice solver output back to len(model_ids).
    """
    cols = snapshot_columns(
        models, instances, rpm_fn, default_size_units, max_copies, constraints
    )
    problem = _expand_problem_device(cols, pad=pad)
    return problem, cols.model_ids, cols.instance_ids


class GlobalPlan:
    """Solved assignment: model -> ordered preferred instances.

    Plans travel: the leader solves and publishes the serialized plan to the
    KV store (placement/plan_sync.py) and every instance adopts it from a
    watch — the analog of the reference's leader-computed placement
    decisions propagating via the shared registry (ModelMesh.java:6616-6747),
    except here the whole assignment ships as one artifact. ``age_ms`` is
    measured from *local adoption time* so follower TTLs don't depend on
    clock agreement with the leader: a dead leader stops publishing and
    plans expire everywhere on their own clocks.
    """

    def __init__(
        self, placements: Optional[dict[str, list[str]]], solved_at_ms: int,
        solve_ms: float, generation: int = 0,
    ):
        self._placements = placements
        # Columnar alternative representation (from_columnar / from_bytes
        # v2): (model_ids, counts u8[n], flat instance indices, inst_ids).
        # The 100k-entry dict-of-lists is only materialized if someone asks
        # for `.placements` — the solve -> publish path never does, which
        # keeps ~2-400 ms of Python object churn out of the refresh loop.
        self._columnar: Optional[tuple[list, np.ndarray, np.ndarray, list]] = None
        self._index: Optional[dict[str, int]] = None
        self._offsets: Optional[np.ndarray] = None
        self.solved_at_ms = solved_at_ms
        self.solve_ms = solve_ms
        self.generation = generation
        self.adopted_at_ms = solved_at_ms
        # Local-only stage timings from solve_plan (not serialized).
        self.stats: dict[str, float] = {}
        # Per-instance column potentials / congestion prices for
        # warm-starting the next solve (local-only: followers never need
        # them, only the refresher does).
        self.warm_g: Optional[dict[str, float]] = None
        self.warm_price: Optional[dict[str, float]] = None

    @classmethod
    def from_columnar(
        cls, model_ids: list, counts: np.ndarray, flat: np.ndarray,
        inst_ids: list, solved_at_ms: int, solve_ms: float,
        generation: int = 0,
    ) -> "GlobalPlan":
        """Wrap solver output without building the per-model dict.

        ``counts[i]`` targets for model ``model_ids[i]`` live at
        ``flat[offsets[i]:offsets[i]+counts[i]]`` (indices into inst_ids).
        """
        counts = np.asarray(counts)
        if counts.size and int(counts.max()) > 255:
            # u8 casts below would wrap silently and desynchronize the flat
            # index stream for every later model (wire corruption). Nothing
            # upstream produces >255 targets (auction caps at MAX_COPIES=8),
            # so treat it as a caller bug, loudly.
            raise ValueError("per-model target count exceeds 255")
        plan = cls(None, solved_at_ms, solve_ms, generation)
        plan._columnar = (model_ids, counts.astype(np.uint8),
                          np.asarray(flat), inst_ids)
        return plan

    @property
    def placements(self) -> dict[str, list[str]]:
        if self._placements is None:
            model_ids, counts, flat, inst_ids = self._columnar
            flat_list = flat.tolist()
            placements: dict[str, list[str]] = {}
            pos = 0
            for mid, c in zip(model_ids, counts.tolist()):
                placements[mid] = [inst_ids[j] for j in flat_list[pos:pos + c]]
                pos += c
            self._placements = placements
        return self._placements

    def num_models(self) -> int:
        if self._placements is not None:
            return len(self._placements)
        return len(self._columnar[0])

    def ensure_index(self) -> None:
        """Build the lookup index eagerly (PlanFollower calls this from the
        watch thread so the first routed request never pays for it)."""
        if self._columnar is not None and self._index is None:
            model_ids, counts, _, _ = self._columnar
            off = np.zeros(len(model_ids) + 1, np.int64)
            np.cumsum(counts, out=off[1:])
            # _offsets before _index: concurrent lock-free lookup()s treat a
            # non-None _index as "ready" and immediately read _offsets.
            self._offsets = off
            self._index = {mid: i for i, mid in enumerate(model_ids)}

    def lookup(self, model_id: str) -> Optional[list[str]]:
        """Targets for one model (routing hot path; no full dict needed)."""
        if self._placements is not None:
            return self._placements.get(model_id)
        self.ensure_index()
        row = self._index.get(model_id)
        if row is None:
            return None
        _, counts, flat, inst_ids = self._columnar
        # int() both operands: python_int + np.uint8 coerces INTO uint8
        # under NumPy 2 and overflows at offset 256.
        start = int(self._offsets[row])
        end = start + int(counts[row])
        return [inst_ids[j] for j in flat[start:end].tolist()]

    def truncate(self, keep: int) -> "GlobalPlan":
        """First ``keep`` models (placement order = hottest first), for the
        publisher's byte-budget trim."""
        if self._columnar is not None:
            model_ids, counts, flat, inst_ids = self._columnar
            cut = int(np.sum(counts[:keep], dtype=np.int64))
            flat_cut = flat[:cut]
            # Re-index against only the instances the kept rows reference:
            # the publisher's byte-budget trim relies on the payload
            # actually shrinking, and a full fleet-sized id table would put
            # a floor under it.
            used = np.unique(flat_cut)
            plan = GlobalPlan.from_columnar(
                model_ids[:keep], counts[:keep],
                np.searchsorted(used, flat_cut),
                [inst_ids[int(j)] for j in used],
                self.solved_at_ms, self.solve_ms, self.generation,
            )
        else:
            items = list(self._placements.items())[:keep]
            plan = GlobalPlan(
                dict(items), self.solved_at_ms, self.solve_ms, self.generation
            )
        plan.adopted_at_ms = self.adopted_at_ms
        return plan

    def age_ms(self) -> int:
        return now_ms() - self.adopted_at_ms

    # -- wire format -------------------------------------------------------
    #
    # Columnar binary v2 (zlib'd): header JSON + instance-id table +
    # model-id table (placement order preserved — publish_plan's tail
    # truncation depends on hottest-first ordering) + per-model copy
    # counts (u8) + flattened instance indices (u16/u32 by fleet size).
    # At 100k models this serializes ~10x faster and ~3x smaller than the
    # v1 JSON dict (which cost 300-500 ms per publish — a large slice of
    # the whole e2e refresh). from_bytes still decodes v1 payloads so a
    # mixed-version fleet keeps adopting during a rolling update.

    _MAGIC_V2 = b"MMP2"

    def to_bytes(self) -> bytes:
        import json
        import zlib

        if self._columnar is not None and self._placements is None:
            # Columnar fast path: the solver's arrays serialize directly —
            # no dict walk, no inst-table rebuild.
            model_ids, counts, flat, inst_ids = self._columnar
            if not any("\n" in s for s in model_ids) and not any(
                "\n" in s for s in inst_ids
            ):
                idx_dtype = (
                    np.uint16 if len(inst_ids) < 65_536 else np.uint32
                )
                return self._pack_v2(
                    inst_ids, model_ids, counts,
                    np.asarray(flat, idx_dtype), idx_dtype,
                )
            # fall through to the dict path (materializes placements)
        # Newlines delimit the id tables and copy counts ride a u8 column;
        # a pathological id containing "\n" or a row with >255 targets
        # (nothing upstream produces either, but the format must not
        # corrupt) falls back to the JSON encoding.
        if any(
            len(kv[1]) > 255 or "\n" in kv[0] or any("\n" in t for t in kv[1])
            for kv in self.placements.items()
        ):
            payload = json.dumps({
                "g": self.generation, "t": self.solved_at_ms,
                "ms": self.solve_ms, "p": self.placements,
            }, separators=(",", ":"))
            return zlib.compress(payload.encode(), level=1)
        inst_table: dict[str, int] = {}
        counts = np.empty(len(self.placements), np.uint8)
        flat: list[int] = []
        for i, targets in enumerate(self.placements.values()):
            counts[i] = len(targets)
            for t in targets:
                flat.append(inst_table.setdefault(t, len(inst_table)))
        idx_dtype = np.uint16 if len(inst_table) < 65_536 else np.uint32
        return self._pack_v2(
            list(inst_table), list(self.placements), counts,
            np.asarray(flat, idx_dtype), idx_dtype,
        )

    def _pack_v2(self, inst_ids, model_ids, counts, flat, idx_dtype) -> bytes:
        import json
        import zlib

        header = json.dumps({
            "g": self.generation, "t": self.solved_at_ms,
            "ms": self.solve_ms, "n": len(model_ids),
            "w": int(np.dtype(idx_dtype).itemsize),
        }, separators=(",", ":")).encode()

        def framed(b: bytes) -> list[bytes]:
            return [len(b).to_bytes(4, "big"), b]

        parts = [
            self._MAGIC_V2,
            *framed(header),
            *framed("\n".join(inst_ids).encode()),
            *framed("\n".join(model_ids).encode()),
            np.ascontiguousarray(counts, np.uint8).tobytes(),
            np.ascontiguousarray(flat, idx_dtype).tobytes(),
        ]
        return zlib.compress(b"".join(parts), level=1)

    @classmethod
    def from_bytes(cls, data: bytes) -> "GlobalPlan":
        import json
        import zlib

        raw = zlib.decompress(data)
        if not raw.startswith(cls._MAGIC_V2):
            # v1: zlib'd JSON dict (pre-round-3 leaders).
            d = json.loads(raw.decode())
            plan = cls(d["p"], d["t"], d["ms"], d.get("g", 0))
            plan.adopted_at_ms = now_ms()
            return plan
        off = len(cls._MAGIC_V2)

        def take(n):
            nonlocal off
            out = raw[off:off + n]
            off += n
            return out

        hlen = int.from_bytes(take(4), "big")
        h = json.loads(take(hlen).decode())
        inst_ids = take(int.from_bytes(take(4), "big")).decode().split("\n")
        model_blob = take(int.from_bytes(take(4), "big")).decode()
        model_ids = model_blob.split("\n") if model_blob else []
        n = h["n"]
        counts = np.frombuffer(take(n), np.uint8)
        idx_dtype = np.uint16 if h["w"] == 2 else np.uint32
        flat = np.frombuffer(raw[off:], idx_dtype)
        # Stay columnar: followers route via lookup(); the dict-of-lists is
        # only built if a consumer iterates .placements.
        plan = cls.from_columnar(
            model_ids, counts, flat, inst_ids, h["t"], h["ms"], h.get("g", 0)
        )
        plan.adopted_at_ms = now_ms()
        return plan


class PendingSolve(NamedTuple):
    """A dispatched-but-not-finalized refresh: the device is (possibly
    still) crunching ``sol`` while the host is free to build the NEXT
    snapshot — the pipelined refresh overlap (placement/refresh_loop.py).
    ``sol`` holds async device arrays; ``finalize_plan`` blocks on them."""

    cols: ProblemColumns
    sol: object          # ops.solve.Placement (device arrays, in flight)
    t_start: float       # perf_counter at snapshot start
    t_snapshot: float    # perf_counter when the host snapshot was done
    t_dispatch: float    # perf_counter when the solve was enqueued
    warm: bool
    # Which backend the dispatch picked: dense | sparse | sharded |
    # sharded-sparse | incremental (observable in plan.stats and the
    # bench JSON tail).
    path: str = "dense"
    topk: int = 0
    dirty_rows: Optional[int] = None  # rows re-solved (incremental only)


def dispatch_solve(
    cols: ProblemColumns,
    seed: int = 0,
    mesh=None,
    warm_g: Optional[Mapping[str, float]] = None,
    warm_price: Optional[Mapping[str, float]] = None,
    config=None,
    carry=None,
    donate: bool = False,
    t_start: Optional[float] = None,
    t_snapshot: Optional[float] = None,
    base: Optional[SolveBase] = None,
    dirty_rows=None,
) -> PendingSolve:
    """Expand ``cols`` on device and enqueue the solve WITHOUT blocking.

    JAX dispatch is asynchronous: the returned PendingSolve's arrays are
    futures, and the host can immediately go build the next snapshot while
    the device works — ``finalize_plan`` collects the result.

    This is the solver dispatch layer (ROADMAP item 1): one common
    signature over four backends, picked from problem shape, mesh, and
    the MM_SOLVER_* env pins —

    - **dense** single-device (ops/solve.py) — small fleets;
    - **sparse** top-K (ops/sparse.py) — auto above
      SPARSE_AUTO_MIN_INSTANCES padded columns, or MM_SOLVER_SPARSE /
      MM_SOLVER_TOPK pins (``_resolve_sparse_config``);
    - **sharded** across a device mesh (parallel/sharded_solver.py),
      composing with sparse (the mesh kernel gathers top-K per shard);
    - **incremental** dirty-row re-solve: when ``base`` (the last full
      solve's frozen state) and ``dirty_rows`` (row ids into
      ``cols.model_ids``) are given, only those rows are re-selected
      against the frozen column potentials/prices and merged into the
      base assignment. Callers gate on dirty fraction and noise-epoch
      match (``JaxPlacementStrategy.refresh``) and must check the
      merged overflow against INCREMENTAL_OVERFLOW_FRAC after
      finalizing. Single-device only (``mesh=None``).

    Warm-start carries, in order of preference: ``carry`` as (g0, price0)
    DEVICE arrays from the previous solve (already bucket-padded and
    column-aligned — the double-buffered steady-state path, no host round
    trip); else the ``warm_g`` / ``warm_price`` per-instance-id dicts
    scattered onto zeros (robust to joins/leaves); else cold zeros. The
    carry arrays are ALWAYS materialized — switching the jitted solve's
    init between None and an array would change the argument pytree and
    force a recompile on the first warm refresh.

    ``donate=True`` routes through the buffer-donating jit entry: the
    carry buffers are consumed and XLA reuses their HBM for the outputs,
    so a steady-state loop never reallocates them. Only safe when the
    caller hands over ownership (device ``carry`` it won't reuse) and the
    backend honors donation (TPU/GPU; CPU warns and copies).
    """
    import jax.numpy as jnp

    from modelmesh_tpu.ops.solve import (
        SolveConfig,
        SolveInit,
        solve_placement,
        solve_placement_donated,
        solve_placement_incremental,
    )

    t_start = time.perf_counter() if t_start is None else t_start  #: wall-clock: perf_counter solve-timing metric
    t_snapshot = time.perf_counter() if t_snapshot is None else t_snapshot  #: wall-clock: perf_counter solve-timing metric
    n_pad = _bucket(len(cols.model_ids))
    m_pad = _bucket(len(cols.instance_ids), 64)
    max_copies = int(cols.copies.max()) if len(cols.copies) else 1
    config, sparse = _resolve_sparse_config(config, m_pad, max_copies)

    if base is not None and dirty_rows is not None:
        if mesh is not None:
            raise ValueError("incremental re-solve requires mesh=None")
        if (
            getattr(base.indices, "shape", (0,))[0] != n_pad
            or getattr(base.g, "shape", (0,))[0] != m_pad
        ):
            raise ValueError(
                "SolveBase shapes do not match the padded problem "
                "(stale base after a fleet resize?)"
            )
        cfg = SolveConfig() if config is None else config
        problem = _expand_problem_device(cols, pad=True)
        d = np.asarray(sorted(int(r) for r in dirty_rows), np.int32)  #: host-sync: host-built dirty-row ids, not a device readback
        d_pad = _bucket(max(len(d), 1), 64)
        padded = np.full(d_pad, n_pad, np.int32)
        padded[: len(d)] = d
        sol = solve_placement_incremental(
            problem, cfg, jnp.asarray(seed, jnp.uint32),
            jnp.asarray(padded), base.indices, base.valid,
            base.g, base.prices, base.row_err,
        )
        return PendingSolve(
            cols=cols, sol=sol, t_start=t_start, t_snapshot=t_snapshot,
            t_dispatch=time.perf_counter(), warm=True,  #: wall-clock: perf_counter solve-timing metric
            path="incremental", topk=cfg.topk, dirty_rows=len(d),
        )

    if carry is not None:
        g0, price0 = carry
        if g0.shape[0] != m_pad or price0.shape[0] != m_pad:
            raise ValueError(
                f"device carry shape {g0.shape[0]} != padded columns {m_pad}"
            )
        warm = True
    else:
        # Host path: id-aligned scatter; instances unknown to the carry
        # (new pods) start at 0 = cold.
        g0 = np.zeros(m_pad, np.float32)
        price0 = np.zeros(m_pad, np.float32)
        if warm_g:
            for j, iid in enumerate(cols.instance_ids):
                g0[j] = warm_g.get(iid, 0.0)
        if warm_price:
            for j, iid in enumerate(cols.instance_ids):
                price0[j] = warm_price.get(iid, 0.0)
        warm = bool(warm_g)
    if mesh is not None:
        from modelmesh_tpu.parallel.mesh import INSTANCE_AXIS, MODEL_AXIS

        if donate:
            # Donation is only wired through the single-device jit entry;
            # silently dropping the flag would let a caller skip the
            # carry readback (as donors must) with nothing ever donated,
            # permanently staling its warm-start dicts.
            raise ValueError("donate=True is not supported with mesh")
        if MODEL_AXIS not in mesh.shape or INSTANCE_AXIS not in mesh.shape:
            raise ValueError(
                f"mesh axes {tuple(mesh.shape)} != "
                f"({MODEL_AXIS!r}, {INSTANCE_AXIS!r}); build with "
                "parallel.mesh.make_mesh"
            )
        n_mdl, n_inst = mesh.shape[MODEL_AXIS], mesh.shape[INSTANCE_AXIS]
        if n_pad % n_mdl or m_pad % n_inst:
            raise ValueError(
                f"mesh {dict(mesh.shape)} does not divide the padded problem"
            )
        problem = _expand_problem_device(cols, pad=True, mesh=mesh)
        sol = _solver_for(mesh, config)(
            problem, seed=seed, g0=g0, price0=price0
        )
        path = "sharded-sparse" if sparse else "sharded"
    else:
        problem = _expand_problem_device(cols, pad=True)
        # Always pass config explicitly: solve_placement defaults it, but
        # the donated entry jits _solve_placement_impl directly (no
        # default) — config is static, so the literal SolveConfig() hits
        # the same cache entry as the wrapper's default.
        cfg = SolveConfig() if config is None else config
        solve = solve_placement_donated if donate else solve_placement
        sol = solve(problem, config=cfg, seed=seed,
                    init=SolveInit(g0=g0, price0=price0))
        path = "sparse" if sparse else "dense"
    cfg_topk = getattr(config, "topk", 0) if config is not None else 0
    return PendingSolve(
        cols=cols, sol=sol, t_start=t_start, t_snapshot=t_snapshot,
        t_dispatch=time.perf_counter(), warm=warm,  #: wall-clock: perf_counter solve-timing metric
        path=path, topk=cfg_topk if sparse else 0,
    )


def finalize_plan(
    pending: PendingSolve, fetch_carries: bool = True
) -> GlobalPlan:
    """Block on a dispatched solve and pack it into a GlobalPlan.

    ``fetch_carries=False`` skips the g/prices readback entirely (the
    plan's ``warm_g``/``warm_price`` stay None): the pipelined
    steady-state driver chains carries device-to-device — and the
    incremental path's g/prices are aliases of the frozen device base —
    so materializing the id-keyed host dicts every cycle would be a
    pure host round trip. The dicts then keep whatever values the last
    full readback gave them (the chain-break fallback warm start)."""
    import jax

    cols, sol = pending.cols, pending.sol
    sol = jax.block_until_ready(sol)  #: host-sync: delineates device solve time from host extraction in plan.stats
    t2 = time.perf_counter()  #: wall-clock: perf_counter solve-timing metric
    # Compact readback: u16 indices + per-row valid counts instead of the
    # raw i32[N,K] + bool[N,K] (2.1 MB vs 5.2 MB at the padded 100k tier —
    # the D2H link, not the solve, dominates the refresh on a remote
    # device). `valid` is a prefix mask by construction (slot < copies is a
    # prefix; top-k values are descending so the threshold cut is too), so
    # counts lose nothing. Pinned by test_jax_engine's compact-vs-mask test.
    packed_dev = _compact_result(
        sol, narrow=len(cols.instance_ids) < 65_536
    )
    # ONE batched D2H for everything the host needs this cycle — the
    # packed plan, the quality scalars, the iteration counters and
    # (unless the caller keeps them device-resident) the warm-start
    # carries: on a remote device every separate device_get is its own
    # round trip, and the link latency (not the solve) dominates the
    # refresh there. Pinned by test_device_residency's device_get shim.
    fetch = {
        "packed": packed_dev,
        "overflow": sol.overflow,
        "row_err": sol.row_err,
    }
    if fetch_carries:
        fetch["g"] = sol.g
        fetch["prices"] = sol.prices
    for name in ("sinkhorn_iters_run", "auction_iters_run"):
        v = getattr(sol, name, None)
        if v is not None:
            fetch[name] = v
    got = jax.device_get(fetch)  #: host-sync: the single batched per-cycle readback
    packed, overflow, row_err = got["packed"], got["overflow"], got["row_err"]
    g_host, price_host = got.get("g"), got.get("prices")
    n = len(cols.model_ids)
    idxa = packed[:n, :-1]
    counts = packed[:n, -1].astype(np.uint8)
    # Hottest-first order: publish_plan truncates from the tail under its
    # byte budget, so the models that lose central placement must be the
    # coldest, not whichever ones the registry iterated last.
    order = np.argsort(-cols.rates, kind="stable")
    idxo = idxa[order]
    counts = counts[order]
    valid = np.arange(idxo.shape[1], dtype=np.uint8)[None, :] < counts[:, None]
    flat = idxo[valid]
    model_ids = [cols.model_ids[i] for i in order.tolist()]
    t3 = time.perf_counter()  #: wall-clock: perf_counter solve-timing metric
    plan = GlobalPlan.from_columnar(
        model_ids, counts, flat, cols.instance_ids, now_ms(),
        (t3 - pending.t_start) * 1e3,
    )
    plan.stats = {
        "snapshot_ms": (pending.t_snapshot - pending.t_start) * 1e3,
        "solve_ms": (t2 - pending.t_snapshot) * 1e3,
        "extract_ms": (t3 - t2) * 1e3,
        "warm": pending.warm,
        "solver_path": pending.path,
    }
    if pending.topk:
        plan.stats["topk"] = pending.topk
    if pending.dirty_rows is not None:
        plan.stats["dirty_rows"] = pending.dirty_rows
    # Solution-quality scalars: the bench JSON tail and the incremental
    # path's overflow fallback gate both read these.
    plan.stats["overflow"] = float(overflow)
    plan.stats["row_err"] = float(row_err)
    for name in ("sinkhorn_iters_run", "auction_iters_run"):
        if name in got:
            plan.stats[name] = int(got[name])
    # Warm-start carries for the NEXT refresh (~4 KB each at 1k instances).
    if g_host is not None:
        g_arr = np.asarray(g_host)[: len(cols.instance_ids)]  #: host-sync: already host-resident — rides the batched fetch above
        plan.warm_g = dict(
            zip(cols.instance_ids, g_arr.astype(float).tolist())
        )
    if price_host is not None:
        p_arr = np.asarray(price_host)[: len(cols.instance_ids)]  #: host-sync: already host-resident — rides the batched fetch above
        plan.warm_price = dict(
            zip(cols.instance_ids, p_arr.astype(float).tolist())
        )
    return plan


def solve_plan(
    models: Sequence[tuple[str, ModelRecord]],
    instances: Sequence[tuple[str, InstanceRecord]],
    rpm_fn: Optional[RpmSource] = None,
    seed: int = 0,
    constraints=None,
    mesh=None,
    warm_g: Optional[Mapping[str, float]] = None,
    config=None,
    warm_price: Optional[Mapping[str, float]] = None,
    cols: Optional[ProblemColumns] = None,
) -> GlobalPlan:
    """One global solve -> GlobalPlan (blocking; runs on the JAX device).

    Stage timings land in ``plan.stats`` (snapshot / device solve / plan
    extraction, milliseconds) — the e2e refresh cost, not just the kernel
    (round-2 VERDICT weak #2). Shapes are bucket-padded so consecutive
    refreshes with drifting model counts reuse the compiled solver.

    ``mesh``: a parallel.mesh device mesh shards the solve across chips
    (parallel/sharded_solver.py) — the 1M x 10k ladder path. Bucket sizes
    are powers of two or 3·2^k, so any power-of-two mesh axis ≤ the pad
    floors (256 rows, 64 cols) divides them evenly.

    ``config``: a SolveConfig overriding the solver defaults (None keeps
    the compiled-default cache entry). The strategy builds one from the
    MM_SOLVER_* env knobs (solve_config_from_env).

    ``warm_g`` / ``warm_price``: per-instance-id column potentials and
    congestion prices from the previous solve (``plan.warm_g`` /
    ``plan.warm_price``) — warm-start Sinkhorn and the auction (SURVEY.md
    section 7 hard part #4, incremental solves as state churns). Only
    column state needs carrying, and keying by instance id makes the
    carry robust to models/instances joining or leaving.

    ``cols``: a pre-built snapshot (e.g. from ``patch_columns``); skips
    the internal ``snapshot_columns`` call. This is the blocking
    convenience wrapper around dispatch_solve + finalize_plan — the
    pipelined steady-state driver calls those directly to overlap the
    next snapshot with the in-flight solve.
    """
    if not models or not instances:
        return GlobalPlan({}, now_ms(), 0.0)
    t0 = time.perf_counter()  #: wall-clock: perf_counter solve-timing metric
    if cols is None:
        cols = snapshot_columns(
            models, instances, rpm_fn, constraints=constraints
        )
    t1 = time.perf_counter()  #: wall-clock: perf_counter solve-timing metric
    pending = dispatch_solve(
        cols, seed=seed, mesh=mesh, warm_g=warm_g, warm_price=warm_price,
        config=config, t_start=t0, t_snapshot=t1,
    )
    return finalize_plan(pending)


_compact_jits: dict = {}


def _compact_result(sol, narrow: bool):
    """Jitted epilogue shrinking the solver result before D2H transfer.

    Packs indices and per-row valid counts into ONE [N, K+1] array so the
    readback is a single transfer — on a remote-device link every array
    costs a full round trip (~65 ms on the measured axon tunnel), which
    dwarfs the extra byte-per-row of carrying counts at index width."""
    import jax
    import jax.numpy as jnp

    fn = _compact_jits.get(narrow)
    if fn is None:
        dtype = jnp.uint16 if narrow else jnp.int32

        def compact(idx, valid):
            cnt = valid.sum(1).astype(dtype)
            return jnp.concatenate(
                [idx.astype(dtype), cnt[:, None]], axis=1
            )

        fn = _compact_jits[narrow] = jax.jit(compact)
    return fn(sol.indices, sol.valid)


class JaxPlacementStrategy(PlacementStrategy):
    """Plan-serving strategy with greedy fallback.

    ``refresher`` mode: call ``refresh(models, instances, rpm_fn)``
    periodically — in production the leader reaper does this and
    publishes the result fleet-wide (serving/tasks.py); followers adopt
    via PlanFollower. Decisions read the latest plan lock-free.
    """

    def __init__(
        self,
        # Must exceed the publish cadence (the leader reaper's
        # reaper_interval_s, default 420 s) or followers spend most of each
        # cycle TTL-expired and silently serving greedy.
        plan_ttl_ms: int = 15 * 60_000,
        fallback: Optional[PlacementStrategy] = None,
        constraints=None,
        mesh=None,
        solve_config="env",
    ):
        self.plan_ttl_ms = plan_ttl_ms
        self.fallback = fallback or GreedyStrategy()
        # serving/constraints.TypeConstraints — attached by the instance
        # (like greedy's) so solves honor required masks and preferred
        # labels (build_problem feasible/preferred).
        self.constraints = constraints
        # mesh=None solves on the default device; mesh="auto" shards
        # refreshes across all visible devices (multi-chip leader hosts —
        # the 1M x 10k ladder tier); a parallel.mesh Mesh is explicit.
        # Opt-in rather than defaulted: an instance's JAX devices are not
        # necessarily a placement-solver pool.
        if mesh == "auto":
            import jax

            from modelmesh_tpu.parallel.mesh import make_mesh

            devs = jax.devices()
            # Largest power-of-two subset: bucket-padded shapes are 2^k or
            # 3·2^k, so power-of-two axes always divide them; a 6- or
            # 12-device host must not turn every refresh into a ValueError.
            usable = 1 << (len(devs).bit_length() - 1)
            mesh = make_mesh(devices=devs[:usable]) if usable > 1 else None
        self.mesh = mesh
        # "env" -> MM_SOLVER_* knobs (solve_config_from_env); None -> the
        # compiled defaults; or an explicit SolveConfig.
        if solve_config == "env":
            cfg = solve_config_from_env()
            from modelmesh_tpu.ops.solve import SolveConfig

            solve_config = None if cfg == SolveConfig() else cfg
        self.solve_config = solve_config
        self._plan: Optional[GlobalPlan] = None
        # Plan generation (always increments — readers order plans by it)
        # is deliberately SEPARATE from the rounding-noise seed: the
        # auction's carried prices and its Gumbel draw are a matched pair,
        # so incremental refreshes freeze the noise epoch (see refresh())
        # and the seed rotates only on full rebuilds.
        self._generation = 0  #: guarded-by: _refresh_lock
        self._seed = 0  #: guarded-by: _refresh_lock
        self._refresh_lock = mm_lock("JaxPlacementStrategy._refresh_lock")
        # Column-potential / price carries across refreshes (solve_plan
        # warm_g / warm_price).
        #: guarded-by: _refresh_lock
        self._warm_g: Optional[dict[str, float]] = None
        #: guarded-by: _refresh_lock
        self._warm_price: Optional[dict[str, float]] = None
        # Delta-snapshot state: the cached columns plus the dirty marks
        # accumulated since the last refresh (mark_dirty, watch-fed).
        # Marks map id -> highest record version announced (0 = version
        # unknown); the version lets a refresh detect marks whose
        # mutation is NEWER than the list snapshot it is patching from
        # and re-queue them (see _requeue_stale_marks_locked). _dirty_lock is
        # separate from _refresh_lock so event threads never block behind
        # a multi-hundred-ms solve.
        self._snap_cache: Optional[SnapshotCache] = None  #: guarded-by: _refresh_lock
        self._dirty_lock = mm_lock("JaxPlacementStrategy._dirty_lock")
        self._dirty_models: dict = {}  #: guarded-by: _dirty_lock
        self._dirty_instances: dict = {}  #: guarded-by: _dirty_lock
        # Consecutive delta refreshes since the last full rebuild. Under
        # perpetual small churn the dirty fraction never trips the patch
        # fallback, so without a cap the frozen noise epoch would freeze
        # an unlucky Gumbel draw FOREVER — _build_cols_locked forces a rebuild
        # (and thus a seed rotation) every MAX_DELTA_STREAK deltas, which
        # also bounds how long an unmarked-dirty record can serve stale
        # columns.
        self._delta_streak = 0  #: guarded-by: _refresh_lock
        # Frozen state of the last full (non-incremental) solve — the
        # incremental dirty-row path's merge target. None until a full
        # solve completes on the default device; invalidated on seed
        # rotation (SolveBase.seed mismatch), fleet resizes (padded-shape
        # mismatch), and by the pipelined driver (whose donated flights
        # may consume the carry buffers a base would alias).
        self._base: Optional[SolveBase] = None  #: guarded-by: _refresh_lock
        from modelmesh_tpu.utils import envs

        # Dirty-row fraction ceiling for the incremental re-solve; 0
        # disables the path entirely (every refresh solves full).
        self.incr_max_dirty_frac = envs.get_float(
            "MM_SOLVER_INCREMENTAL_MAX_DIRTY_FRAC"
        )

    @property
    def plan(self) -> Optional[GlobalPlan]:
        return self._plan

    def mark_dirty(
        self, models: Sequence = (), instances: Sequence = ()
    ) -> None:
        """Record churned records for the next ``refresh(incremental=True)``.

        The tracking contract: every model/instance whose record changed
        since the last refresh must be marked, or the delta snapshot serves
        stale columns for it until the next full rebuild. Registry/instance
        watch handlers are the natural callers.

        Entries are bare ids or ``(id, record_version)`` pairs. A
        versioned mark closes the watch-race window: if the refresh that
        consumes it is patching from a list snapshot OLDER than the
        marked version (the caller's ``items()`` read happened before the
        mutation landed), the mark is re-queued instead of silently
        consumed — see ``_requeue_stale_marks_locked``. Bare ids keep the
        original best-effort semantics."""
        with self._dirty_lock:
            for entry in models:
                mid, ver = entry if isinstance(entry, tuple) else (entry, 0)
                if ver >= self._dirty_models.get(mid, 0):
                    self._dirty_models[mid] = ver
            for entry in instances:
                iid, ver = entry if isinstance(entry, tuple) else (entry, 0)
                if ver >= self._dirty_instances.get(iid, 0):
                    self._dirty_instances[iid] = ver

    def _take_dirty(self) -> tuple[dict, dict]:
        with self._dirty_lock:
            dm, di = self._dirty_models, self._dirty_instances
            self._dirty_models, self._dirty_instances = {}, {}
            return dm, di

    def _requeue_stale_marks_locked(self, dm, di, models, instances) -> None:
        """Re-queue consumed marks whose record version is NEWER than the
        snapshot just applied: a watch event that landed between the
        refresher's ``items()`` read and ``_take_dirty`` was patched (or
        rebuilt) from the stale pre-event record — without this its mark
        would be gone and the record's columns stale for up to
        MAX_DELTA_STREAK refreshes, until the forced full rebuild."""
        cache = self._snap_cache
        if cache is None:
            return
        stale_m = [
            (mid, ver) for mid, ver in dm.items()
            if ver
            and (i := cache.model_pos.get(mid)) is not None
            and models[i][1].version < ver
        ]
        stale_i = [
            (iid, ver) for iid, ver in di.items()
            if ver
            and (j := cache.inst_pos.get(iid)) is not None
            and instances[j][1].version < ver
        ]
        if stale_m or stale_i:
            self.mark_dirty(stale_m, stale_i)

    def _build_cols_locked(self, models, instances, rpm_fn, incremental: bool):
        """Delta-patch the cached snapshot when allowed, else rebuild (and
        re-prime the cache). Returns (cols, was_delta, dirty_models,
        dirty_instances) — the consumed marks, so the refresh can derive
        the dirty ROW ids for the incremental re-solve."""
        dm, di = self._take_dirty()
        if (
            incremental
            and self._snap_cache is not None
            and self._delta_streak < MAX_DELTA_STREAK
        ):
            cols = patch_columns(
                self._snap_cache, models, instances, rpm_fn,
                set(dm), set(di), constraints=self.constraints,
            )
            if cols is not None:
                self._delta_streak += 1
                self._requeue_stale_marks_locked(dm, di, models, instances)
                return cols, True, dm, di
        cols, self._snap_cache = snapshot_columns(
            models, instances, rpm_fn, constraints=self.constraints,
            return_cache=True,
        )
        self._delta_streak = 0
        # A rebuild from a stale list has the same race: keep marks whose
        # mutation the rebuilt snapshot provably hasn't seen.
        self._requeue_stale_marks_locked(dm, di, models, instances)
        return cols, False, dm, di

    def _epoch_carries_locked(self, delta: bool):
        """Noise-epoch discipline, shared by the blocking ``refresh`` and
        ``PipelinedRefresher.submit`` so the matched-pair rules cannot
        fork: a delta refresh KEEPS the Gumbel seed and may warm-start
        prices; a full rebuild rotates the seed and DROPS the price
        carry, which is only meaningful under the draw it was selected
        with (rotating without dropping re-herds rows — ~40x worse probe
        overflow measured at 20k x 256 — and kills the warm early exit).
        Sinkhorn's g is draw-independent and always carries. Returns the
        (warm_g, warm_price) id-keyed dicts to use. Callers hold
        _refresh_lock."""
        if not delta:
            self._seed += 1
            # INVALIDATE the stored prices, don't just skip them for this
            # solve: they belong to the old draw, and if this rebuild's
            # own price readback is skipped (donated pipelined flight) a
            # later delta refresh would pair them with the rotated seed.
            self._warm_price = None
        return self._warm_g, self._warm_price

    def _incremental_rows_locked(self, cols, delta, dm, di):
        """Dirty ROW ids for an incremental re-solve, or None when the
        dispatch gates say full solve:

        - a full rebuild happened (positions may have moved — the base
          assignment is keyed by row), or there is no base yet;
        - the base was solved under a different noise epoch (seed) or at
          different padded shapes (fleet resize);
        - ANY instance is dirty: the frozen column potentials/prices
          price the OLD instance state, and a capacity / placeability
          flip moves the cost surface for every row — column churn
          always takes the full warm solve;
        - the dirty-model fraction exceeds incr_max_dirty_frac (above
          it, re-selecting rows against frozen prices drifts too far
          from the equilibrium a joint solve would find).

        Clean rows whose RATE drifted past RATE_DRIFT_FRAC of the base
        solve's hottest rate join the dirty set: each delta patch
        re-reads rpm for every record, so the balance cost term moves
        without any dirty mark, and before the incremental path existed
        every refresh re-ranked those rows for free. The ceiling is
        applied to the EXPANDED set, so a fleet-wide traffic shift
        falls back to the full solve it deserves.
        """
        base = self._base
        if (
            not delta or base is None or di or not dm
            or self.mesh is not None or self.incr_max_dirty_frac <= 0
            or base.seed != self._seed
        ):
            return None
        cfg = self.solve_config
        if cfg is not None and cfg.tau > 0 and cfg.noise_impl != "hash":
            # The incremental kernel replays the base draw POSITIONALLY
            # (hash_gumbel_at); threefry cannot be evaluated at scattered
            # rows, so a threefry-pinned strategy must take the full
            # path — routing it through resolve_dirty_rows would raise
            # out of refresh() instead of falling back.
            return None
        n = len(cols.model_ids)
        if (
            getattr(base.indices, "shape", (0,))[0] != _bucket(n)
            or getattr(base.g, "shape", (0,))[0]
            != _bucket(len(cols.instance_ids), 64)
        ):
            return None
        cache = self._snap_cache
        rows = set()
        for mid in dm:
            i = None if cache is None else cache.model_pos.get(mid)
            if i is None:
                return None
            rows.add(i)
        if base.rates is not None and len(base.rates) >= n:
            cur = np.asarray(cols.rates, np.float32)[:n]  #: host-sync: snapshot rates are host numpy columns
            scale = float(base.rates[:n].max()) if n else 0.0
            if scale > 0.0:
                drifted = np.nonzero(
                    np.abs(cur - base.rates[:n]) > RATE_DRIFT_FRAC * scale
                )[0]
                rows.update(int(i) for i in drifted)
        if len(rows) > self.incr_max_dirty_frac * n:
            return None
        return sorted(rows)

    def _solve_locked(self, cols, delta, dm, di, t0):
        """Incremental dirty-row re-solve when the gates allow, with the
        overflow quality fallback; else a full (warm) solve, whose frozen
        state becomes the next incremental base."""
        rows = self._incremental_rows_locked(cols, delta, dm, di)
        if rows is not None:
            pending = dispatch_solve(
                cols, seed=self._seed, config=self.solve_config,
                base=self._base, dirty_rows=rows, t_start=t0,
            )
            plan = finalize_plan(pending)
            demand = float(np.sum(cols.sizes * cols.copies))
            budget = self._base.overflow + INCREMENTAL_OVERFLOW_FRAC * max(
                demand, 1e-9
            )
            if plan.stats["overflow"] <= budget:
                # Advance the merge target to the merged assignment; the
                # column state (g/prices/row_err — and the overflow
                # reference) stays frozen at the full solve, so drift
                # accumulated across MANY increments is still measured
                # against it.
                self._base = self._base._replace(
                    indices=pending.sol.indices, valid=pending.sol.valid
                )
                return plan
            log.info(
                "incremental re-solve overflow %.3g drifted past the "
                "base solve's %.3g + %.2f%% of demand; falling back to "
                "a full solve",
                plan.stats["overflow"], self._base.overflow,
                INCREMENTAL_OVERFLOW_FRAC * 100,
            )
            self._base = None
        warm_g, warm_price = self._epoch_carries_locked(delta)
        pending = dispatch_solve(
            cols, seed=self._seed, mesh=self.mesh,
            warm_g=warm_g, warm_price=warm_price,
            config=self.solve_config, t_start=t0,
        )
        plan = finalize_plan(pending)
        sol = pending.sol
        if self.mesh is None and sol.g is not None and sol.prices is not None:
            self._base = SolveBase(
                indices=sol.indices, valid=sol.valid, g=sol.g,
                prices=sol.prices, row_err=sol.row_err, seed=self._seed,
                overflow=plan.stats["overflow"],
                rates=np.asarray(cols.rates, np.float32).copy(),  #: host-sync: snapshot rates are host numpy columns
            )
        else:
            self._base = None
        return plan

    def refresh(
        self,
        models: Sequence[tuple[str, ModelRecord]],
        instances: Sequence[tuple[str, InstanceRecord]],
        rpm_fn: Optional[RpmSource] = None,
        incremental: bool = False,
    ) -> GlobalPlan:
        with self._refresh_lock:
            self._generation += 1
            delta = None
            if models and instances:
                t0 = time.perf_counter()  #: wall-clock: perf_counter solve-timing metric
                cols, delta, dm, di = self._build_cols_locked(
                    models, instances, rpm_fn, incremental
                )
                # Noise-epoch discipline (_epoch_carries_locked): a frozen draw
                # keeps the warm prices valid AND the plan stable under
                # small churn — fewer gratuitous model moves. An unlucky
                # draw is never frozen forever: full rebuilds rotate it,
                # and _build_cols_locked forces one every MAX_DELTA_STREAK
                # consecutive deltas even under perpetual small churn.
                # _solve_locked routes model-only small-churn deltas
                # through the incremental dirty-row re-solve against the
                # last full solve's frozen column state.
                plan = self._solve_locked(cols, delta, dm, di, t0)
            else:
                # Empty view: no solve happens, so do NOT rotate the seed —
                # _warm_price stays selected under the current draw, and a
                # rotation here would mispair them for the next real delta
                # refresh (plan ordering is _generation's job, not _seed's).
                plan = solve_plan(
                    models, instances, rpm_fn, seed=self._seed,
                    constraints=self.constraints, mesh=self.mesh,
                    warm_g=self._warm_g, config=self.solve_config,
                    warm_price=self._warm_price,
                )
            if plan.warm_g is not None:
                # Keep the carry across empty-snapshot blips (registry
                # rebuild / watch reconnect): a transiently empty refresh
                # must not force the next real solve cold.
                self._warm_g = plan.warm_g
            if plan.warm_price is not None:
                self._warm_price = plan.warm_price
            if delta is not None:
                plan.stats["delta_snapshot"] = delta
            plan.generation = self._generation
            self._plan = plan
            log.info(
                "placement plan refreshed: %d models x %d instances in %.1f ms",
                plan.num_models(), len(instances), plan.solve_ms,
            )
            return plan

    def adopt(self, plan: Optional[GlobalPlan]) -> None:
        """Install a plan published by the leader (watch-fed; None clears).

        Adoption order is the KV watch's event order — the store serializes
        publishes, so the latest delivered plan is the freshest and no
        generation comparison against a possibly-restarted leader is needed.
        """
        self._plan = plan

    # -- SPI ----------------------------------------------------------------

    def choose_load_target(
        self, req: PlacementRequest, view: ClusterView
    ) -> Optional[str]:
        plan = self._plan
        if plan is not None and plan.age_ms() <= self.plan_ttl_ms:
            desired = plan.lookup(req.model_id)
            if desired:
                live = {iid for iid, rec in view.placeable()}
                for iid in desired:
                    if iid in req.exclude or iid not in live:
                        continue
                    if iid in req.model.instance_ids:
                        continue  # already loaded there
                    return LOAD_HERE if iid == req.requesting_instance else iid
        return self.fallback.choose_load_target(req, view)

    def choose_group_targets(
        self, req: PlacementRequest, view: ClusterView,
        shard_count: int, shard_units: int,
    ) -> Optional[dict[str, int]]:
        """Solver-coplanned group placement: the plan's desired instances
        for this model become the group's preferred members (the solve
        already balanced them against fleet capacity — co-location as
        plan columns, the AutoShard-style precedent), topped up to K via
        the greedy group planner with plan members excluded from its
        pool. Group planning stays OUT of the parity-pinned solver
        kernels: the plan is consumed read-only here, never re-shaped,
        so the bitwise cost-surface gates are untouched."""
        keep: dict[str, int] = {}
        taken: set[int] = set()
        for iid, idx in req.model.shard_instances.items():
            if (
                0 <= idx < shard_count
                and idx not in taken
                and iid not in req.exclude
                and iid in view.live_map
                and not view.live_map[iid].draining
            ):
                keep[iid] = idx
                taken.add(idx)
        plan = self._plan
        if plan is not None and plan.age_ms() <= self.plan_ttl_ms:
            live = view.live_map
            missing = [i for i in range(shard_count) if i not in taken]
            for iid in plan.lookup(req.model_id) or ():
                if not missing:
                    break
                rec = live.get(iid)
                if (
                    iid in keep or iid in req.exclude or rec is None
                    or rec.disabled or rec.draining
                    or rec.free_units < shard_units
                ):
                    continue
                idx = missing.pop(0)
                keep[iid] = idx
                taken.add(idx)
        if len(taken) == shard_count:
            return keep
        # Top up the remainder greedily, with the adopted members held
        # sticky via a request whose record claims them.
        merged = dict(req.model.shard_instances)
        merged.update(keep)
        model = req.model
        if merged != model.shard_instances:
            model = copy.deepcopy(req.model)
            model.shard_instances = merged
            # Stickiness in the fallback requires live holders to appear
            # eligible; instance_ids membership is not consulted there.
            synth = dataclasses.replace(req, model=model)
        else:
            synth = req
        return self.fallback.choose_group_targets(
            synth, view, shard_count, shard_units
        )

    def choose_serve_target(
        self, model: ModelRecord, view: ClusterView, exclude: frozenset[str]
    ) -> Optional[str]:
        # Serve balancing stays local/greedy: it needs fresh busyness, not a
        # global solve.
        return self.fallback.choose_serve_target(model, view, exclude)

    def rank_serve_candidates(
        self, model: ModelRecord, view: ClusterView, exclude: frozenset[str]
    ):
        # Candidate-set export for the d-choices route cache: same
        # local/greedy delegation as choose_serve_target.
        return self.fallback.rank_serve_candidates(model, view, exclude)
