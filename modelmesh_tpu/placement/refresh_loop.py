"""Pipelined steady-state refresh loop: software pipelining for plan refresh.

The blocking ``JaxPlacementStrategy.refresh`` serializes the three refresh
phases — host snapshot, device solve, host plan extraction — even though
they use disjoint resources. This driver overlaps them across consecutive
refreshes (the steady-state regime BLITZSCALE-style reuse targets):

- ``submit(N)`` builds snapshot N on the host (a delta patch when dirty
  tracking allows) WHILE the device is still crunching solve N-1,
- dispatches solve N immediately (JAX dispatch is async), chaining the
  warm-start carries (Sinkhorn column potentials + auction prices) from
  solve N-1's still-on-device output arrays — a device-to-device data
  dependency XLA resolves in HBM, with no host round trip, and with the
  carry buffers DONATED on accelerator backends so the steady loop
  re-uses rather than reallocates them (double buffering: solve N-1's
  carry buffer becomes solve N's output buffer and vice versa),
- only then blocks to finalize plan N-1 and install it.

Steady-state cycle time is therefore max(host work, device solve), not
their sum, and the installed plan always lags the submitted snapshot by
exactly one refresh (pipeline depth 1 — bounded staleness, and plans are
advisory anyway).

The steady state is INCREMENTAL-FIRST: when the strategy's dispatch
gates allow (small model-only churn, matched noise epoch — see
``JaxPlacementStrategy._incremental_rows_locked``), a cycle re-solves
only the dirty rows against the device-pinned ``SolveBase`` frozen at
the last full solve, and the base's merge target advances with the
flight's async arrays — no host round trip. Full solves are the
background cadence that re-freezes the base (the MAX_DELTA_STREAK
forced rebuild, instance churn, and the drift/overflow gates), not the
common case. Host materialization happens once per cycle, for the
packed plan the registry publisher needs (finalize_plan's single
batched readback; carries stay device-resident).

Plan visibility is tear-free by construction: a finished plan is installed
into the strategy by a single reference assignment, so concurrent
``choose_load_target`` readers see either generation N-1 or N, never a
mix (pinned by tests/test_steady_refresh.py).
"""

from __future__ import annotations

import logging
import time
from typing import NamedTuple, Optional, Sequence

import numpy as np

from modelmesh_tpu.placement.jax_engine import (
    INCREMENTAL_OVERFLOW_FRAC,
    GlobalPlan,
    JaxPlacementStrategy,
    PendingSolve,
    SolveBase,
    _bucket,
    dispatch_solve,
    finalize_plan,
)

log = logging.getLogger(__name__)


class _InFlight(NamedTuple):
    pending: PendingSolve
    generation: int
    delta: Optional[bool]
    # The noise-epoch seed the solve was dispatched under: its price
    # output is only adoptable as a warm carry while this is still the
    # strategy's current seed (prices and the Gumbel draw are a matched
    # pair).
    seed: int


class PipelinedRefresher:
    """Double-buffered refresh driver around a ``JaxPlacementStrategy``.

    Not thread-safe per instance (the leader's refresh task is one loop);
    plan installation into the strategy is atomic, so request threads can
    read concurrently.
    """

    def __init__(self, strategy: JaxPlacementStrategy, donate: str = "auto"):
        import jax

        self.strategy = strategy
        self._inflight: Optional[_InFlight] = None
        # instance-id column order the in-flight solve's carry is aligned
        # to; a changed fleet breaks the device chain (fall back to the
        # id-keyed host dicts for one refresh).
        self._carry_iids: Optional[list] = None
        if donate == "auto":
            # CPU ignores donation (with a warning per call) — skip it.
            donate = jax.default_backend() != "cpu"
        # Donation is only wired through the single-device jit entry
        # (solve_placement_donated); the mesh path would silently ignore
        # it while finalize skipped the carry readback, leaving the
        # id-keyed fallback dicts permanently stale. It is also mutually
        # exclusive with the device-pinned incremental base: the base
        # aliases the very g/prices buffers a donated flight would
        # consume (resolve_dirty_rows passes g0/price0 straight through
        # as its Placement's carries), so an incremental-enabled
        # strategy keeps donation off and pins the base instead —
        # incremental-first beats buffer reuse in the steady state.
        self._donate = (
            bool(donate)
            and strategy.mesh is None
            and strategy.incr_max_dirty_frac <= 0
        )

    def submit(
        self,
        models: Sequence,
        instances: Sequence,
        rpm_fn=None,
        incremental: bool = True,
    ) -> Optional[GlobalPlan]:
        """Snapshot + dispatch refresh N, then finalize and install plan
        N-1. Returns plan N-1; None on the first call (the pipeline is
        priming; call ``drain()`` to flush the tail) or when plan N-1
        was superseded by an interleaved blocking refresh()."""
        strat = self.strategy
        if not models or not instances:
            # Nothing to solve: flush the pipeline so the caller still
            # observes a terminal state, and keep carries for the next
            # real refresh (transient empty views must not force cold).
            return self.drain()
        with strat._refresh_lock:
            t0 = time.perf_counter()  #: wall-clock: perf_counter solve-timing metric
            cols, delta, dm, di = strat._build_cols_locked(
                models, instances, rpm_fn, incremental
            )
            prev = self._inflight
            carry = None
            donated = False
            # Incremental-first steady state: when the dispatch gates
            # (dirty fraction, matched noise epoch, no instance churn —
            # JaxPlacementStrategy._incremental_rows_locked) allow it,
            # the cycle re-solves only the dirty rows against the
            # device-pinned base frozen at the last full solve. Full
            # solves are the cadence path, not the common case: the
            # MAX_DELTA_STREAK forced rebuild and the drift/overflow
            # gates are what re-freeze the base.
            rows = strat._incremental_rows_locked(cols, delta, dm, di)
            if rows is not None:
                strat._generation += 1
                pending = dispatch_solve(
                    cols, seed=strat._seed, config=strat.solve_config,
                    base=strat._base, dirty_rows=rows, t_start=t0,
                )
                # Advance the merge target NOW, with the in-flight solve's
                # async arrays (a device-to-device reference chain, no
                # host sync): the next cycle's dirty rows must merge into
                # THIS flight's assignment even if it is still crunching
                # when they dispatch. The frozen column state (g/prices/
                # overflow reference) stays at the full solve, so drift
                # accumulated across many increments is still measured
                # against it at finalize.
                strat._base = strat._base._replace(
                    indices=pending.sol.indices, valid=pending.sol.valid
                )
            else:
                # A flight superseded by a blocking refresh() (newer
                # generation already installed) must not chain its device
                # carry: the blocking full rebuild rotated the seed, so the
                # stale flight's prices belong to the OLD draw — fall back
                # to the id-keyed dicts the newer refresh updated instead.
                cur = strat._plan
                superseded = (
                    prev is not None and cur is not None
                    and cur.generation > prev.generation
                )
                if delta and prev is not None and not superseded and (
                    self._carry_iids == cols.instance_ids
                ):
                    sol = prev.pending.sol
                    if sol.g is not None and sol.prices is not None and (
                        sol.g.shape[0] == _bucket(len(cols.instance_ids), 64)
                    ):
                        # Chain the carries device-to-device (async arrays:
                        # this only records a dependency, it does not block).
                        carry = (sol.g, sol.prices)
                        donated = self._donate
                # Shared noise-epoch discipline (delta keeps the seed + may
                # warm prices; full rebuild rotates + drops prices) — see
                # JaxPlacementStrategy._epoch_carries_locked. The device chain,
                # when taken, supersedes the id-keyed dicts entirely.
                warm_g, warm_price = strat._epoch_carries_locked(delta)
                strat._generation += 1
                pending = dispatch_solve(
                    cols, seed=strat._seed, mesh=strat.mesh,
                    warm_g=None if carry else warm_g,
                    warm_price=None if carry else warm_price,
                    config=strat.solve_config, carry=carry,
                    donate=donated, t_start=t0,
                )
            self._inflight = _InFlight(
                pending, strat._generation, delta, strat._seed
            )
            self._carry_iids = cols.instance_ids
            plan = (
                self._finalize_install_locked(
                    prev, consumed=donated, chained=carry is not None
                )
                if prev else None
            )
        return plan

    def drain(self) -> Optional[GlobalPlan]:
        """Finalize the in-flight refresh (if any) and install its plan."""
        strat = self.strategy
        with strat._refresh_lock:
            prev, self._inflight = self._inflight, None
            self._carry_iids = None
            if prev is None:
                return strat._plan
            # An in-flight solve's own carry buffers are only ever donated
            # by a LATER dispatch consuming them; at drain there is none.
            out = self._finalize_install_locked(prev, consumed=False)
            # A superseded flight finalizes to None — the freshest
            # installed plan is still the right thing to hand back.
            return out if out is not None else strat._plan

    # -- internals ----------------------------------------------------------

    def _finalize_install_locked(
        self, flight: _InFlight, consumed: bool, chained: bool = False
    ) -> Optional[GlobalPlan]:
        """Block on solve N-1, pack the plan, install it atomically.
        Returns None when a newer generation was installed meanwhile
        (the stale plan must not reach the caller's publish loop).

        ``consumed``: the carry buffers were donated into the next solve —
        finalize must not read them back (donated buffers are dead on
        accelerator backends), so the id-keyed host fallback dicts keep
        their previous values instead of updating.

        ``chained``: the next solve already took this flight's carries
        device-to-device (non-donating), so materializing the host
        fallback dicts would be a pure extra round trip — skip the
        readback and keep the state device-resident. Incremental flights
        skip it unconditionally (their g/prices are aliases of the
        frozen base, which the host already never needs).
        """
        strat = self.strategy
        incremental = flight.pending.path == "incremental"
        plan = finalize_plan(
            flight.pending._replace(
                sol=_without_carries(flight.pending.sol)
                if consumed else flight.pending.sol
            ),
            fetch_carries=not (consumed or chained or incremental),
        )
        if flight.delta is not None:
            plan.stats["delta_snapshot"] = flight.delta
        plan.stats["pipelined"] = True
        plan.generation = flight.generation
        cur = strat._plan
        if cur is not None and cur.generation > flight.generation:
            # A blocking strategy.refresh() installed a NEWER plan while
            # this flight was in the air — installing (or adopting its
            # carries) would roll readers and the warm state back a
            # generation, and HANDING the stale plan to the caller would
            # let its publish loop roll the whole cluster back (followers
            # fence on KV revision, not generation). Drop it.
            log.info(
                "pipelined plan gen %d superseded by gen %d; dropped",
                flight.generation, cur.generation,
            )
            return None
        if plan.warm_g is not None:
            strat._warm_g = plan.warm_g
        # Adopt prices only while the flight's seed is still current: a
        # full rebuild dispatched AFTER this flight rotated the seed and
        # invalidated _warm_price — re-adopting old-draw prices here
        # would mispair them with the new draw. g is draw-independent.
        if plan.warm_price is not None and flight.seed == strat._seed:
            strat._warm_price = plan.warm_price
        if incremental:
            # The deferred twin of _solve_locked's overflow quality gate:
            # the merged assignment already shipped (its drift is bounded
            # by ONE increment past the budget), but a breach drops the
            # base so the NEXT cycle re-freezes it with a full solve.
            base = strat._base
            if base is not None and base.seed == flight.seed:
                cols = flight.pending.cols
                demand = float(np.sum(cols.sizes * cols.copies))
                budget = base.overflow + INCREMENTAL_OVERFLOW_FRAC * max(
                    demand, 1e-9
                )
                if plan.stats["overflow"] > budget:
                    log.info(
                        "pipelined incremental overflow %.3g drifted past "
                        "the base solve's %.3g + %.2f%% of demand; next "
                        "cycle re-freezes the base with a full solve",
                        plan.stats["overflow"], base.overflow,
                        INCREMENTAL_OVERFLOW_FRAC * 100,
                    )
                    strat._base = None
        elif strat.mesh is None and not consumed:
            # Re-freeze the incremental base from this full solve's
            # still-on-device outputs — no host round trip; the only host
            # pieces (overflow reference, rates column) rode the one
            # batched readback / the host snapshot. Skipped when the
            # flight just dispatched is ALREADY incremental: it merged
            # into (and advanced) the existing base, and overwriting that
            # chain with this older full state would resurrect stale rows.
            sol = flight.pending.sol
            inflight = self._inflight
            if (
                sol.g is not None and sol.prices is not None
                and flight.seed == strat._seed
                and not (
                    inflight is not None
                    and inflight.pending.path == "incremental"
                )
            ):
                cols = flight.pending.cols
                strat._base = SolveBase(
                    indices=sol.indices, valid=sol.valid, g=sol.g,
                    prices=sol.prices, row_err=sol.row_err,
                    seed=flight.seed,
                    overflow=plan.stats["overflow"],
                    rates=np.asarray(cols.rates, np.float32).copy(),  #: host-sync: snapshot rates are host numpy columns
                )
        strat._plan = plan  # atomic install: readers see old or new, whole
        log.info(
            "pipelined plan installed: gen %d, %d models in %.1f ms "
            "(delta=%s)",
            plan.generation, plan.num_models(), plan.solve_ms, flight.delta,
        )
        return plan


def _without_carries(sol):
    """Drop the warm-carry outputs from a Placement whose buffers were
    donated onward — finalize_plan then skips extracting them."""
    return sol._replace(g=None, prices=None)
