"""Pipelined steady-state refresh loop: software pipelining for plan refresh.

The blocking ``JaxPlacementStrategy.refresh`` serializes the three refresh
phases — host snapshot, device solve, host plan extraction — even though
they use disjoint resources. This driver overlaps them across consecutive
refreshes (the steady-state regime BLITZSCALE-style reuse targets):

- ``submit(N)`` builds snapshot N on the host (a delta patch when dirty
  tracking allows) WHILE the device is still crunching solve N-1,
- dispatches solve N immediately (JAX dispatch is async), chaining the
  warm-start carries (Sinkhorn column potentials + auction prices) from
  solve N-1's still-on-device output arrays — a device-to-device data
  dependency XLA resolves in HBM, with no host round trip, and with the
  carry buffers DONATED on accelerator backends so the steady loop
  re-uses rather than reallocates them (double buffering: solve N-1's
  carry buffer becomes solve N's output buffer and vice versa),
- only then blocks to finalize plan N-1 and install it.

Steady-state cycle time is therefore max(host work, device solve), not
their sum, and the installed plan always lags the submitted snapshot by
exactly one refresh (pipeline depth 1 — bounded staleness, and plans are
advisory anyway).

Plan visibility is tear-free by construction: a finished plan is installed
into the strategy by a single reference assignment, so concurrent
``choose_load_target`` readers see either generation N-1 or N, never a
mix (pinned by tests/test_steady_refresh.py).
"""

from __future__ import annotations

import logging
import time
from typing import NamedTuple, Optional, Sequence

from modelmesh_tpu.placement.jax_engine import (
    GlobalPlan,
    JaxPlacementStrategy,
    PendingSolve,
    _bucket,
    dispatch_solve,
    finalize_plan,
)

log = logging.getLogger(__name__)


class _InFlight(NamedTuple):
    pending: PendingSolve
    generation: int
    delta: Optional[bool]
    # The noise-epoch seed the solve was dispatched under: its price
    # output is only adoptable as a warm carry while this is still the
    # strategy's current seed (prices and the Gumbel draw are a matched
    # pair).
    seed: int


class PipelinedRefresher:
    """Double-buffered refresh driver around a ``JaxPlacementStrategy``.

    Not thread-safe per instance (the leader's refresh task is one loop);
    plan installation into the strategy is atomic, so request threads can
    read concurrently.
    """

    def __init__(self, strategy: JaxPlacementStrategy, donate: str = "auto"):
        import jax

        self.strategy = strategy
        self._inflight: Optional[_InFlight] = None
        # instance-id column order the in-flight solve's carry is aligned
        # to; a changed fleet breaks the device chain (fall back to the
        # id-keyed host dicts for one refresh).
        self._carry_iids: Optional[list] = None
        if donate == "auto":
            # CPU ignores donation (with a warning per call) — skip it.
            donate = jax.default_backend() != "cpu"
        # Donation is only wired through the single-device jit entry
        # (solve_placement_donated); the mesh path would silently ignore
        # it while finalize skipped the carry readback, leaving the
        # id-keyed fallback dicts permanently stale.
        self._donate = bool(donate) and strategy.mesh is None

    def submit(
        self,
        models: Sequence,
        instances: Sequence,
        rpm_fn=None,
        incremental: bool = True,
    ) -> Optional[GlobalPlan]:
        """Snapshot + dispatch refresh N, then finalize and install plan
        N-1. Returns plan N-1; None on the first call (the pipeline is
        priming; call ``drain()`` to flush the tail) or when plan N-1
        was superseded by an interleaved blocking refresh()."""
        strat = self.strategy
        if not models or not instances:
            # Nothing to solve: flush the pipeline so the caller still
            # observes a terminal state, and keep carries for the next
            # real refresh (transient empty views must not force cold).
            return self.drain()
        with strat._refresh_lock:
            t0 = time.perf_counter()  #: wall-clock: perf_counter solve-timing metric
            cols, delta, _dm, _di = strat._build_cols_locked(
                models, instances, rpm_fn, incremental
            )
            # The pipelined driver always dispatches FULL solves and never
            # captures an incremental base (a donated flight consumes the
            # very g/prices buffers a base would alias); a base left over
            # from an earlier blocking refresh is superseded the moment a
            # newer pipelined plan lands, so drop it now.
            strat._base = None
            prev = self._inflight
            carry = None
            donated = False
            # A flight superseded by a blocking refresh() (newer
            # generation already installed) must not chain its device
            # carry: the blocking full rebuild rotated the seed, so the
            # stale flight's prices belong to the OLD draw — fall back
            # to the id-keyed dicts the newer refresh updated instead.
            cur = strat._plan
            superseded = (
                prev is not None and cur is not None
                and cur.generation > prev.generation
            )
            if delta and prev is not None and not superseded and (
                self._carry_iids == cols.instance_ids
            ):
                sol = prev.pending.sol
                if sol.g is not None and sol.prices is not None and (
                    sol.g.shape[0] == _bucket(len(cols.instance_ids), 64)
                ):
                    # Chain the carries device-to-device (async arrays:
                    # this only records a dependency, it does not block).
                    carry = (sol.g, sol.prices)
                    donated = self._donate
            # Shared noise-epoch discipline (delta keeps the seed + may
            # warm prices; full rebuild rotates + drops prices) — see
            # JaxPlacementStrategy._epoch_carries_locked. The device chain,
            # when taken, supersedes the id-keyed dicts entirely.
            warm_g, warm_price = strat._epoch_carries_locked(delta)
            strat._generation += 1
            pending = dispatch_solve(
                cols, seed=strat._seed, mesh=strat.mesh,
                warm_g=None if carry else warm_g,
                warm_price=None if carry else warm_price,
                config=strat.solve_config, carry=carry,
                donate=donated, t_start=t0,
            )
            self._inflight = _InFlight(
                pending, strat._generation, delta, strat._seed
            )
            self._carry_iids = cols.instance_ids
            plan = self._finalize_install_locked(prev, consumed=donated) if prev else None
        return plan

    def drain(self) -> Optional[GlobalPlan]:
        """Finalize the in-flight refresh (if any) and install its plan."""
        strat = self.strategy
        with strat._refresh_lock:
            prev, self._inflight = self._inflight, None
            self._carry_iids = None
            if prev is None:
                return strat._plan
            # An in-flight solve's own carry buffers are only ever donated
            # by a LATER dispatch consuming them; at drain there is none.
            out = self._finalize_install_locked(prev, consumed=False)
            # A superseded flight finalizes to None — the freshest
            # installed plan is still the right thing to hand back.
            return out if out is not None else strat._plan

    # -- internals ----------------------------------------------------------

    def _finalize_install_locked(
        self, flight: _InFlight, consumed: bool
    ) -> Optional[GlobalPlan]:
        """Block on solve N-1, pack the plan, install it atomically.
        Returns None when a newer generation was installed meanwhile
        (the stale plan must not reach the caller's publish loop).

        ``consumed``: the carry buffers were donated into the next solve —
        finalize must not read them back (donated buffers are dead on
        accelerator backends), so the id-keyed host fallback dicts keep
        their previous values instead of updating.
        """
        strat = self.strategy
        plan = finalize_plan(
            flight.pending._replace(
                sol=_without_carries(flight.pending.sol)
                if consumed else flight.pending.sol
            )
        )
        if flight.delta is not None:
            plan.stats["delta_snapshot"] = flight.delta
        plan.stats["pipelined"] = True
        plan.generation = flight.generation
        cur = strat._plan
        if cur is not None and cur.generation > flight.generation:
            # A blocking strategy.refresh() installed a NEWER plan while
            # this flight was in the air — installing (or adopting its
            # carries) would roll readers and the warm state back a
            # generation, and HANDING the stale plan to the caller would
            # let its publish loop roll the whole cluster back (followers
            # fence on KV revision, not generation). Drop it.
            log.info(
                "pipelined plan gen %d superseded by gen %d; dropped",
                flight.generation, cur.generation,
            )
            return None
        if plan.warm_g is not None:
            strat._warm_g = plan.warm_g
        # Adopt prices only while the flight's seed is still current: a
        # full rebuild dispatched AFTER this flight rotated the seed and
        # invalidated _warm_price — re-adopting old-draw prices here
        # would mispair them with the new draw. g is draw-independent.
        if plan.warm_price is not None and flight.seed == strat._seed:
            strat._warm_price = plan.warm_price
        strat._plan = plan  # atomic install: readers see old or new, whole
        log.info(
            "pipelined plan installed: gen %d, %d models in %.1f ms "
            "(delta=%s)",
            plan.generation, plan.num_models(), plan.solve_ms, flight.delta,
        )
        return plan


def _without_carries(sol):
    """Drop the warm-carry outputs from a Placement whose buffers were
    donated onward — finalize_plan then skips extracting them."""
    return sol._replace(g=None, prices=None)
