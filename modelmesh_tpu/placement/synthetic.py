"""Synthetic cluster-state generator for benchmarks and scale tests.

One shared workload definition so bench.py and tests/test_refresh_scale.py
measure the SAME synthetic registry instead of drifting copies: n models
across ``types`` model types with lognormal-ish sizes, every
``loaded_every``-th model pre-loaded on a random instance, m instances over
three zones.
"""

from __future__ import annotations

import numpy as np

from modelmesh_tpu.records import InstanceRecord, ModelRecord


def synthetic_records(
    n: int,
    m: int,
    *,
    capacity_units: int = 50_000,
    loaded_every: int = 3,
    types: int = 8,
    seed: int = 7,
):
    """Returns (models, instances) as (id, record) tuple lists — the same
    shape registry/instance snapshots have at a refresh site."""
    rng = np.random.default_rng(seed)
    sizes = rng.integers(16, 256, n)
    loaded_on = rng.integers(0, m, n)
    models = []
    for i in range(n):
        mr = ModelRecord(
            model_type=f"t{i % types}", size_units=int(sizes[i]),
            last_used=1_000_000 + i,
        )
        if loaded_every and i % loaded_every == 0:
            mr.instance_ids[f"i{loaded_on[i]}"] = 1
        models.append((f"m{i}", mr))
    instances = [
        (f"i{j}", InstanceRecord(
            capacity_units=capacity_units, used_units=500, zone=f"z{j % 3}",
            lru_ts=1_000, req_per_minute=int(j % 60),
        ))
        for j in range(m)
    ]
    return models, instances
