"""Fleet-wide plan distribution over the KV store.

The leader's reaper solves one global assignment (jax_engine.solve_plan) and
publishes the serialized GlobalPlan under ``<prefix>/plan``; every instance —
leader included — runs a PlanFollower that watches that key and installs each
published plan into its JaxPlacementStrategy. This closes the loop the
reference closes through the shared registry (leader placement decisions at
ModelMesh.java:6616-6747 become visible to all instances via registry
watches): placement decisions taken at ANY instance follow the central solve,
while per-instance local guards (capacity, churn age, exclusions) remain
authoritative and greedy remains the fallback for plan misses.

Size discipline: the KV data plane caps values at the gRPC message limit
(16 MiB default, serving config). A 100k-model plan compresses well under
that, but the publisher still enforces a byte budget by truncating the
placement map (models beyond the budget simply fall back to greedy at the
followers) rather than failing the publish or splitting into
non-atomically-visible chunks.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from modelmesh_tpu.kv.store import EventType, KVStore, WatchHandle
from modelmesh_tpu.placement.jax_engine import GlobalPlan

log = logging.getLogger(__name__)

PLAN_KEY = "plan"
# The plan byte budget lives in the MM_MAX_PLAN_BYTES env registration
# (utils/envs.py, default 12 MiB — headroom under the 16 MiB data plane).
# Absolute staleness bound on ADOPTION, judged by the publisher's solve
# timestamp (generous to tolerate clock skew — plans are advisory). Without
# it, an instance starting hours after the leader died would resurrect the
# orphaned plan with a fresh TTL from its initial read.
MAX_PLAN_WALL_AGE_MS = 60 * 60_000


def plan_key(prefix: str) -> str:
    return f"{prefix.rstrip('/')}/{PLAN_KEY}"


def publish_plan(
    store: KVStore,
    prefix: str,
    plan: GlobalPlan,
    max_bytes: Optional[int] = None,
) -> int:
    """Serialize + put the plan; returns the published byte size.

    ``max_bytes`` defaults from the MM_MAX_PLAN_BYTES env knob
    (utils/envs.py) so operators can tune the plan byte budget without a
    code change. If the serialized plan exceeds it, the placement map is
    truncated from the TAIL. This relies on solve_plan emitting placements
    hottest-first (jax_engine.py sorts by problem rates precisely so this
    truncation sheds the coldest models); reordering the placement dict
    breaks that invariant. Dropped models serve greedy at followers.
    """
    if max_bytes is None:
        from modelmesh_tpu.utils import envs

        max_bytes = envs.get_int("MM_MAX_PLAN_BYTES")
    store_cap = store.max_value_bytes()
    if store_cap is not None:
        max_bytes = min(max_bytes, store_cap)
    data = plan.to_bytes()
    if len(data) > max_bytes:
        # Binary-search-free trim: drop proportionally and re-check once,
        # then hard-drop in halves until under budget.
        n_keep = plan.num_models()
        while n_keep and len(data) > max_bytes:
            keep = max(1, int(n_keep * max_bytes / len(data) * 0.9))
            if keep >= n_keep:
                keep = n_keep // 2
            n_keep = keep
            data = plan.truncate(n_keep).to_bytes()
        log.warning(
            "plan publish truncated to %d models (%d bytes, budget %d)",
            n_keep, len(data), max_bytes,
        )
    store.put(plan_key(prefix), data)
    return len(data)


class PlanFollower:
    """Watch-fed plan subscription: installs published plans into a strategy.

    Attach to any strategy exposing ``adopt(plan|None)`` (JaxPlacementStrategy).
    The initial state is read synchronously so an instance that starts after
    the leader's last solve still serves the current plan immediately.
    """

    def __init__(self, store: KVStore, prefix: str, strategy) -> None:
        self._key = plan_key(prefix)
        self._strategy = strategy
        self._handle: Optional[WatchHandle] = None
        # Revision fencing: the constructor's synchronous reads and the
        # watch callbacks are two unordered delivery paths; installing only
        # monotonically newer mod_revs keeps a descheduled initial read from
        # clobbering a fresher watch-delivered plan.
        self._lock = threading.Lock()
        self._last_rev = 0
        start_rev = None
        try:
            kv = store.get(self._key)
            if kv is not None:
                self._decode_and_adopt(kv.value, kv.mod_rev)
                start_rev = kv.mod_rev
        except Exception:  # noqa: BLE001 — plan is advisory; greedy covers
            log.exception("initial plan read failed; starting from watch")
        self._handle = store.watch(self._key, self._on_events, start_rev=start_rev)
        if start_rev is None:
            # Close the get->watch gap: a plan published in between would be
            # invisible to a None-start watch (no replay) until the next
            # solve. One post-subscription read covers it; the watch handles
            # everything after.
            try:
                kv = store.get(self._key)
                if kv is not None:
                    self._decode_and_adopt(kv.value, kv.mod_rev)
            except Exception:  # noqa: BLE001
                pass

    def _decode_and_adopt(self, value: bytes, mod_rev: int) -> None:
        try:
            plan = GlobalPlan.from_bytes(value)
        except Exception:  # noqa: BLE001 — a bad plan must not kill the watch
            log.exception("discarding undecodable published plan")
            return
        wall_age = plan.adopted_at_ms - plan.solved_at_ms
        if wall_age > MAX_PLAN_WALL_AGE_MS:
            log.warning(
                "ignoring orphaned plan (solved %.0f min ago — leader gone?)",
                wall_age / 60_000,
            )
            return
        # Build the model->row index here, in the watch thread, so the first
        # routed request after adoption doesn't pay for it.
        plan.ensure_index()
        with self._lock:
            if mod_rev <= self._last_rev:
                return
            self._last_rev = mod_rev
            self._strategy.adopt(plan)

    def _on_events(self, events) -> None:
        for ev in events:
            if ev.kv.key != self._key:
                continue  # prefix watch may over-match sibling keys
            if ev.type is EventType.PUT:
                self._decode_and_adopt(ev.kv.value, ev.kv.mod_rev)
            else:
                with self._lock:
                    if ev.kv.mod_rev > self._last_rev:
                        self._last_rev = ev.kv.mod_rev
                        self._strategy.adopt(None)

    def close(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
