"""Shadow-mode strategy evaluation: serve greedy, measure the solver.

SURVEY.md section 7 build plan step 9 prescribes running the JAX global
strategy "in shadow-mode vs greedy before promoting": every LOAD-placement
decision is taken by the ``primary`` (production) strategy, while the
``shadow`` strategy answers the same question on the side (serve-target
balancing is not scored — the jax strategy serves via its greedy fallback
by design, so that comparison would be tautological). Agreement is
counted, recent divergences are kept for the ***STATE*** dump, and shadow
failures can never affect serving — operators read the agreement rate,
then flip ``--strategy jax`` with evidence instead of faith.

The reference has no analog (its heuristics are hardcoded inline); this is
the promotion-safety half of the PlacementStrategy SPI departure.
"""

from __future__ import annotations

import collections
import logging
import threading
from typing import Optional

from modelmesh_tpu.placement.strategy import (
    ClusterView,
    PlacementRequest,
    PlacementStrategy,
)
from modelmesh_tpu.records import ModelRecord

log = logging.getLogger(__name__)


class ShadowStrategy(PlacementStrategy):
    """Route decisions through ``primary``; score ``shadow`` on the side.

    Divergence is not error: the solver legitimately disagrees with greedy
    (that's why it exists) — the operator question is whether its answers
    are *plausible* (valid instances, stable rate). ``stats()`` gives the
    rates; ``recent_divergences`` the concrete cases to eyeball.
    """

    def __init__(
        self,
        primary: PlacementStrategy,
        shadow: PlacementStrategy,
        keep_recent: int = 64,
    ):
        self.primary = primary
        self.shadow = shadow
        self._lock = threading.Lock()
        self._counts = collections.Counter()
        self._recent = collections.deque(maxlen=keep_recent)

    # -- attach points (instance wiring fans state into both sides) --------

    @property
    def time_stats(self):
        return getattr(self.primary, "time_stats", None)

    @time_stats.setter
    def time_stats(self, ts) -> None:
        for s in (self.primary, self.shadow):
            if hasattr(s, "time_stats"):
                s.time_stats = ts
            fb = getattr(s, "fallback", None)
            if fb is not None and hasattr(fb, "time_stats"):
                fb.time_stats = ts

    @property
    def constraints(self):
        return getattr(self.primary, "constraints", None)

    @constraints.setter
    def constraints(self, c) -> None:
        for s in (self.primary, self.shadow):
            if hasattr(s, "constraints") and getattr(s, "constraints") is None:
                s.constraints = c
            fb = getattr(s, "fallback", None)
            if fb is not None and hasattr(fb, "constraints") and (
                getattr(fb, "constraints", None) is None
            ):
                fb.constraints = c

    def adopt(self, plan) -> None:
        """PlanFollower feed: published plans flow to the shadow solver."""
        if hasattr(self.shadow, "adopt"):
            self.shadow.adopt(plan)

    def refresh(self, models, instances, rpm_fn=None):
        """Leader reaper cadence (serving/tasks.py): a shadow fleet must
        still SOLVE and publish plans — without this, no plan ever exists,
        the shadow permanently answers from its greedy fallback, and the
        agreement metric reads ~1.0: false evidence, the exact failure
        shadow mode exists to prevent."""
        return self.shadow.refresh(models, instances, rpm_fn)

    # -- decision SPI -------------------------------------------------------

    def _observe(self, kind: str, model_id: str, primary_out, shadow_fn):
        try:
            shadow_out = shadow_fn()
        except Exception as e:  # noqa: BLE001 — shadow must never hurt
            with self._lock:
                self._counts[f"{kind}_shadow_error"] += 1
            log.debug("shadow %s failed for %s: %s", kind, model_id, e)
            return
        with self._lock:
            if shadow_out == primary_out:
                self._counts[f"{kind}_agree"] += 1
            else:
                self._counts[f"{kind}_diverge"] += 1
                self._recent.append(
                    {"kind": kind, "model": model_id,
                     "primary": primary_out, "shadow": shadow_out}
                )

    def choose_load_target(
        self, req: PlacementRequest, view: ClusterView
    ) -> Optional[str]:
        out = self.primary.choose_load_target(req, view)
        self._observe(
            "load", req.model_id, out,
            lambda: self.shadow.choose_load_target(req, view),
        )
        return out

    def choose_serve_target(
        self, model: ModelRecord, view: ClusterView, exclude: frozenset[str]
    ) -> Optional[str]:
        # NOT scored: the jax strategy serves via its greedy fallback by
        # design (balancing needs fresh busyness, not a global solve —
        # jax_engine.choose_serve_target), so shadow-vs-primary here would
        # compare greedy to greedy and report a tautological 1.0 agreement
        # — false promotion evidence. Only load placement carries solver
        # signal.
        return self.primary.choose_serve_target(model, view, exclude)

    def rank_serve_candidates(
        self, model: ModelRecord, view: ClusterView, exclude: frozenset[str]
    ):
        # Unscored pass-through, same rationale as choose_serve_target.
        return self.primary.rank_serve_candidates(model, view, exclude)

    # -- reporting ----------------------------------------------------------

    def shadow_stats(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
            recent = list(self._recent)
        out: dict = {"counts": counts, "recent_divergences": recent}
        # Only load placement is scored (serve decisions pass through
        # unscored — see choose_serve_target).
        agree = counts.get("load_agree", 0)
        total = agree + counts.get("load_diverge", 0)
        if total:
            out["load_agreement"] = round(agree / total, 4)
        return out
