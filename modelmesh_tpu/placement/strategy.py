"""PlacementStrategy SPI: every placement decision behind one interface.

The architectural departure from the reference (SURVEY.md section 7): the
reference hardcodes its greedy heuristics inline (PLACEMENT_ORDER
ModelMesh.java:4646, CacheMissForwardingLB :4757-5004, janitor scale-down
:6197-6379, reaper proactive loads :6616-6747). Here those decisions are
pluggable: ``greedy`` reproduces the reference behavior as the default and
correctness oracle; ``jax`` (placement/jax_engine.py) solves the global
assignment on TPU and serves plans from which per-request decisions read.
"""

from __future__ import annotations

import abc
import dataclasses
import functools
from typing import Optional, Sequence

from modelmesh_tpu.records import InstanceRecord, ModelRecord

# Sentinel: "load on the requesting instance itself" (the reference's
# ABORT_REQUEST path meaning 'you take it', ModelMesh.java:4987-5004).
LOAD_HERE = "<here>"


@dataclasses.dataclass(frozen=True)
class PlacementRequest:
    model_id: str
    model: ModelRecord
    required_units: int
    requesting_instance: str
    exclude: frozenset[str] = frozenset()
    last_used_ms: int = 0


@dataclasses.dataclass(frozen=True)
class ClusterView:
    """Immutable snapshot of live instances (from the instances TableView).

    ``epoch`` is the TableView version the snapshot was taken at (-1 for
    ad-hoc views built outside the watch-fed path). Views are shared
    across requests until the epoch moves, so the derived collections are
    computed once per snapshot, not per request (cached_property writes
    straight into __dict__, which the frozen dataclass permits)."""

    instances: Sequence[tuple[str, InstanceRecord]]
    epoch: int = -1

    @functools.cached_property
    def _live(self) -> list[tuple[str, InstanceRecord]]:
        return [(i, r) for i, r in self.instances if not r.shutting_down]

    @functools.cached_property
    def live_map(self) -> dict[str, InstanceRecord]:
        """id -> record of live instances; the O(1) lookup the per-request
        serve-target selection reads instead of rebuilding a dict."""
        return dict(self._live)

    @functools.cached_property
    def _placeable(self) -> list[tuple[str, InstanceRecord]]:
        return [
            (i, r) for i, r in self._live
            if not r.disabled and not r.draining
        ]

    def live(self) -> list[tuple[str, InstanceRecord]]:
        return self._live

    def placeable(self) -> list[tuple[str, InstanceRecord]]:
        """Candidates for NEW placements: live, not admin-drained, and not
        DRAINING (reconfig/drain.py). Serve routing keeps using live() —
        a disabled or draining instance's already-loaded copies continue
        serving (drain, not eviction)."""
        return self._placeable


class PlacementStrategy(abc.ABC):
    @abc.abstractmethod
    def choose_load_target(
        self, req: PlacementRequest, view: ClusterView
    ) -> Optional[str]:
        """Pick the instance that should load a new copy.

        Returns an instance id, LOAD_HERE (requester loads it), or None
        (nowhere to place — caller surfaces NoCapacityError).
        """

    @abc.abstractmethod
    def choose_serve_target(
        self, model: ModelRecord, view: ClusterView,
        exclude: frozenset[str],
    ) -> Optional[str]:
        """Pick a loaded copy to serve a request (cache-hit balancing)."""

    def choose_group_targets(
        self, req: PlacementRequest, view: ClusterView,
        shard_count: int, shard_units: int,
    ) -> Optional[dict[str, int]]:
        """Plan a PLACEMENT GROUP for a sharded model: assign each shard
        index 0..shard_count-1 to a DISTINCT instance, each with room for
        one shard (``shard_units``). Returns {instance_id: shard_index}
        or None when the fleet cannot host the whole group — group
        placement is atomic: all K members or nothing (a partial group
        can never serve, so partially placing one only wastes capacity).

        Existing same-index members in ``req.model.shard_instances``
        should be kept sticky so a re-plan tops up the missing shards
        instead of shuffling weights that already landed.

        Default: capacity-greedy — live placeable non-excluded instances
        ranked by free capacity, sticky members first. Strategies with a
        global plan override this (the solver co-plans the group as
        co-location columns in its cost surface).
        """
        keep: dict[str, int] = {}
        taken: set[int] = set()
        for iid, idx in req.model.shard_instances.items():
            if (
                0 <= idx < shard_count
                and idx not in taken
                and iid not in req.exclude
                and iid in view.live_map
                and not view.live_map[iid].draining
            ):
                keep[iid] = idx
                taken.add(idx)
        candidates = sorted(
            (
                (iid, rec) for iid, rec in view.placeable()
                if iid not in req.exclude and iid not in keep
                and rec.free_units >= shard_units
            ),
            key=lambda p: (-p[1].free_units, p[0]),
        )
        missing = [i for i in range(shard_count) if i not in taken]
        if len(candidates) < len(missing):
            return None
        assignments = dict(keep)
        for idx, (iid, _) in zip(missing, candidates):
            assignments[iid] = idx
        return assignments
