"""Placement strategies: the SPI, greedy oracle, and JAX global solver."""

from modelmesh_tpu.placement.greedy import GreedyStrategy
from modelmesh_tpu.placement.strategy import (
    LOAD_HERE,
    ClusterView,
    PlacementRequest,
    PlacementStrategy,
)

__all__ = [
    "GreedyStrategy",
    "LOAD_HERE",
    "ClusterView",
    "PlacementRequest",
    "PlacementStrategy",
]
