"""Device-mesh helpers for the sharded placement solver.

Axis convention:
- ``"mdl"``  — shards the model axis (rows of the cost matrix). This is the
  long dimension (up to 1M models) and the primary sharding axis.
- ``"inst"`` — optionally shards the instance axis (columns) for cost
  assembly and column-potential work; rows are gathered before top-k.

The solver's collectives (psum / pmax / all_gather) ride whatever fabric the
mesh spans: ICI within a slice, DCN across hosts.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "mdl"
INSTANCE_AXIS = "inst"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` compat shim: the API graduated out of
    ``jax.experimental`` (renaming ``check_rep`` -> ``check_vma``) in newer
    releases; dispatch to whichever this jax provides so the sharded paths
    run on both sides of the move."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def make_mesh(
    shape: Sequence[int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a (mdl, inst) mesh. Default: all devices on the model axis."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices), 1)
    arr = np.asarray(devices).reshape(tuple(shape))
    return Mesh(arr, (MODEL_AXIS, INSTANCE_AXIS))


def problem_pspec():
    """PartitionSpec pytree for a PlacementProblem: model-axis arrays sharded
    on ``mdl``, instance-axis arrays on ``inst``, matrices on both.

    Single source of truth for the solver's input layout — used both as
    shard_map in_specs and (wrapped in NamedSharding) for device_put.
    """
    from modelmesh_tpu.ops.costs import PlacementProblem

    row = P(MODEL_AXIS)
    col = P(INSTANCE_AXIS)
    mat = P(MODEL_AXIS, INSTANCE_AXIS)
    return PlacementProblem(
        sizes=row, copies=row, rates=row, loaded=mat, feasible=mat,
        capacity=col, reserved=col, lru_age=col, busyness=col, zone=col,
        preferred=mat,
    )


def problem_shardings(mesh: Mesh):
    """NamedSharding pytree for device_put of a PlacementProblem."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        problem_pspec(),
        is_leaf=lambda x: isinstance(x, P),
    )
