"""Device-mesh helpers for the sharded placement solver.

Axis convention:
- ``"mdl"``  — shards the model axis (rows of the cost matrix). This is the
  long dimension (up to 1M models) and the primary sharding axis.
- ``"inst"`` — optionally shards the instance axis (columns) for cost
  assembly and column-potential work; rows are gathered before top-k.

The solver's collectives (psum / pmax / all_gather) ride whatever fabric the
mesh spans: ICI within a slice, DCN across hosts.
"""

from __future__ import annotations

import threading as _threading
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "mdl"
INSTANCE_AXIS = "inst"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` compat shim: the API graduated out of
    ``jax.experimental`` (renaming ``check_rep`` -> ``check_vma``) in newer
    releases; dispatch to whichever this jax provides so the sharded paths
    run on both sides of the move."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def make_mesh(
    shape: Sequence[int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a (mdl, inst) mesh. Default: all devices on the model axis."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devices), 1)
    arr = np.asarray(devices).reshape(tuple(shape))
    return Mesh(arr, (MODEL_AXIS, INSTANCE_AXIS))


def problem_pspec():
    """PartitionSpec pytree for a PlacementProblem: model-axis arrays sharded
    on ``mdl``, instance-axis arrays on ``inst``, matrices on both.

    Single source of truth for the solver's input layout — used both as
    shard_map in_specs and (wrapped in NamedSharding) for device_put.
    """
    from modelmesh_tpu.ops.costs import PlacementProblem

    row = P(MODEL_AXIS)
    col = P(INSTANCE_AXIS)
    mat = P(MODEL_AXIS, INSTANCE_AXIS)
    return PlacementProblem(
        sizes=row, copies=row, rates=row, loaded=mat, feasible=mat,
        capacity=col, reserved=col, lru_age=col, busyness=col, zone=col,
        preferred=mat,
    )


def problem_shardings(mesh: Mesh):
    """NamedSharding pytree for device_put of a PlacementProblem."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        problem_pspec(),
        is_leaf=lambda x: isinstance(x, P),
    )


# -- serving mesh (sharded multi-device model execution) ----------------------
#
# The solver meshes above shard the PLACEMENT PROBLEM; the serving mesh
# shards MODEL WEIGHTS for execution (models/server.py sharded path). On
# a real TPU the mesh spans the slice's chips over ICI; under tier-1 the
# conftest's XLA_FLAGS=--xla_force_host_platform_device_count emulation
# provides the multi-device pool on CPU, so the exact same pjit program
# runs in tests.

_serving_lock = _threading.Lock()
_serving_meshes: dict[int, Mesh] = {}  #: guarded-by: _serving_lock


def serving_mesh(n_devices: int | None = None) -> Mesh:
    """The 1-D weight-sharding mesh (axis ``mdl``) over ``n_devices``
    local devices (default: MM_SHARDED_MESH_DEVICES, 0 = every visible
    device). Cached per size — pjit caches are keyed on mesh identity,
    so handing out a fresh Mesh per load would recompile every model."""
    if n_devices is None:
        from modelmesh_tpu.utils import envs

        n_devices = envs.get_int("MM_SHARDED_MESH_DEVICES")
    devs = jax.devices()
    n = len(devs) if not n_devices else min(int(n_devices), len(devs))
    n = max(n, 1)
    with _serving_lock:
        mesh = _serving_meshes.get(n)
        if mesh is None:
            mesh = Mesh(np.asarray(devs[:n]), (MODEL_AXIS,))
            _serving_meshes[n] = mesh
        return mesh


def param_pspec(leaf, n_devices: int) -> P:
    """Partition spec for ONE parameter leaf on the serving mesh: shard
    the last axis (column-parallel — the per-family convention for every
    LAYER_STREAMABLE family, whose compute is dense matmuls feeding the
    next layer) when it divides the mesh; replicate everything else
    (biases, layer norms, and any awkward shape). A non-dividing axis is
    replicated rather than padded: correctness over memory, and the
    bitwise parity gate forbids value-changing padding."""
    shape = getattr(leaf, "shape", ())
    if len(shape) >= 2 and n_devices > 1 and shape[-1] % n_devices == 0:
        return P(*([None] * (len(shape) - 1) + [MODEL_AXIS]))
    return P()


def shard_params(params, mesh: Mesh):
    """device_put a parameter pytree onto the serving mesh with the
    per-leaf specs from ``param_pspec``. The committed shardings make
    every downstream ``jit`` of apply() execute distributed — XLA
    propagates the layout and inserts the collectives (guide idiom:
    shard the divisible weight axis, replicate the rest)."""
    n = mesh.devices.size
    return jax.tree.map(
        lambda leaf: jax.device_put(
            leaf, NamedSharding(mesh, param_pspec(leaf, n))
        ),
        params,
    )
