"""Device-mesh helpers and the multi-chip sharded placement solver."""

from modelmesh_tpu.parallel.mesh import (
    INSTANCE_AXIS,
    MODEL_AXIS,
    make_mesh,
    problem_shardings,
)
from modelmesh_tpu.parallel.sharded_solver import (
    make_sharded_solver,
    shard_problem,
)

__all__ = [
    "INSTANCE_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "problem_shardings",
    "make_sharded_solver",
    "shard_problem",
]
