"""Multi-chip sharded global placement solve (shard_map over a device mesh).

Scales the ops/solve.py pipeline to the 1M models x 10k instances tier of the
BASELINE.json ladder by sharding the cost matrix rows (model axis) across
devices, optionally also columns (instance axis):

- cost assembly: fully blocked; cross-block normalizations use pmin/pmax and
  psum collectives.
- Sinkhorn: blockwise log-sum-exp — local max + ``pmax`` then shifted
  ``psum`` of exponentials, the standard sharded-LSE recipe. Row potentials
  stay sharded on ``mdl``, column potentials on ``inst``.
- auction rounding: per-row top-k needs full rows, so plan logits are
  ``all_gather``-ed along ``inst`` (a no-op on the default 1-column mesh);
  implied instance loads are ``psum``-ed along ``mdl`` so every device sees
  identical congestion prices.

All collectives are XLA natives riding ICI/DCN; there is no host round-trip
inside the solve.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from modelmesh_tpu.ops.sinkhorn import gated_sinkhorn_loop, resolve_lse_impl
from modelmesh_tpu.ops.auction import (
    K_CAND,
    MAX_COPIES,
    RESHORTLIST_EVERY,
    _NEG_INF,
    _implied_load,
    _stall_gated_rounds,
    check_rounding_config,
    final_candidate,
    hash_gumbel,
    price_step,
    resolve_load_impl,
    select_from_candidates,
    shortlist,
    warm_probe,
)
from modelmesh_tpu.ops.costs import INFEASIBLE, CostWeights, PlacementProblem
from modelmesh_tpu.ops.solve import Placement, SolveConfig
from modelmesh_tpu.parallel import mesh as mesh_mod
from modelmesh_tpu.parallel.mesh import INSTANCE_AXIS, MODEL_AXIS


def _norm_sharded(x: jax.Array, axis_name: str) -> jax.Array:
    lo = jax.lax.pmin(jnp.min(x), axis_name)
    hi = jax.lax.pmax(jnp.max(x), axis_name)
    span = hi - lo
    return jnp.where(span > 0, (x - lo) / jnp.maximum(span, 1e-30), 0.0)


def _cost_block(p: PlacementProblem, w: CostWeights, dtype) -> jax.Array:
    """Cost matrix block from row-sharded model state + col-sharded instance
    state. Mirrors ops.costs.assemble_cost with sharded reductions."""
    loaded_f = p.loaded.astype(jnp.float32)
    loaded_mass = jax.lax.psum(
        p.sizes @ loaded_f, MODEL_AXIS
    )  # [m_blk] (sizes @ loaded == loaded.T @ sizes, minus the transpose)
    used_frac = jnp.clip(
        (p.reserved + loaded_mass) / jnp.maximum(p.capacity, 1.0), 0.0, 1.5
    )
    busy = _norm_sharded(p.busyness, INSTANCE_AXIS)
    age = _norm_sharded(p.lru_age, INSTANCE_AXIS)
    rate = _norm_sharded(p.rates, MODEL_AXIS)

    zone_onehot = jax.nn.one_hot(p.zone, w.num_zones, dtype=jnp.float32)
    cpz = jax.lax.psum(
        loaded_f @ zone_onehot, INSTANCE_AXIS
    )  # [n_blk, Z] full-width zone counts
    denom = jnp.maximum(jnp.sum(cpz, axis=1, keepdims=True), 1.0)
    # One-element gather of the instance's zone column (bit-identical to
    # the one-hot matmul it replaces — see ops.costs.assemble_cost).
    crowding = jnp.where(
        (p.zone >= 0) & (p.zone < w.num_zones),
        (cpz / denom)[:, p.zone],
        0.0,
    )

    per_instance = w.utilization * used_frac - w.lru_age * age
    cost = (
        w.move * (1.0 - loaded_f)
        + per_instance[None, :]
        + w.balance * rate[:, None] * busy[None, :]
        + w.zone_spread * crowding
        + w.preference * (1.0 - p.preferred.astype(jnp.float32))
        + INFEASIBLE * (1.0 - p.feasible.astype(jnp.float32))
    )
    return cost.astype(dtype)


def _lse(z_blk: jax.Array, axis: int, axis_name: str) -> jax.Array:
    """Sharded log-sum-exp of an [n_blk, m_blk] block along ``axis`` whose
    full extent is distributed over mesh axis ``axis_name``."""
    m = jax.lax.pmax(jnp.max(z_blk, axis=axis), axis_name)
    shift = jnp.expand_dims(m, axis)
    s = jax.lax.psum(jnp.sum(jnp.exp(z_blk - shift), axis=axis), axis_name)
    return jnp.log(jnp.maximum(s, 1e-30)) + m


def _sharded_sinkhorn(C, row_mass, col_mass, eps: float, iters: int,
                      lse_impl: str = "xla", g0=None,
                      tol: float = 0.0, chunk: int = 4):
    # Semi-unbalanced (rows equality, columns CAPS via g <= 0) — must match
    # ops/sinkhorn.py exactly; the parity tests compare potentials.
    log_a = jnp.log(jnp.maximum(row_mass, 1e-30))
    log_b = jnp.log(jnp.maximum(col_mass, 1e-30))
    Cf = C.astype(jnp.float32)

    if lse_impl == "pallas":
        # Per-shard Pallas partial reductions (ops/pallas_lse.py) combined
        # with pmax/psum — each device streams only ITS C block through
        # VMEM; the collective carries just the (m, s) vectors.
        from modelmesh_tpu.ops import pallas_lse

        interp = jax.default_backend() != "tpu"
        Cp = pallas_lse.pad_cost(C)  # per-shard block, padded ONCE
        n_blk, m_blk = C.shape

        def row_lse(g):
            m_l, s_l = pallas_lse.row_lse_partial(
                Cp, g, eps, interpret=interp, valid_rows=n_blk
            )
            m_g = jax.lax.pmax(m_l, INSTANCE_AXIS)
            s_g = jax.lax.psum(s_l * jnp.exp(m_l - m_g), INSTANCE_AXIS)
            return jnp.log(jnp.maximum(s_g, 1e-30)) + m_g

        def col_lse(f):
            m_l, s_l = pallas_lse.col_lse_partial(
                Cp, f, eps, interpret=interp, valid_cols=m_blk
            )
            m_g = jax.lax.pmax(m_l, MODEL_AXIS)
            s_g = jax.lax.psum(s_l * jnp.exp(m_l - m_g), MODEL_AXIS)
            return jnp.log(jnp.maximum(s_g, 1e-30)) + m_g
    else:
        def row_lse(g):
            return _lse((g[None, :] - Cf) / eps, 1, INSTANCE_AXIS)

        def col_lse(f):
            return _lse((f[:, None] - Cf) / eps, 0, MODEL_AXIS)

    def body(carry, _):
        f, g = carry
        f = eps * (log_a - row_lse(g))
        g = jnp.minimum(0.0, eps * (log_b - col_lse(f)))
        return (f, g), None

    def run_iters(f, g, length):
        (f, g), _ = jax.lax.scan(body, (f, g), None, length=length)
        return f, g

    total = jax.lax.psum(jnp.sum(row_mass), MODEL_AXIS)

    def marginal_err(f, g):
        # Mirrors ops.sinkhorn's relative-L1 diagnostic: mean|violation| /
        # mean(mass) == sum|violation| / sum(mass), psum'd so every device
        # sees the identical (replicated) scalar — the while_loop cond
        # below must agree across the mesh.
        row_sum = jnp.exp((f + eps * row_lse(g)) / eps)
        err = jax.lax.psum(jnp.sum(jnp.abs(row_sum - row_mass)), MODEL_AXIS)
        return err / jnp.maximum(total, 1e-30)

    f_init = jnp.zeros_like(log_a)
    g_init = (
        jnp.minimum(0.0, g0.astype(jnp.float32))
        if g0 is not None else jnp.zeros_like(log_b)
    )
    if tol <= 0.0 or chunk <= 0 or iters <= 0:
        f, g = run_iters(f_init, g_init, iters)
        return f, g, marginal_err(f, g), jnp.asarray(iters, jnp.int32)
    # Shared gate driver (probe + chunked while_loop) from ops.sinkhorn —
    # the parity tests compare potentials AND iters_run, so the logic
    # must not fork. g is sharded on the instance axis (and replicated
    # across the model axis), so the probe scalar is pmax'd over
    # INSTANCE_AXIS — every device takes the same cond branch.
    return gated_sinkhorn_loop(
        run_iters, marginal_err, f_init, g_init,
        eps=eps, iters=iters, tol=tol, chunk=chunk,
        dg_reduce=lambda dg: jax.lax.pmax(dg, INSTANCE_AXIS),
    )


def _sharded_auction(scores_full, sizes, copies, cap_full, iters: int,
                     eta: float, load_impl: str = "auto",
                     final_select: str = "exact",
                     stall_tol: float = 0.0, price0=None):
    """scores_full: [n_blk, M] (rows sharded on mdl, full instance width).

    Gumbel perturbation is folded in by the caller (per-shard key) so the
    dynamics match ops.auction.auction; instance loads are psum'd over the
    model axis so every device applies identical price updates.
    """
    num_instances = cap_full.shape[0]
    cap = jnp.maximum(cap_full, 1e-6)
    copies = jnp.minimum(copies, MAX_COPIES)

    kc = min(K_CAND, num_instances)
    load_impl = resolve_load_impl(load_impl)

    def implied_load(idx, valid):
        local = _implied_load(idx, valid, sizes, num_instances, load_impl)
        return jax.lax.psum(local, MODEL_AXIS)

    # Best-ASSIGNMENT tracking + round-based re-shortlisting — must mirror
    # ops.auction.auction (shared helpers; `load`/overflow are psum'd over
    # the model axis so every device tracks identical best/price state and
    # takes the same where() branches).
    n_blk = scores_full.shape[0]

    def narrow_round(carry, length):
        price, best_price, best_idx, best_valid, best_load, best_of = carry
        cand_vals, cand_idx = shortlist(scores_full, price, kc)

        def body(carry, _):
            price, bp, bi, bv, bl, bo = carry
            idx, valid = select_from_candidates(
                cand_vals, cand_idx, copies, price
            )
            load = implied_load(idx, valid)
            of = jnp.sum(jnp.maximum(load - cap, 0.0))
            better = of < bo
            # Best-iterate SELECTION prices — the warm-start carry, same
            # as ops.auction (last-iterate prices are mid-cobweb).
            bp = jnp.where(better, price, bp)
            bi = jnp.where(better, idx, bi)
            bv = jnp.where(better, valid, bv)
            bl = jnp.where(better, load, bl)
            bo = jnp.minimum(of, bo)
            return (
                price_step(load, cap, price, eta), bp, bi, bv, bl, bo,
            ), None

        carry, _ = jax.lax.scan(body, carry, None, length=length)
        return carry

    p_init = (
        jnp.maximum(price0.astype(jnp.float32), 0.0)
        if price0 is not None
        else jnp.zeros((num_instances,), jnp.float32)
    )

    def epilogue(carry, iters_run):
        price, best_price, best_idx, best_valid, best_load, best_of = carry
        if final_select == "none":
            return (best_idx, best_valid, best_load, best_price, best_of,
                    iters_run)
        idx_l, valid_l = final_candidate(
            scores_full - price[None, :], copies, final_select
        )
        load_l = implied_load(idx_l, valid_l)
        of_l = jnp.sum(jnp.maximum(load_l - cap, 0.0))
        use_last = of_l <= best_of
        idx = jnp.where(use_last, idx_l, best_idx)
        valid = jnp.where(use_last, valid_l, best_valid)
        # Winner's load rides the carry (saves a recompute AND its psum).
        load = jnp.where(use_last, load_l, best_load)
        overflow = jnp.minimum(of_l, best_of)
        return (idx, valid, load, jnp.where(use_last, price, best_price),
                overflow, iters_run)

    # Cold carry (price, best_price, best_idx, best_valid, best_load,
    # best_of) — one definition for every branch, matching ops.auction,
    # so a future layout change cannot desync them.
    carry = (
        p_init,
        p_init,
        jnp.zeros((n_blk, MAX_COPIES), jnp.int32),
        jnp.zeros((n_blk, MAX_COPIES), bool),
        jnp.zeros((num_instances,), jnp.float32),
        jnp.asarray(jnp.inf, jnp.float32),
    )
    if stall_tol <= 0.0:
        for length in [RESHORTLIST_EVERY] * (iters // RESHORTLIST_EVERY) + (
            [iters % RESHORTLIST_EVERY] if iters % RESHORTLIST_EVERY else []
        ):
            carry = narrow_round(carry, length)
        return epilogue(carry, jnp.asarray(iters, jnp.int32))

    total_demand = jax.lax.psum(
        jnp.sum(sizes * copies.astype(jnp.float32)), MODEL_AXIS
    )
    if final_select == "none":
        # Mirror ops.auction: "none" avoids full-width selections, and
        # the warm probe is one — gate the rounds only.
        carry, iters_run = _stall_gated_rounds(
            narrow_round, carry, iters, stall_tol, total_demand,
        )
        return epilogue(carry, iters_run)

    # Shared warm probe (ops.auction.warm_probe — the gate arithmetic
    # must not fork between the solvers). implied_load psums over the
    # model axis, so every probe scalar is replicated and all devices
    # take the same cond branch.
    idx_p, valid_p, load_p, of_p, p_probe, probe_ok = warm_probe(
        lambda p: final_candidate(
            scores_full - p[None, :], copies, final_select
        ),
        p_init, cap,
        implied_load, eta, stall_tol, total_demand,
    )

    def _probe_exit(_):
        return (idx_p, valid_p, load_p, p_probe, of_p,
                jnp.asarray(1, jnp.int32))

    def _rounds(_):
        seeded = (p_probe, p_init, idx_p, valid_p, load_p, of_p)
        carry, iters_run = _stall_gated_rounds(
            narrow_round, seeded, iters, stall_tol, total_demand,
        )
        return epilogue(carry, iters_run + 1)

    return jax.lax.cond(probe_ok, _probe_exit, _rounds, None)


def _sparse_solve_kernel(
    p: PlacementProblem, seed: jax.Array, g0: jax.Array, price0: jax.Array,
    config: SolveConfig, weights: CostWeights,
):
    """Sparse top-K pipeline on the mesh (ops/sparse.py kernels).

    Rows stay sharded on ``mdl``; the per-shard cost block is
    all-gathered to full instance width (a no-op on the default
    1-column mesh) so the top-K gather sees whole rows with GLOBAL
    column ids — the same ids, costs, and positional noise the
    single-device gather sees for those rows, so the candidate sets are
    identical. Column reductions (the sparse Sinkhorn's ``u @ P``
    product, the auction's implied load, the gate scalars) psum over the
    model axis, after which every device holds replicated full-width
    column state and takes identical gate branches; the g/price outputs
    are sliced back to this shard's ``inst`` block to ride the same
    output specs as the dense kernel.
    """
    from modelmesh_tpu.ops.sparse import (
        perturb_gathered,
        sparse_auction,
        sparse_sinkhorn,
        topk_candidates,
    )

    C_full = jax.lax.all_gather(
        _cost_block(p, weights, config.dtype), INSTANCE_AXIS, axis=1,
        tiled=True,
    )  # [n_blk, M]
    feas_full = jax.lax.all_gather(
        p.feasible, INSTANCE_AXIS, axis=1, tiled=True
    )
    n_blk = C_full.shape[0]
    row_off = (jax.lax.axis_index(MODEL_AXIS) * n_blk).astype(jnp.uint32)
    cost_k, idx_k, feas_k, mask = topk_candidates(
        C_full, feas_full, config.topk, seed=seed, row_offset=row_off
    )
    copies = jnp.minimum(p.copies, MAX_COPIES)
    row_mass = p.sizes * copies.astype(jnp.float32)
    free = jnp.maximum(p.capacity - p.reserved, 0.0)
    free_full = jax.lax.all_gather(free, INSTANCE_AXIS, axis=0, tiled=True)
    g0_full = jax.lax.all_gather(g0, INSTANCE_AXIS, axis=0, tiled=True)
    price0_full = jax.lax.all_gather(
        price0, INSTANCE_AXIS, axis=0, tiled=True
    )
    col_psum = lambda x: jax.lax.psum(x, MODEL_AXIS)  # noqa: E731
    sk = sparse_sinkhorn(
        C_full, mask, row_mass, free_full,
        eps=config.eps, iters=config.sinkhorn_iters, g0=g0_full,
        tol=config.sinkhorn_tol, chunk=config.sinkhorn_chunk,
        col_psum=col_psum,
        dg_reduce=lambda dg: jax.lax.pmax(dg, MODEL_AXIS),
    )
    logits_k = (
        (sk.f[:, None] + sk.g[idx_k] - cost_k.astype(jnp.float32))
        / config.eps
    ).astype(config.dtype)
    scores_k = perturb_gathered(
        logits_k, idx_k, feas_k, config.tau, seed, row_offset=row_off
    )
    idx, valid, load, price, overflow, au_iters = sparse_auction(
        scores_k, idx_k, p.sizes, copies, free_full,
        iters=config.auction_iters, eta=config.eta,
        load_impl=config.load_impl, final_select=config.final_select,
        stall_tol=config.auction_stall_tol, price0=price0_full,
        sel_k=config.sel_width or MAX_COPIES, axis_psum=col_psum,
    )
    # g and prices are full-width and identical on every device; slice
    # this shard's block so the outputs ride the ``inst``-sharded specs.
    m_blk = free.shape[0]
    blk = jax.lax.axis_index(INSTANCE_AXIS) * m_blk
    return Placement(
        indices=idx, valid=valid, load=load, overflow=overflow,
        row_err=sk.row_err, f=sk.f,
        g=jax.lax.dynamic_slice_in_dim(sk.g, blk, m_blk),
        prices=jax.lax.dynamic_slice_in_dim(price, blk, m_blk),
        sinkhorn_iters_run=sk.iters_run, auction_iters_run=au_iters,
    )


def _solve_kernel(
    p: PlacementProblem, seed: jax.Array, g0: jax.Array, price0: jax.Array,
    config: SolveConfig, weights: CostWeights, n_inst: int = 1,
):
    # Same gate as solve_placement's ``topk < num_instances``: a K that
    # covers the full (global, not per-shard) padded width runs the
    # dense kernel, so the identical config takes the identical path on
    # and off the mesh — the two pipelines only agree to float rounding,
    # and path divergence would fork placements between a leader with a
    # mesh and a single-device solve of the same snapshot.
    if 0 < config.topk < p.capacity.shape[0] * n_inst:
        from modelmesh_tpu.ops.sparse import check_sparse_config

        # Trace-time, like solve_sparse: the sparse-only constraints
        # (hash noise, sel_width) apply only when this branch is taken —
        # a full-width topk legitimately runs dense, where e.g. threefry
        # noise is fine, exactly as ops.solve_placement accepts it.
        check_sparse_config(config)
        return _sparse_solve_kernel(p, seed, g0, price0, config, weights)
    C = _cost_block(p, weights, config.dtype)
    copies = jnp.minimum(p.copies, MAX_COPIES)
    row_mass = p.sizes * copies.astype(jnp.float32)
    free = jnp.maximum(p.capacity - p.reserved, 0.0)
    f, g, row_err, sk_iters = _sharded_sinkhorn(
        C, row_mass, free, config.eps, config.sinkhorn_iters,
        lse_impl=resolve_lse_impl(config.lse_impl), g0=g0,
        tol=config.sinkhorn_tol, chunk=config.sinkhorn_chunk,
    )
    # Quantize to the cost dtype exactly like ops.sinkhorn.plan_logits does,
    # so single-device and sharded rounding see identical scores.
    logits = (
        (f[:, None] + g[None, :] - C.astype(jnp.float32)) / config.eps
    ).astype(config.dtype)
    logits = jnp.where(p.feasible, logits.astype(jnp.float32), _NEG_INF)
    # Full-width rows for top-k (no-op when inst mesh axis is 1).
    logits_full = jax.lax.all_gather(logits, INSTANCE_AXIS, axis=1, tiled=True)
    if config.tau > 0:
        # Gumbel perturbation de-herds identical rows (see ops.auction:
        # top-k of logits + Gumbel samples ~ the soft plan). "hash" offsets
        # the counter by the shard's global row start, so the draw equals
        # the single-device one bit-for-bit; threefry folds the shard index
        # into the key instead (distinct but not offset-consistent).
        if config.noise_impl == "hash":
            row_off = (
                jax.lax.axis_index(MODEL_AXIS) * logits_full.shape[0]
            ).astype(jnp.uint32)
            noise = config.tau * hash_gumbel(
                logits_full.shape, seed, row_off
            )
        else:
            key = jax.random.fold_in(
                jax.random.PRNGKey(seed), jax.lax.axis_index(MODEL_AXIS)
            )
            noise = config.tau * jax.random.gumbel(key, logits_full.shape)
        logits_full = jnp.where(
            logits_full > _NEG_INF / 2, logits_full + noise, logits_full
        )
    free_full = jax.lax.all_gather(free, INSTANCE_AXIS, axis=0, tiled=True)
    price0_full = jax.lax.all_gather(price0, INSTANCE_AXIS, axis=0, tiled=True)
    idx, valid, load, price, overflow, au_iters = _sharded_auction(
        logits_full, p.sizes, copies, free_full, config.auction_iters,
        config.eta, load_impl=config.load_impl,
        final_select=config.final_select,
        stall_tol=config.auction_stall_tol, price0=price0_full,
    )
    # Prices are full-width and identical on every device; slice this
    # shard's block so the output can ride the ``inst``-sharded spec like g.
    m_blk = free.shape[0]
    blk = jax.lax.axis_index(INSTANCE_AXIS) * m_blk
    price_blk = jax.lax.dynamic_slice_in_dim(price, blk, m_blk)
    return Placement(
        indices=idx, valid=valid, load=load, overflow=overflow,
        row_err=row_err, f=f, g=g, prices=price_blk,
        sinkhorn_iters_run=sk_iters, auction_iters_run=au_iters,
    )


def make_sharded_solver(
    mesh: Mesh,
    config: SolveConfig = SolveConfig(),
    weights: CostWeights = CostWeights(),
):
    # lse_impl: "auto" resolves at trace time inside the kernel (pallas on
    # TPU backends, XLA elsewhere) exactly like the single-device path.
    """Build a jitted sharded solver bound to ``mesh``.

    Raises the same ValueErrors as the single-device ``auction`` for
    invalid rounding knobs (noise_impl / final_select / iters).

    The returned callable is ``solver(problem, seed=...)`` — seed is traced,
    so varying it per solve never recompiles. The problem's model-axis
    length must be divisible by the ``mdl`` mesh axis and instance-axis
    length by ``inst``; outputs: indices/valid sharded on ``mdl``, load
    replicated.
    """
    # Rounding knobs are route-independent; sparse-only constraints are
    # validated at trace time inside the kernel's sparse branch, because
    # the route depends on the PROBLEM width (topk < global padded
    # count) which build time cannot know.
    check_rounding_config(
        config.noise_impl, config.final_select, config.auction_iters
    )
    col = P(INSTANCE_AXIS)
    in_specs = (mesh_mod.problem_pspec(), P(), col, col)
    row = P(MODEL_AXIS)
    out_specs = Placement(
        indices=row, valid=row, load=P(), overflow=P(), row_err=P(),
        f=row, g=col, prices=col,
        sinkhorn_iters_run=P(), auction_iters_run=P(),
    )
    kernel = partial(_solve_kernel, config=config, weights=weights,
                     n_inst=mesh.shape[INSTANCE_AXIS])
    shmapped = mesh_mod.shard_map(
        lambda prob, seed, g0, price0: kernel(prob, seed, g0, price0),
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    jitted = jax.jit(shmapped)

    def solver(problem: PlacementProblem, seed=0x5EED, g0=None, price0=None):
        if g0 is None:
            g0 = jnp.zeros(problem.capacity.shape, jnp.float32)
        if price0 is None:
            price0 = jnp.zeros(problem.capacity.shape, jnp.float32)
        return jitted(problem, jnp.asarray(seed, jnp.uint32), g0, price0)

    return solver


def shard_problem(problem: PlacementProblem, mesh: Mesh) -> PlacementProblem:
    """device_put a host problem with the solver's input shardings."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, s),
        problem,
        mesh_mod.problem_shardings(mesh),
    )
