"""Ring attention: sequence-parallel exact attention over a device mesh.

Long-context serving support for the model zoo (models/families.py
transformer) and a first-class demonstration of the sequence-parallel
pattern: the sequence axis is sharded across devices, each device holds
one Q/K/V block, and K/V blocks rotate around the ring via
``lax.ppermute`` while each device accumulates its Q block's attention
with online (flash-style) softmax statistics. Exact — not an
approximation: after P-1 rotations every Q block has attended to every
K/V block, with numerics matching single-device attention up to
reassociation of the softmax accumulation.

The pattern is the standard TPU recipe (shard_map + collective-permute
riding ICI; compute overlaps the permute because each step's matmuls are
independent of the in-flight transfer). No reference counterpart — the
reference has no model compute at all; this exists because long-context
model serving is a first-class target for the TPU framework.

Layout: [batch, heads, seq, head_dim] with seq sharded on the mesh axis.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from modelmesh_tpu.parallel import mesh as mesh_helpers

SEQ_AXIS = "seq"

_NEG_INF = -1.0e30


def _block_stats(q, k, v, mask):
    """One block's attention partials: (m, l, o) online-softmax stats.

    q: [B, H, Sq, D], k/v: [B, H, Sk, D], mask: [Sq, Sk] additive.
    Scores accumulate in f32 regardless of input dtype.
    """
    d = q.shape[-1]
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * (1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32)))
    s = s + mask[None, None, :, :]
    m = jnp.max(s, axis=-1)                       # [B, H, Sq]
    # A fully-masked row (causal: no keys visible yet) has m = -inf;
    # shift by 0 there so exp() produces zeros, not NaNs.
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(s - m_safe[..., None])
    l = jnp.sum(p, axis=-1)                       # [B, H, Sq]
    # P@V in the value dtype (bf16 for the model families — same as the
    # dense attention path, and the MXU-native mode), accumulated in f32.
    # f32 callers are unchanged.
    o = jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return m_safe, l, o


def _merge(acc, blk):
    """Combine two online-softmax partials (the flash-attention merge)."""
    m_a, l_a, o_a = acc
    m_b, l_b, o_b = blk
    m = jnp.maximum(m_a, m_b)
    ca = jnp.exp(m_a - m)
    cb = jnp.exp(m_b - m)
    return m, l_a * ca + l_b * cb, o_a * ca[..., None] + o_b * cb[..., None]


def _ring_body(q, k, v, *, n_dev: int, block: int, causal: bool,
               axis_name: str):
    """Per-device shard_map body: rotate K/V around the ring, accumulate."""
    my = jax.lax.axis_index(axis_name)
    q_pos = my * block + jnp.arange(block)        # global Q positions

    def mask_for(src):
        if not causal:
            return jnp.zeros((block, block), jnp.float32)
        k_pos = src * block + jnp.arange(block)
        return jnp.where(
            q_pos[:, None] >= k_pos[None, :], 0.0, _NEG_INF
        ).astype(jnp.float32)

    # Step 0: local block.
    acc = _block_stats(q, k, v, mask_for(my))
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    for step in range(1, n_dev):
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        src = (my - step) % n_dev
        acc = _merge(acc, _block_stats(q, k, v, mask_for(src)))
    m, l, o = acc
    # Fully-masked rows (l == 0) can only exist for non-causal callers
    # with degenerate masks; guard the divide anyway.
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def make_ring_attention(mesh: Mesh, seq_len: int, *, causal: bool = True,
                        axis_name: str = SEQ_AXIS):
    """Build a jitted sequence-parallel attention for ``mesh``.

    Returns ``fn(q, k, v) -> out`` over [B, H, S, D] arrays with S
    sharded on ``axis_name`` (the function applies the shardings itself
    via shard_map; pass host or device arrays). ``seq_len`` must divide
    evenly by the mesh axis.
    """
    n_dev = mesh.shape[axis_name]
    if seq_len % n_dev:
        raise ValueError(f"seq_len {seq_len} not divisible by {n_dev}")
    block = seq_len // n_dev
    spec = P(None, None, axis_name, None)
    body = partial(
        _ring_body, n_dev=n_dev, block=block, causal=causal,
        axis_name=axis_name,
    )
    shmapped = mesh_helpers.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    jitted = jax.jit(shmapped)

    def fn(q, k, v):
        # Fail at the boundary, not with a broadcast error deep inside
        # the shard_map body: the causal mask is sized for seq_len.
        if q.shape[2] != seq_len:
            raise ValueError(
                f"built for seq_len={seq_len}, got {q.shape[2]}"
            )
        return jitted(q, k, v)

    return fn


def make_seq_mesh(devices=None, axis_name: str = SEQ_AXIS) -> Mesh:
    """1-D sequence-parallel mesh over the visible devices."""
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def reference_attention(q, k, v, causal: bool = True):
    """Single-device full attention (the parity oracle)."""
    s_len = q.shape[2]
    if causal:
        pos = jnp.arange(s_len)
        mask = jnp.where(
            pos[:, None] >= pos[None, :], 0.0, _NEG_INF
        ).astype(jnp.float32)
    else:
        mask = jnp.zeros((s_len, s_len), jnp.float32)
    m, l, o = _block_stats(q, k, v, mask)
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
