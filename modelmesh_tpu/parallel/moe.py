"""Expert parallelism: a mixture-of-experts FFN sharded over the mesh.

The canonical TPU EP recipe (GShard/Switch): tokens live data-parallel on
each device, expert weights are SHARDED across the ``exp`` mesh axis, and
two ``all_to_all`` collectives route token slots to the devices owning
their routed experts and back. Everything between the collectives is a
dense bf16 einsum over [experts_local, capacity, d] blocks — MXU-shaped,
no gathers, no dynamic shapes.

Serving context: the model zoo's transformer family uses this as its FFN
when built with ``ep=1`` and multiple devices are visible
(models/families.py), the long-context analog of the ``sp=1`` ring
attention path. No reference counterpart — the reference has no model
compute at all (SURVEY.md §2.6); this exists because MoE serving is a
first-class target for a TPU serving framework.

Top-1 (switch) routing with a per-(source device, expert) capacity:
C = ceil(T_local * capacity_factor / E). Tokens over capacity are
DROPPED (standard switch behavior) — the residual connection in the
transformer block carries them through unchanged. The dense oracle
(``reference_moe``) reproduces the same drops bit-for-bit, so parity
tests are exact up to bf16 reassociation, not approximate.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from modelmesh_tpu.parallel import mesh as mesh_helpers

EXPERT_AXIS = "exp"


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int):
    """Router f32 (small, precision matters for argmax stability), expert
    FFN weights bf16 [E, d, ff] / [E, ff, d]."""
    kg, k1, k2 = jax.random.split(key, 3)
    return {
        "router": jax.random.normal(kg, (d_model, n_experts), jnp.float32)
        * 0.02,
        "w_in": jax.random.normal(k1, (n_experts, d_model, d_ff), jnp.bfloat16)
        / math.sqrt(d_model),
        "w_out": jax.random.normal(k2, (n_experts, d_ff, d_model), jnp.bfloat16)
        / math.sqrt(d_ff),
    }


def _route(x, router, n_experts: int, capacity: int):
    """Top-1 routing with per-expert capacity.

    x: [T, d] -> (dispatch [T, E, C] one-hot, probs [T]) — dispatch[t, e, c]
    is 1 iff token t is slot c of expert e. Tokens beyond capacity drop.
    """
    logits = x.astype(jnp.float32) @ router          # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)              # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]  # [T]
    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)  # [T, E]
    # Slot index of each token within its expert = exclusive running count.
    pos = jnp.cumsum(onehot, axis=0) - onehot        # [T, E]
    slot = jnp.sum(pos * onehot, axis=1).astype(jnp.int32)  # [T]
    keep = slot < capacity
    dispatch = (
        onehot[:, :, None]
        * jax.nn.one_hot(slot, capacity, dtype=jnp.float32)[:, None, :]
        * keep[:, None, None]
    )                                                # [T, E, C]
    return dispatch, gate


def _expert_ffn(blocks, w_in, w_out):
    """blocks: [E_local, S, d] -> gelu(x @ w_in) @ w_out per local expert,
    bf16 matmuls with f32 accumulation (MXU-native)."""
    h = jnp.einsum(
        "esd,edf->esf", blocks.astype(jnp.bfloat16), w_in,
        preferred_element_type=jnp.float32,
    )
    h = jax.nn.gelu(h).astype(jnp.bfloat16)
    return jnp.einsum(
        "esf,efd->esd", h, w_out, preferred_element_type=jnp.float32,
    )


def make_expert_parallel_ffn(
    mesh: Mesh,
    n_experts: int,
    capacity_factor: float = 1.25,
    axis_name: str = EXPERT_AXIS,
):
    """Build ``fn(params, x) -> y`` over [T, d] with experts sharded on
    ``axis_name``. T and n_experts must divide by the mesh axis size.
    """
    n_dev = mesh.shape[axis_name]
    if n_experts % n_dev:
        raise ValueError(f"{n_experts} experts not divisible by {n_dev}")
    e_local = n_experts // n_dev

    def body(params, x):
        # x: [T_local, d] token-sharded; router replicated; w_in/w_out are
        # the LOCAL [E_local, ...] expert shards (see in_specs).
        t_local = x.shape[0]
        capacity = max(1, math.ceil(t_local * capacity_factor / n_experts))
        dispatch, gate = _route(x, params["router"], n_experts, capacity)
        # Dispatch into [E, C, d] slots, then exchange: group the expert
        # axis as [owner device, local expert] and all_to_all so each
        # device receives, from every peer, the slots for ITS experts.
        # (Global expert id e = owner * E_local + k everywhere below.)
        slots = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
        slots = slots.reshape(n_dev, e_local, capacity, -1)
        slots = jax.lax.all_to_all(
            slots, axis_name, split_axis=0, concat_axis=0, tiled=False
        )                                # [source shard, E_local, C, d]
        blocks = slots.transpose(1, 0, 2, 3).reshape(
            e_local, n_dev * capacity, -1
        )                                # [E_local, all source slots, d]
        out_blocks = _expert_ffn(blocks, params["w_in"], params["w_out"])
        back = out_blocks.reshape(e_local, n_dev, capacity, -1).transpose(
            1, 0, 2, 3
        )                                # [source shard, E_local, C, d]
        back = jax.lax.all_to_all(
            back, axis_name, split_axis=0, concat_axis=0, tiled=False
        )                                # [owner, E_local, C, d] (ours)
        back = back.reshape(n_experts, capacity, -1)
        y = jnp.einsum("tec,ecd->td", dispatch, back)
        return (y * gate[:, None]).astype(x.dtype)

    shmapped = mesh_helpers.shard_map(
        body,
        mesh=mesh,
        # Expert weights genuinely SHARDED over the axis (the memory point
        # of EP: each device holds E/n_dev experts); router replicated.
        in_specs=(
            {
                "router": P(),
                "w_in": P(axis_name),
                "w_out": P(axis_name),
            },
            P(axis_name, None),
        ),
        out_specs=P(axis_name, None),
        check_vma=False,
    )

    jitted = jax.jit(shmapped)

    def fn(params, x):
        if x.shape[0] % n_dev:
            raise ValueError(
                f"token count {x.shape[0]} not divisible by {n_dev} devices"
            )
        return jitted(params, x)

    return fn


def reference_moe(params, x, n_experts: int, capacity_factor: float = 1.25,
                  n_dev: int = 1):
    """Single-device oracle with the SAME routing, capacity, and drop
    semantics as the sharded path on an ``n_dev`` mesh (capacity is
    per-source-shard there, so the oracle routes each token shard
    independently). Exact parity up to bf16 reassociation."""
    shards = jnp.split(x, n_dev, axis=0)
    outs = []
    for xs in shards:
        t_local = xs.shape[0]
        capacity = max(1, math.ceil(t_local * capacity_factor / n_experts))
        dispatch, gate = _route(xs, params["router"], n_experts, capacity)
        slots = jnp.einsum("tec,td->ecd", dispatch, xs.astype(jnp.float32))
        out_blocks = _expert_ffn(slots, params["w_in"], params["w_out"])
        y = jnp.einsum("tec,ecd->td", dispatch, out_blocks)
        outs.append((y * gate[:, None]).astype(xs.dtype))
    return jnp.concatenate(outs, axis=0)


def make_expert_mesh(devices=None, axis_name: str = EXPERT_AXIS) -> Mesh:
    """1-D expert-parallel mesh over the visible devices."""
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))
