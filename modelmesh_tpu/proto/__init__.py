"""Generated protobuf modules (protoc --python_out; see protos/*.proto).

gRPC stubs/servicers are hand-built in modelmesh_tpu.runtime.grpc_defs —
the image has no grpc_tools plugin, and the generic method-map approach
doubles as the raw-bytes passthrough machinery the data plane needs anyway.
"""

from modelmesh_tpu.proto import mesh_api_pb2, mesh_internal_pb2, mesh_runtime_pb2

__all__ = ["mesh_api_pb2", "mesh_internal_pb2", "mesh_runtime_pb2"]
